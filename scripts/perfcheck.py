#!/usr/bin/env python
"""Performance-trajectory gate (scripts/perfcheck.py).

Compares a FRESH bench/soak summary JSON against the checked-in
trajectory files (BENCH_r0*.json / SOAK_r01.json) with per-metric
tolerance bands and emits one machine-readable verdict document —
CI's answer to "did this change quietly regress the numbers the
repo's README/PERF.md advertise?".

Two comparison kinds:

  bench — numeric bands.  Throughput metrics are FLOORS (fresh must
      stay within `rel` below baseline), latency metrics are CEILINGS.
      Comparisons are only meaningful at matching scale, so the gate
      first checks the shape fields (n_evals / placements_per_eval /
      workers) and fails with `incomparable` when they differ (override
      with --allow-scale-mismatch for cross-shape exploration).
      Absolute gates (sampler overhead budget, attribution floor, zero
      SLO breaches) apply to the fresh doc alone, baseline-free.
  soak — the seeded virtual-time soak is deterministic BY CONTRACT
      (same seed, same bytes), so same-profile runs compare exactly:
      fingerprints, digests, eval counts, breach counts.  Wall-clock
      fields are informational (they measure the host, not the code).

Usage:
    python scripts/perfcheck.py --kind bench --fresh out.json
    python scripts/perfcheck.py --kind soak --fresh SOAK_ci.json \
        --baseline SOAK_r01.json
    python scripts/perfcheck.py --band value=0.25 --fresh out.json
    python scripts/perfcheck.py --self-check        # CI wiring test

Exit codes: 0 pass, 1 fail, 2 usage/shape error.  The verdict JSON
(stdout, or --json PATH) carries one row per metric with
status ok | fail | skip and the band that was applied.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (direction, rel_tol, abs_slack) per bench metric.
#   min:  fresh >= baseline * (1 - rel) - abs      (throughput floor)
#   max:  fresh <= baseline * (1 + rel) + abs      (latency ceiling)
#   exact: fresh == baseline
# rel tolerances are deliberately wide: CI hosts differ from the bench
# host; the gate exists to catch step regressions (2x slowdowns,
# latency blowups), not single-digit noise.
BENCH_BANDS: Dict[str, Tuple[str, float, float]] = {
    "value": ("min", 0.40, 0.0),
    "sustained_evals_per_sec": ("min", 0.40, 0.0),
    "placements_per_sec": ("min", 0.40, 0.0),
    "sustained_placements_per_sec": ("min", 0.40, 0.0),
    "single_eval_placements_per_sec": ("min", 0.40, 0.0),
    "networked_evals_per_s": ("min", 0.50, 0.0),
    "p99_plan_queue_ms": ("max", 1.00, 1.0),
    "p50_plan_queue_ms": ("max", 1.00, 1.0),
    "plan_refute_rate": ("max", 0.0, 0.05),
    "resident_chain_hit_rate": ("min", 0.0, 0.10),
    "h2d_bytes_per_wave": ("max", 1.00, 4096.0),
    "quality_nodes_used_tpu": ("max", 0.25, 2.0),
    "quality_zone_balance_max_over_min": ("max", 0.25, 0.10),
    "sampler_overhead_fraction": ("max", 0.0, 0.02),
    "timeline_overhead_fraction": ("max", 0.0, 0.02),
}

# baseline-free gates on the fresh doc: (op, threshold); checked only
# when the field is present (older docs predate the profiling plane)
BENCH_ABS_GATES: Dict[str, Tuple[str, float]] = {
    "slo_breaches": ("==", 0),
    "plan_refute_rate": ("<=", 0.25),
    # profiling-plane acceptance: sampler within budget, >= 90% of
    # sampled wall time attributed to a named bucket
    "sampler_overhead_fraction": ("<=", 0.02),
    "profile_attributed_fraction": (">=", 0.90),
    # timeline-plane acceptance (core/timeline.py): per-tick sampling
    # plus annotation routing stay within the same observability budget
    # as the host profiler
    "timeline_overhead_fraction": ("<=", 0.02),
}

# bench comparisons only make sense at one workload shape
BENCH_SCALE_KEYS = ("n_evals", "placements_per_eval", "workers")

# multi-process worker scaling (core/workerpool.py): with 2+ process
# workers the sustained rate must beat the 1-worker leg of the same
# doc's A/B pair by this factor.  Only meaningful where there are
# cores to scale onto, so the gate SKIPS (does not pass vacuously,
# does not fail) on one-core hosts and in thread mode — thread-mode
# docs are judged by the ordinary r05 bands above instead.
MIN_PROCESS_SCALING = 1.7


def check_worker_scaling(fresh: Dict) -> Dict:
    row: Dict = {"metric": "worker_scaling",
                 "gate": f">= {MIN_PROCESS_SCALING}x 1-worker sustained"}
    by_w = fresh.get("sustained_evals_per_s_by_workers")
    if not isinstance(by_w, dict):
        row["status"] = "skip"
        row["reason"] = "no sustained_evals_per_s_by_workers in doc"
        return row
    multi = sorted(int(k) for k in by_w
                   if str(k).isdigit() and int(k) >= 2)
    if "1" not in by_w or not multi:
        row["status"] = "skip"
        row["reason"] = "doc lacks the (1, N>=2) A/B pair " \
                        "(run bench --workers 2)"
        return row
    if fresh.get("worker_mode") != "process":
        row["status"] = "skip"
        row["reason"] = "thread mode: host phases serialize on the " \
                        "GIL; the scaling gate is process-mode only"
        return row
    cpus = _num(fresh.get("host_cores")) or 0
    if cpus < 2:
        row["status"] = "skip"
        row["reason"] = f"host has {int(cpus)} core(s): no second " \
                        "core to scale onto (gate runs on multi-core " \
                        "CI hosts)"
        return row
    n = multi[-1]
    one, many = _num(by_w["1"]), _num(by_w[str(n)])
    if not one or many is None:
        row["status"] = "skip"
        row["reason"] = "non-numeric A/B entries"
        return row
    row.update(workers=n, one_worker=one, multi_worker=many,
               ratio=round(many / one, 3),
               limit=round(MIN_PROCESS_SCALING * one, 3))
    row["status"] = "ok" if many >= MIN_PROCESS_SCALING * one else "fail"
    return row

# read-path fanout (core/fanout.py): baseline-free gates on a fresh
# `bench --watchers` doc.  Correctness gates are exact (a stale wake
# or an un-parked round is a bug at ANY scale); the throughput ratio
# and drop gates are SCALE-AWARE — see the check functions below.
WATCHERS_ABS_GATES: Dict[str, Tuple[str, float]] = {
    "stale_reads": ("==", 0),
    "armed_shortfall": ("==", 0),
}

# fleet sizes up to this are the CI smoke shape (one shape per core
# class); past it the doc is a scale experiment and the host's
# scheduler is part of what's being measured
WATCHERS_SMALL_FLEET = 1000

# parked-fleet vs idle write-throughput floor — the machine-
# independent stand-in for "scheduler throughput must not regress".
# At the CI shape a parked fleet must cost ~nothing (measured 1.01).
# At 10k-watchers-per-core the measured residual is ~0.5 and it is
# NOT the hub (8 result evals for the whole phase; the tax isolates
# to O(subscribers) event delivery + host thread scheduling, PERF.md
# §20) — the floor there is set to catch the failure mode that
# matters: a broadcast-per-write regression collapses the ratio to
# ~0.14, well under 0.35.
WATCHER_RATIO_FLOOR_SMALL = 0.90
WATCHER_RATIO_FLOOR_LARGE = 0.35

# p99 commit-to-wake band scales with fleet size: waking N watchers on
# one core is inherently O(N) GIL-serialized work, so the gate is a
# PER-WATCHER budget, not an absolute ceiling — 2ms of wake-path work
# per watcher (measured: ~0.33ms/watcher at 600, ~1.3ms at 10k; the
# headroom absorbs CI-host noise without masking a step regression,
# which shows up as 10x not 1.5x).  Floor keeps tiny fleets from
# getting a sub-second band that scheduler-commit jitter could trip.
WATCHER_WAKE_MS_PER_WATCHER = 2.0
WATCHER_WAKE_FLOOR_MS = 1000.0

# the coalescing claim itself: result-index evaluations must be
# O(write rounds), never O(watchers) — the hub memoizes one eval per
# commit batch per shape.  Budget of 6/round covers the arm-time eval,
# the wake eval, and re-check churn; a per-waiter-eval regression at
# 600+ watchers overshoots this by two orders of magnitude.
WATCHER_EVALS_PER_ROUND = 6


def check_watcher_wake(fresh: Dict) -> Dict:
    row: Dict = {"metric": "wake_p99_ms",
                 "gate": f"<= max({WATCHER_WAKE_FLOOR_MS:.0f}, "
                         f"{WATCHER_WAKE_MS_PER_WATCHER} * watchers)"}
    total = _num(fresh.get("watchers_total"))
    p99 = _num(fresh.get("wake_p99_ms"))
    if total is None or p99 is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks watchers_total/wake_p99_ms"
        return row
    limit = max(WATCHER_WAKE_FLOOR_MS,
                WATCHER_WAKE_MS_PER_WATCHER * total)
    row.update(fresh=p99, watchers_total=int(total), limit=limit)
    row["status"] = "ok" if p99 <= limit else "fail"
    return row


def check_watcher_ratio(fresh: Dict) -> Dict:
    total = _num(fresh.get("watchers_total"))
    ratio = _num(fresh.get("write_throughput_ratio"))
    row: Dict = {"metric": "write_throughput_ratio", "fresh": ratio}
    if total is None or ratio is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks watchers_total/write_throughput_ratio"
        return row
    floor = (WATCHER_RATIO_FLOOR_SMALL if total <= WATCHERS_SMALL_FLEET
             else WATCHER_RATIO_FLOOR_LARGE)
    row.update(watchers_total=int(total), limit=floor,
               gate=f">= {floor}")
    row["status"] = "ok" if ratio >= floor else "fail"
    return row


def check_watcher_drops(fresh: Dict) -> Dict:
    """Zero drops at the CI shape; at scale a slow consumer falling
    off the ring's trimmed tail is the DESIGN (counted backpressure,
    never publisher blocking) and the in-run delivery assert already
    guarantees liveness — so the large-fleet row is informational."""
    total = _num(fresh.get("watchers_total"))
    dropped = _num(fresh.get("stream_dropped"))
    row: Dict = {"metric": "stream_dropped", "fresh": dropped}
    if total is None or dropped is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks watchers_total/stream_dropped"
        return row
    if total > WATCHERS_SMALL_FLEET:
        row["status"] = "skip"
        row["reason"] = "scale run: drops are accounted backpressure " \
                        "(gated == 0 at the CI shape only)"
        return row
    row["gate"] = "== 0"
    row["status"] = "ok" if dropped == 0 else "fail"
    return row


def check_watcher_coalescing(fresh: Dict) -> Dict:
    row: Dict = {"metric": "hub_evals",
                 "gate": f"<= {WATCHER_EVALS_PER_ROUND} * rounds"}
    rounds = _num(fresh.get("rounds"))
    evals = _num(fresh.get("hub_evals"))
    if rounds is None or evals is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks rounds/hub_evals"
        return row
    limit = WATCHER_EVALS_PER_ROUND * rounds
    row.update(fresh=evals, rounds=int(rounds), limit=limit)
    row["status"] = "ok" if evals <= limit else "fail"
    return row


def compare_watchers(fresh: Dict) -> Dict:
    """--kind watchers: judge a `bench --watchers` doc ALONE (the
    fanout bench carries its own in-doc A/B pair — parked-vs-idle
    write throughput and hub-vs-legacy p99 — so there is no
    cross-run baseline to drift; scale lives in the doc and the wake
    band scales with it)."""
    checks: List[Dict] = [check_watcher_wake(fresh),
                          check_watcher_coalescing(fresh),
                          check_watcher_ratio(fresh),
                          check_watcher_drops(fresh)]
    for metric, gate in sorted(WATCHERS_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "watchers",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            "watchers_total": fresh.get("watchers_total"),
            "write_throughput_ratio":
                fresh.get("write_throughput_ratio"),
            "legacy_http_wake": fresh.get("legacy_http_wake")}


# ---- memory kind (ISSUE 19): judge a soak summary's footprint alone -------
# RSS high-water ceiling when the caller doesn't pass --rss-ceiling-mb
MEMORY_RSS_CEILING_MB_DEFAULT = 4096.0
# ring evictions are counted backpressure by design; the budget scales
# with the soak's virtual horizon (a 4h churn soak legitimately trims)
MEMORY_EVICTIONS_PER_VH = 250_000.0
MEMORY_EVICTIONS_FLOOR = 1_000.0
MEMORY_ABS_GATES: Dict[str, Tuple[str, float]] = {
    # a floor fallback = a replica forced to full resync because the
    # journal evicted past its cursor — compaction must keep this at 0
    "journal_floor_fallbacks": ("==", 0),
    # ledger cost over soak wall time: the 0.1% budget (PERF.md §21)
    "mem_overhead_fraction": ("<=", 0.001),
    # mean scrape cost sanity ceiling (µs)
    "mem_scrape_us": ("<=", 5000.0),
}


def check_memory_rss(fresh: Dict, ceiling_mb: float) -> Dict:
    row: Dict = {"metric": "rss_peak_bytes",
                 "gate": f"<= {ceiling_mb:g} MiB"}
    peak = _num(fresh.get("rss_peak_bytes"))
    if peak is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks rss_peak_bytes"
        return row
    limit = ceiling_mb * 1024.0 * 1024.0
    row.update(fresh=peak, limit=limit,
               fresh_mb=round(peak / (1024.0 * 1024.0), 1))
    row["status"] = "ok" if peak <= limit else "fail"
    return row


def check_memory_evictions(fresh: Dict) -> Dict:
    row: Dict = {"metric": "ring_evictions",
                 "gate": f"<= max({MEMORY_EVICTIONS_FLOOR:g}, "
                         f"{MEMORY_EVICTIONS_PER_VH:g} * "
                         f"virtual_hours)"}
    ev = _num(fresh.get("ring_evictions"))
    vh = _num(fresh.get("soak_virtual_hours"))
    if ev is None or vh is None:
        row["status"] = "skip"
        row["reason"] = "doc lacks ring_evictions/soak_virtual_hours"
        return row
    limit = max(MEMORY_EVICTIONS_FLOOR, MEMORY_EVICTIONS_PER_VH * vh)
    row.update(fresh=ev, limit=limit)
    row["status"] = "ok" if ev <= limit else "fail"
    return row


def compare_memory(fresh: Dict,
                   ceiling_mb: float =
                   MEMORY_RSS_CEILING_MB_DEFAULT) -> Dict:
    """--kind memory: judge a soak summary's footprint fields ALONE
    (baseline-free like workers/watchers — RSS is a host fact, so a
    cross-run band would gate the machine, not the code)."""
    checks: List[Dict] = [check_memory_rss(fresh, ceiling_mb),
                          check_memory_evictions(fresh)]
    for metric, gate in sorted(MEMORY_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "memory",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            "rss_peak_bytes": fresh.get("rss_peak_bytes"),
            "journal_bytes": fresh.get("journal_bytes"),
            "journal_compactions": fresh.get("journal_compactions"),
            "mem_overhead_fraction":
                fresh.get("mem_overhead_fraction")}


# ---- federation kind (ISSUE 20): judge a federation doc alone -------------
FEDERATION_ABS_GATES: Dict[str, Tuple[str, float]] = {
    # leader-side scrape cost over the measurement wall time: the same
    # 0.1% observability budget every other plane answers to
    "federation_overhead_fraction": ("<=", 0.001),
    # one peer snapshot fetch+fold, p99 over the run (ms) — loopback /
    # LAN scale; a slow peer shows up here before it breaches an SLO
    "peer_scrape_p99_ms": ("<=", 50.0),
    # a clean run scrapes every peer every interval; any failure means
    # the harness (or the cluster) is broken, not slow
    "scrape_failures": ("==", 0),
}


def compare_federation(fresh: Dict) -> Dict:
    """--kind federation: judge a federation measurement doc ALONE
    (baseline-free like workers/watchers/memory — scrape cost is a
    host fact; the gates are budgets, not trajectories)."""
    checks: List[Dict] = []
    for metric, gate in sorted(FEDERATION_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "federation",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            "scrapes": fresh.get("scrapes"),
            "peers": fresh.get("peers"),
            "federation_overhead_fraction":
                fresh.get("federation_overhead_fraction"),
            "stitch_ms": fresh.get("stitch_ms")}


# deterministic-by-contract soak fields: exact equality
SOAK_EXACT = ("converged_fingerprint", "trace_digest", "soak_evals",
              "schedule_events", "soak_breaches", "soak_virtual_hours",
              "p99_plan_queue_ms",
              # the canonical timeline dump's digest (core/timeline.py):
              # same seed, same clock-aligned history, byte for byte
              "timeline_digest")

# the fresh soak must be green regardless of what the baseline says
SOAK_ABS_GATES: Dict[str, Tuple[str, float]] = {
    "soak_breaches": ("==", 0),
}


def _load(path: str) -> Dict:
    with open(path) as f:
        doc = json.load(f)
    # BENCH_r0x wrappers carry the parsed summary under "parsed"
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _latest_bench_baseline() -> Optional[str]:
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    return paths[-1] if paths else None


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _check_band(metric: str, base, fresh,
                band: Tuple[str, float, float]) -> Dict:
    direction, rel, slack = band
    row = {"metric": metric, "baseline": base, "fresh": fresh,
           "direction": direction, "rel_tol": rel, "abs_slack": slack}
    if direction == "exact":
        # exact bands also cover string fields (fingerprints, digests)
        if base is None or fresh is None:
            row["status"] = "skip"
            row["reason"] = "missing on one side"
        else:
            row["status"] = "ok" if fresh == base else "fail"
        return row
    b, f = _num(base), _num(fresh)
    if b is None or f is None:
        row["status"] = "skip"
        row["reason"] = "non-numeric or missing on one side"
        return row
    if direction == "min":
        limit = b * (1.0 - rel) - slack
        ok = f >= limit
    else:  # max
        limit = b * (1.0 + rel) + slack
        ok = f <= limit
    row["limit"] = round(limit, 6)
    row["status"] = "ok" if ok else "fail"
    return row


def _check_abs(metric: str, fresh, gate: Tuple[str, float]) -> Dict:
    op, thr = gate
    row = {"metric": metric, "fresh": fresh, "gate": f"{op} {thr}"}
    f = _num(fresh)
    if f is None:
        row["status"] = "skip"
        row["reason"] = "missing from fresh doc"
        return row
    ok = {"<=": f <= thr, ">=": f >= thr, "==": f == thr}[op]
    row["status"] = "ok" if ok else "fail"
    return row


def compare_bench(base: Dict, fresh: Dict,
                  bands: Dict[str, Tuple[str, float, float]],
                  allow_scale_mismatch: bool = False) -> Dict:
    checks: List[Dict] = []
    mismatched = [k for k in BENCH_SCALE_KEYS
                  if k in base and k in fresh
                  and base[k] != fresh[k]]
    if mismatched and not allow_scale_mismatch:
        return {"kind": "bench", "verdict": "incomparable",
                "scale_mismatch": {
                    k: {"baseline": base[k], "fresh": fresh[k]}
                    for k in mismatched},
                "checks": []}
    for metric, band in sorted(bands.items()):
        if metric not in base and metric not in fresh:
            continue
        checks.append(_check_band(
            metric, base.get(metric), fresh.get(metric), band))
    for metric, gate in sorted(BENCH_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    if "sustained_evals_per_s_by_workers" in fresh:
        checks.append(check_worker_scaling(fresh))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "bench",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks}


def compare_workers(fresh: Dict) -> Dict:
    """--kind workers: judge a worker-A/B doc ALONE (no baseline — a
    2-worker doc is deliberately a different shape from the r05
    1-worker trajectory, so the scale-mismatch guard would reject a
    bench-kind comparison).  The scaling band plus the baseline-free
    absolute gates (refute rate, SLO breaches, sampler budget)."""
    checks: List[Dict] = [check_worker_scaling(fresh)]
    for metric, gate in sorted(BENCH_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "workers",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            "worker_mode": fresh.get("worker_mode"),
            "host_cores": fresh.get("host_cores"),
            "sustained_evals_per_s_by_workers":
                fresh.get("sustained_evals_per_s_by_workers")}


def compare_soak(base: Dict, fresh: Dict) -> Dict:
    checks: List[Dict] = []
    for metric in SOAK_EXACT:
        if metric not in base and metric not in fresh:
            continue
        checks.append(_check_band(metric, base.get(metric),
                                  fresh.get(metric),
                                  ("exact", 0.0, 0.0)))
    # list-valued: violations must be empty on BOTH sides
    row = {"metric": "violations",
           "baseline": base.get("violations", []),
           "fresh": fresh.get("violations", [])}
    row["status"] = ("ok" if not fresh.get("violations") else "fail")
    checks.append(row)
    for metric, gate in sorted(SOAK_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "soak",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            # informational: host speed, not code speed
            "wall_s": {"baseline": base.get("wall_s"),
                       "fresh": fresh.get("wall_s")}}


def _parse_band_overrides(items: List[str],
                          bands: Dict) -> Dict:
    out = dict(bands)
    for it in items:
        if "=" not in it:
            raise SystemExit(f"--band wants metric=REL_TOL, got {it!r}")
        metric, tol = it.split("=", 1)
        direction, _, slack = out.get(metric, ("min", 0.0, 0.0))
        out[metric] = (direction, float(tol), slack)
    return out


def self_check() -> int:
    """CI wiring test: each kind must pass against itself and fail
    against an injected regression — proves the comparator would catch
    a real one (the analyze.py --selftest posture)."""
    bench_path = _latest_bench_baseline()
    soak_path = os.path.join(ROOT, "SOAK_r01.json")
    ok = True
    if bench_path:
        base = _load(bench_path)
        v = compare_bench(base, dict(base), BENCH_BANDS)
        print(f"bench self vs self: {v['verdict']} "
              f"({os.path.basename(bench_path)})")
        ok &= v["verdict"] == "pass"
        bad = dict(base)
        bad["value"] = base["value"] * 0.4
        bad["p99_plan_queue_ms"] = \
            base.get("p99_plan_queue_ms", 1.0) * 10 + 10
        v = compare_bench(base, bad, BENCH_BANDS)
        print(f"bench injected regression: {v['verdict']} "
              f"(failed: {v['failed']})")
        ok &= v["verdict"] == "fail" and "value" in v["failed"]
        v = compare_bench(base, {**base, "workers": 99}, BENCH_BANDS)
        print(f"bench scale mismatch: {v['verdict']}")
        ok &= v["verdict"] == "incomparable"
    else:
        print("no BENCH_r*.json baseline — bench self-check skipped")
    if os.path.exists(soak_path):
        base = _load(soak_path)
        v = compare_soak(base, dict(base))
        print(f"soak self vs self: {v['verdict']}")
        ok &= v["verdict"] == "pass"
        bad = dict(base)
        bad["converged_fingerprint"] = "0" * 64
        bad["soak_breaches"] = 3
        v = compare_soak(base, bad)
        print(f"soak injected regression: {v['verdict']} "
              f"(failed: {v['failed']})")
        ok &= (v["verdict"] == "fail"
               and "converged_fingerprint" in v["failed"]
               and "soak_breaches" in v["failed"])
    else:
        print("no SOAK_r01.json baseline — soak self-check skipped")
    # timeline-plane gate wiring: an injected overhead regression (5%
    # against the 2% budget) must fail the absolute gate; a doc within
    # budget must pass; a doc predating the plane must skip
    over = _check_abs("timeline_overhead_fraction", 0.05,
                      BENCH_ABS_GATES["timeline_overhead_fraction"])
    under = _check_abs("timeline_overhead_fraction", 0.004,
                       BENCH_ABS_GATES["timeline_overhead_fraction"])
    absent = _check_abs("timeline_overhead_fraction", None,
                        BENCH_ABS_GATES["timeline_overhead_fraction"])
    print(f"timeline overhead gate: 5%={over['status']} "
          f"0.4%={under['status']} absent={absent['status']}")
    ok &= (over["status"] == "fail" and under["status"] == "ok"
           and absent["status"] == "skip")
    # worker-scaling band wiring: the gate must catch a sub-1.7x
    # process-mode pair, and must SKIP (not fail) thread-mode and
    # one-core docs where the gate is meaningless
    doc = {"worker_mode": "process", "host_cores": 4,
           "sustained_evals_per_s_by_workers": {"1": 10.0, "2": 18.0}}
    scaled = check_worker_scaling(doc)["status"]
    flat = check_worker_scaling(
        {**doc, "sustained_evals_per_s_by_workers":
         {"1": 10.0, "2": 12.0}})["status"]
    threaded = check_worker_scaling(
        {**doc, "worker_mode": "thread"})["status"]
    onecore = check_worker_scaling({**doc, "host_cores": 1})["status"]
    print(f"worker scaling band: 1.8x={scaled} 1.2x={flat} "
          f"thread={threaded} one-core={onecore}")
    ok &= (scaled == "ok" and flat == "fail"
           and threaded == "skip" and onecore == "skip")
    # watchers-kind wiring: a healthy fanout doc must pass; a stale
    # wake, a collapsed throughput ratio, a per-waiter-eval regression
    # and a wake-latency blowup must each fail; a non-watchers doc
    # (every field absent) must come out all-skip, not all-pass
    wdoc = {"watchers_total": 600, "rounds": 3, "wake_p99_ms": 250.0,
            "hub_evals": 7, "stale_reads": 0, "armed_shortfall": 0,
            "stream_dropped": 0, "write_throughput_ratio": 1.02}
    w_ok = compare_watchers(wdoc)
    w_stale = compare_watchers({**wdoc, "stale_reads": 2})
    w_ratio = compare_watchers(
        {**wdoc, "write_throughput_ratio": 0.31})
    w_evals = compare_watchers({**wdoc, "hub_evals": 1800})
    w_slow = compare_watchers({**wdoc, "wake_p99_ms": 9000.0})
    w_drop = compare_watchers({**wdoc, "stream_dropped": 5})
    # scale shape: wake band + ratio floor + drop gate all relax with
    # fleet size, but a broadcast-per-write collapse (~0.14) still fails
    wbig = {**wdoc, "watchers_total": 10000, "wake_p99_ms": 13000.0,
            "write_throughput_ratio": 0.49, "stream_dropped": 34144}
    w_scaled = compare_watchers(wbig)
    w_collapse = compare_watchers(
        {**wbig, "write_throughput_ratio": 0.14})
    w_absent = compare_watchers({"bench": "other"})
    print(f"watchers gates: healthy={w_ok['verdict']} "
          f"stale={w_stale['verdict']} ratio={w_ratio['verdict']} "
          f"evals={w_evals['verdict']} slow={w_slow['verdict']} "
          f"drop={w_drop['verdict']} 10k={w_scaled['verdict']} "
          f"10k-collapse={w_collapse['verdict']} "
          f"absent-skips={len(w_absent['skipped'])}")
    ok &= (w_ok["verdict"] == "pass"
           and w_stale["verdict"] == "fail"
           and "stale_reads" in w_stale["failed"]
           and w_ratio["verdict"] == "fail"
           and "write_throughput_ratio" in w_ratio["failed"]
           and w_evals["verdict"] == "fail"
           and "hub_evals" in w_evals["failed"]
           and w_slow["verdict"] == "fail"
           and "wake_p99_ms" in w_slow["failed"]
           and w_drop["verdict"] == "fail"
           and "stream_dropped" in w_drop["failed"]
           and w_scaled["verdict"] == "pass"
           and "stream_dropped" in w_scaled["skipped"]
           and w_collapse["verdict"] == "fail"
           and "write_throughput_ratio" in w_collapse["failed"]
           and len(w_absent["skipped"]) == len(w_absent["checks"]))
    # memory-kind wiring (ISSUE 19): a healthy footprint doc must
    # pass; an RSS blowout, a journal floor fallback, an eviction
    # storm, and a ledger-overhead regression must each fail; a doc
    # predating the plane must come out all-skip, not all-pass
    mdoc = {"rss_peak_bytes": 300 * 1024 * 1024,
            "soak_virtual_hours": 2.0, "ring_evictions": 120,
            "journal_floor_fallbacks": 0, "journal_bytes": 50_000,
            "mem_overhead_fraction": 0.0004, "mem_scrape_us": 180.0}
    m_ok = compare_memory(mdoc, 512.0)
    m_rss = compare_memory(
        {**mdoc, "rss_peak_bytes": 900 * 1024 * 1024}, 512.0)
    m_floor = compare_memory(
        {**mdoc, "journal_floor_fallbacks": 3}, 512.0)
    m_evict = compare_memory(
        {**mdoc, "ring_evictions": 5_000_000}, 512.0)
    m_over = compare_memory(
        {**mdoc, "mem_overhead_fraction": 0.02}, 512.0)
    m_absent = compare_memory({"bench": "other"}, 512.0)
    print(f"memory gates: healthy={m_ok['verdict']} "
          f"rss={m_rss['verdict']} floor={m_floor['verdict']} "
          f"evict={m_evict['verdict']} overhead={m_over['verdict']} "
          f"absent-skips={len(m_absent['skipped'])}")
    ok &= (m_ok["verdict"] == "pass"
           and m_rss["verdict"] == "fail"
           and "rss_peak_bytes" in m_rss["failed"]
           and m_floor["verdict"] == "fail"
           and "journal_floor_fallbacks" in m_floor["failed"]
           and m_evict["verdict"] == "fail"
           and "ring_evictions" in m_evict["failed"]
           and m_over["verdict"] == "fail"
           and "mem_overhead_fraction" in m_over["failed"]
           and len(m_absent["skipped"]) == len(m_absent["checks"]))
    # federation-kind wiring (ISSUE 20): a healthy scrape doc must
    # pass; an overhead blowout, a slow peer, and a failed scrape must
    # each fail; a doc predating the plane must come out all-skip
    fdoc = {"scrapes": 12, "peers": 3, "scrape_failures": 0,
            "peer_scrape_p99_ms": 4.0,
            "federation_overhead_fraction": 0.0002, "stitch_ms": 6.0}
    f_ok = compare_federation(fdoc)
    f_over = compare_federation(
        {**fdoc, "federation_overhead_fraction": 0.02})
    f_slow = compare_federation({**fdoc, "peer_scrape_p99_ms": 400.0})
    f_fail = compare_federation({**fdoc, "scrape_failures": 2})
    f_absent = compare_federation({"bench": "other"})
    print(f"federation gates: healthy={f_ok['verdict']} "
          f"overhead={f_over['verdict']} slow={f_slow['verdict']} "
          f"failures={f_fail['verdict']} "
          f"absent-skips={len(f_absent['skipped'])}")
    ok &= (f_ok["verdict"] == "pass"
           and f_over["verdict"] == "fail"
           and "federation_overhead_fraction" in f_over["failed"]
           and f_slow["verdict"] == "fail"
           and "peer_scrape_p99_ms" in f_slow["failed"]
           and f_fail["verdict"] == "fail"
           and "scrape_failures" in f_fail["failed"]
           and len(f_absent["skipped"]) == len(f_absent["checks"]))
    print(f"perfcheck self-check: {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="compare fresh bench/soak JSON against the "
                    "checked-in trajectory with tolerance bands")
    ap.add_argument("--kind",
                    choices=("bench", "soak", "workers", "watchers",
                             "memory", "federation"),
                    default="bench",
                    help="workers: judge a --workers N A/B doc alone "
                         "(process-scaling band + absolute gates; no "
                         "baseline needed).  watchers: judge a "
                         "`bench --watchers` fanout doc alone "
                         "(scale-aware wake band, coalescing gate, "
                         "zero-stale-reads + throughput-ratio gates). "
                         "memory: judge a soak summary's footprint "
                         "alone (RSS high-water ceiling, zero journal "
                         "floor fallbacks, eviction budget, ledger "
                         "overhead <= 0.1%).  federation: judge a "
                         "federation scrape doc alone (overhead <= "
                         "0.1%, peer scrape p99 <= 50ms, zero scrape "
                         "failures on a clean run)")
    ap.add_argument("--fresh", help="fresh summary JSON to judge")
    ap.add_argument("--baseline",
                    help="baseline JSON (default: newest BENCH_r*.json"
                         " / SOAK_r01.json)")
    ap.add_argument("--band", action="append", default=[],
                    metavar="METRIC=REL_TOL",
                    help="override a metric's relative tolerance")
    ap.add_argument("--allow-scale-mismatch", action="store_true",
                    help="compare across different workload shapes "
                         "anyway (exploration, not gating)")
    ap.add_argument("--json", default="",
                    help="also write the verdict doc to this path")
    ap.add_argument("--rss-ceiling-mb", type=float,
                    default=MEMORY_RSS_CEILING_MB_DEFAULT,
                    help="--kind memory: RSS high-water ceiling in "
                         "MiB (default %(default)s)")
    ap.add_argument("--self-check", action="store_true",
                    help="validate the comparator against the "
                         "checked-in baselines (CI wiring test)")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.fresh:
        ap.error("--fresh is required (or use --self-check)")
    if args.kind in ("workers", "watchers", "memory", "federation"):
        try:
            fresh = _load(args.fresh)
        except (OSError, ValueError) as e:
            print(f"cannot load inputs: {e}", file=sys.stderr)
            return 2
        if args.kind == "workers":
            verdict = compare_workers(fresh)
        elif args.kind == "watchers":
            verdict = compare_watchers(fresh)
        elif args.kind == "federation":
            verdict = compare_federation(fresh)
        else:
            verdict = compare_memory(fresh, args.rss_ceiling_mb)
        verdict["fresh_path"] = args.fresh
        out = json.dumps(verdict, indent=2, sort_keys=True)
        print(out)
        if args.json:
            with open(args.json, "w") as f:
                f.write(out + "\n")
        return 0 if verdict["verdict"] == "pass" else 1
    baseline = args.baseline
    if not baseline:
        baseline = (_latest_bench_baseline() if args.kind == "bench"
                    else os.path.join(ROOT, "SOAK_r01.json"))
    if not baseline or not os.path.exists(baseline):
        print(f"no baseline found ({baseline!r})", file=sys.stderr)
        return 2
    try:
        base, fresh = _load(baseline), _load(args.fresh)
    except (OSError, ValueError) as e:
        print(f"cannot load inputs: {e}", file=sys.stderr)
        return 2
    if args.kind == "bench":
        bands = _parse_band_overrides(args.band, BENCH_BANDS)
        verdict = compare_bench(base, fresh, bands,
                                args.allow_scale_mismatch)
    else:
        verdict = compare_soak(base, fresh)
    verdict["baseline_path"] = os.path.relpath(baseline, ROOT)
    verdict["fresh_path"] = args.fresh
    out = json.dumps(verdict, indent=2, sort_keys=True)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
