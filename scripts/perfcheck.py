#!/usr/bin/env python
"""Performance-trajectory gate (scripts/perfcheck.py).

Compares a FRESH bench/soak summary JSON against the checked-in
trajectory files (BENCH_r0*.json / SOAK_r01.json) with per-metric
tolerance bands and emits one machine-readable verdict document —
CI's answer to "did this change quietly regress the numbers the
repo's README/PERF.md advertise?".

Two comparison kinds:

  bench — numeric bands.  Throughput metrics are FLOORS (fresh must
      stay within `rel` below baseline), latency metrics are CEILINGS.
      Comparisons are only meaningful at matching scale, so the gate
      first checks the shape fields (n_evals / placements_per_eval /
      workers) and fails with `incomparable` when they differ (override
      with --allow-scale-mismatch for cross-shape exploration).
      Absolute gates (sampler overhead budget, attribution floor, zero
      SLO breaches) apply to the fresh doc alone, baseline-free.
  soak — the seeded virtual-time soak is deterministic BY CONTRACT
      (same seed, same bytes), so same-profile runs compare exactly:
      fingerprints, digests, eval counts, breach counts.  Wall-clock
      fields are informational (they measure the host, not the code).

Usage:
    python scripts/perfcheck.py --kind bench --fresh out.json
    python scripts/perfcheck.py --kind soak --fresh SOAK_ci.json \
        --baseline SOAK_r01.json
    python scripts/perfcheck.py --band value=0.25 --fresh out.json
    python scripts/perfcheck.py --self-check        # CI wiring test

Exit codes: 0 pass, 1 fail, 2 usage/shape error.  The verdict JSON
(stdout, or --json PATH) carries one row per metric with
status ok | fail | skip and the band that was applied.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (direction, rel_tol, abs_slack) per bench metric.
#   min:  fresh >= baseline * (1 - rel) - abs      (throughput floor)
#   max:  fresh <= baseline * (1 + rel) + abs      (latency ceiling)
#   exact: fresh == baseline
# rel tolerances are deliberately wide: CI hosts differ from the bench
# host; the gate exists to catch step regressions (2x slowdowns,
# latency blowups), not single-digit noise.
BENCH_BANDS: Dict[str, Tuple[str, float, float]] = {
    "value": ("min", 0.40, 0.0),
    "sustained_evals_per_sec": ("min", 0.40, 0.0),
    "placements_per_sec": ("min", 0.40, 0.0),
    "sustained_placements_per_sec": ("min", 0.40, 0.0),
    "single_eval_placements_per_sec": ("min", 0.40, 0.0),
    "networked_evals_per_s": ("min", 0.50, 0.0),
    "p99_plan_queue_ms": ("max", 1.00, 1.0),
    "p50_plan_queue_ms": ("max", 1.00, 1.0),
    "plan_refute_rate": ("max", 0.0, 0.05),
    "resident_chain_hit_rate": ("min", 0.0, 0.10),
    "h2d_bytes_per_wave": ("max", 1.00, 4096.0),
    "quality_nodes_used_tpu": ("max", 0.25, 2.0),
    "quality_zone_balance_max_over_min": ("max", 0.25, 0.10),
    "sampler_overhead_fraction": ("max", 0.0, 0.02),
    "timeline_overhead_fraction": ("max", 0.0, 0.02),
}

# baseline-free gates on the fresh doc: (op, threshold); checked only
# when the field is present (older docs predate the profiling plane)
BENCH_ABS_GATES: Dict[str, Tuple[str, float]] = {
    "slo_breaches": ("==", 0),
    "plan_refute_rate": ("<=", 0.25),
    # profiling-plane acceptance: sampler within budget, >= 90% of
    # sampled wall time attributed to a named bucket
    "sampler_overhead_fraction": ("<=", 0.02),
    "profile_attributed_fraction": (">=", 0.90),
    # timeline-plane acceptance (core/timeline.py): per-tick sampling
    # plus annotation routing stay within the same observability budget
    # as the host profiler
    "timeline_overhead_fraction": ("<=", 0.02),
}

# bench comparisons only make sense at one workload shape
BENCH_SCALE_KEYS = ("n_evals", "placements_per_eval", "workers")

# multi-process worker scaling (core/workerpool.py): with 2+ process
# workers the sustained rate must beat the 1-worker leg of the same
# doc's A/B pair by this factor.  Only meaningful where there are
# cores to scale onto, so the gate SKIPS (does not pass vacuously,
# does not fail) on one-core hosts and in thread mode — thread-mode
# docs are judged by the ordinary r05 bands above instead.
MIN_PROCESS_SCALING = 1.7


def check_worker_scaling(fresh: Dict) -> Dict:
    row: Dict = {"metric": "worker_scaling",
                 "gate": f">= {MIN_PROCESS_SCALING}x 1-worker sustained"}
    by_w = fresh.get("sustained_evals_per_s_by_workers")
    if not isinstance(by_w, dict):
        row["status"] = "skip"
        row["reason"] = "no sustained_evals_per_s_by_workers in doc"
        return row
    multi = sorted(int(k) for k in by_w
                   if str(k).isdigit() and int(k) >= 2)
    if "1" not in by_w or not multi:
        row["status"] = "skip"
        row["reason"] = "doc lacks the (1, N>=2) A/B pair " \
                        "(run bench --workers 2)"
        return row
    if fresh.get("worker_mode") != "process":
        row["status"] = "skip"
        row["reason"] = "thread mode: host phases serialize on the " \
                        "GIL; the scaling gate is process-mode only"
        return row
    cpus = _num(fresh.get("host_cores")) or 0
    if cpus < 2:
        row["status"] = "skip"
        row["reason"] = f"host has {int(cpus)} core(s): no second " \
                        "core to scale onto (gate runs on multi-core " \
                        "CI hosts)"
        return row
    n = multi[-1]
    one, many = _num(by_w["1"]), _num(by_w[str(n)])
    if not one or many is None:
        row["status"] = "skip"
        row["reason"] = "non-numeric A/B entries"
        return row
    row.update(workers=n, one_worker=one, multi_worker=many,
               ratio=round(many / one, 3),
               limit=round(MIN_PROCESS_SCALING * one, 3))
    row["status"] = "ok" if many >= MIN_PROCESS_SCALING * one else "fail"
    return row

# deterministic-by-contract soak fields: exact equality
SOAK_EXACT = ("converged_fingerprint", "trace_digest", "soak_evals",
              "schedule_events", "soak_breaches", "soak_virtual_hours",
              "p99_plan_queue_ms",
              # the canonical timeline dump's digest (core/timeline.py):
              # same seed, same clock-aligned history, byte for byte
              "timeline_digest")

# the fresh soak must be green regardless of what the baseline says
SOAK_ABS_GATES: Dict[str, Tuple[str, float]] = {
    "soak_breaches": ("==", 0),
}


def _load(path: str) -> Dict:
    with open(path) as f:
        doc = json.load(f)
    # BENCH_r0x wrappers carry the parsed summary under "parsed"
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def _latest_bench_baseline() -> Optional[str]:
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    return paths[-1] if paths else None


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _check_band(metric: str, base, fresh,
                band: Tuple[str, float, float]) -> Dict:
    direction, rel, slack = band
    row = {"metric": metric, "baseline": base, "fresh": fresh,
           "direction": direction, "rel_tol": rel, "abs_slack": slack}
    if direction == "exact":
        # exact bands also cover string fields (fingerprints, digests)
        if base is None or fresh is None:
            row["status"] = "skip"
            row["reason"] = "missing on one side"
        else:
            row["status"] = "ok" if fresh == base else "fail"
        return row
    b, f = _num(base), _num(fresh)
    if b is None or f is None:
        row["status"] = "skip"
        row["reason"] = "non-numeric or missing on one side"
        return row
    if direction == "min":
        limit = b * (1.0 - rel) - slack
        ok = f >= limit
    else:  # max
        limit = b * (1.0 + rel) + slack
        ok = f <= limit
    row["limit"] = round(limit, 6)
    row["status"] = "ok" if ok else "fail"
    return row


def _check_abs(metric: str, fresh, gate: Tuple[str, float]) -> Dict:
    op, thr = gate
    row = {"metric": metric, "fresh": fresh, "gate": f"{op} {thr}"}
    f = _num(fresh)
    if f is None:
        row["status"] = "skip"
        row["reason"] = "missing from fresh doc"
        return row
    ok = {"<=": f <= thr, ">=": f >= thr, "==": f == thr}[op]
    row["status"] = "ok" if ok else "fail"
    return row


def compare_bench(base: Dict, fresh: Dict,
                  bands: Dict[str, Tuple[str, float, float]],
                  allow_scale_mismatch: bool = False) -> Dict:
    checks: List[Dict] = []
    mismatched = [k for k in BENCH_SCALE_KEYS
                  if k in base and k in fresh
                  and base[k] != fresh[k]]
    if mismatched and not allow_scale_mismatch:
        return {"kind": "bench", "verdict": "incomparable",
                "scale_mismatch": {
                    k: {"baseline": base[k], "fresh": fresh[k]}
                    for k in mismatched},
                "checks": []}
    for metric, band in sorted(bands.items()):
        if metric not in base and metric not in fresh:
            continue
        checks.append(_check_band(
            metric, base.get(metric), fresh.get(metric), band))
    for metric, gate in sorted(BENCH_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    if "sustained_evals_per_s_by_workers" in fresh:
        checks.append(check_worker_scaling(fresh))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "bench",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks}


def compare_workers(fresh: Dict) -> Dict:
    """--kind workers: judge a worker-A/B doc ALONE (no baseline — a
    2-worker doc is deliberately a different shape from the r05
    1-worker trajectory, so the scale-mismatch guard would reject a
    bench-kind comparison).  The scaling band plus the baseline-free
    absolute gates (refute rate, SLO breaches, sampler budget)."""
    checks: List[Dict] = [check_worker_scaling(fresh)]
    for metric, gate in sorted(BENCH_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "workers",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            "worker_mode": fresh.get("worker_mode"),
            "host_cores": fresh.get("host_cores"),
            "sustained_evals_per_s_by_workers":
                fresh.get("sustained_evals_per_s_by_workers")}


def compare_soak(base: Dict, fresh: Dict) -> Dict:
    checks: List[Dict] = []
    for metric in SOAK_EXACT:
        if metric not in base and metric not in fresh:
            continue
        checks.append(_check_band(metric, base.get(metric),
                                  fresh.get(metric),
                                  ("exact", 0.0, 0.0)))
    # list-valued: violations must be empty on BOTH sides
    row = {"metric": "violations",
           "baseline": base.get("violations", []),
           "fresh": fresh.get("violations", [])}
    row["status"] = ("ok" if not fresh.get("violations") else "fail")
    checks.append(row)
    for metric, gate in sorted(SOAK_ABS_GATES.items()):
        checks.append(_check_abs(metric, fresh.get(metric), gate))
    failed = sorted({c["metric"] for c in checks
                     if c["status"] == "fail"})
    return {"kind": "soak",
            "verdict": "pass" if not failed else "fail",
            "failed": failed,
            "skipped": [c["metric"] for c in checks
                        if c["status"] == "skip"],
            "checks": checks,
            # informational: host speed, not code speed
            "wall_s": {"baseline": base.get("wall_s"),
                       "fresh": fresh.get("wall_s")}}


def _parse_band_overrides(items: List[str],
                          bands: Dict) -> Dict:
    out = dict(bands)
    for it in items:
        if "=" not in it:
            raise SystemExit(f"--band wants metric=REL_TOL, got {it!r}")
        metric, tol = it.split("=", 1)
        direction, _, slack = out.get(metric, ("min", 0.0, 0.0))
        out[metric] = (direction, float(tol), slack)
    return out


def self_check() -> int:
    """CI wiring test: each kind must pass against itself and fail
    against an injected regression — proves the comparator would catch
    a real one (the analyze.py --selftest posture)."""
    bench_path = _latest_bench_baseline()
    soak_path = os.path.join(ROOT, "SOAK_r01.json")
    ok = True
    if bench_path:
        base = _load(bench_path)
        v = compare_bench(base, dict(base), BENCH_BANDS)
        print(f"bench self vs self: {v['verdict']} "
              f"({os.path.basename(bench_path)})")
        ok &= v["verdict"] == "pass"
        bad = dict(base)
        bad["value"] = base["value"] * 0.4
        bad["p99_plan_queue_ms"] = \
            base.get("p99_plan_queue_ms", 1.0) * 10 + 10
        v = compare_bench(base, bad, BENCH_BANDS)
        print(f"bench injected regression: {v['verdict']} "
              f"(failed: {v['failed']})")
        ok &= v["verdict"] == "fail" and "value" in v["failed"]
        v = compare_bench(base, {**base, "workers": 99}, BENCH_BANDS)
        print(f"bench scale mismatch: {v['verdict']}")
        ok &= v["verdict"] == "incomparable"
    else:
        print("no BENCH_r*.json baseline — bench self-check skipped")
    if os.path.exists(soak_path):
        base = _load(soak_path)
        v = compare_soak(base, dict(base))
        print(f"soak self vs self: {v['verdict']}")
        ok &= v["verdict"] == "pass"
        bad = dict(base)
        bad["converged_fingerprint"] = "0" * 64
        bad["soak_breaches"] = 3
        v = compare_soak(base, bad)
        print(f"soak injected regression: {v['verdict']} "
              f"(failed: {v['failed']})")
        ok &= (v["verdict"] == "fail"
               and "converged_fingerprint" in v["failed"]
               and "soak_breaches" in v["failed"])
    else:
        print("no SOAK_r01.json baseline — soak self-check skipped")
    # timeline-plane gate wiring: an injected overhead regression (5%
    # against the 2% budget) must fail the absolute gate; a doc within
    # budget must pass; a doc predating the plane must skip
    over = _check_abs("timeline_overhead_fraction", 0.05,
                      BENCH_ABS_GATES["timeline_overhead_fraction"])
    under = _check_abs("timeline_overhead_fraction", 0.004,
                       BENCH_ABS_GATES["timeline_overhead_fraction"])
    absent = _check_abs("timeline_overhead_fraction", None,
                        BENCH_ABS_GATES["timeline_overhead_fraction"])
    print(f"timeline overhead gate: 5%={over['status']} "
          f"0.4%={under['status']} absent={absent['status']}")
    ok &= (over["status"] == "fail" and under["status"] == "ok"
           and absent["status"] == "skip")
    # worker-scaling band wiring: the gate must catch a sub-1.7x
    # process-mode pair, and must SKIP (not fail) thread-mode and
    # one-core docs where the gate is meaningless
    doc = {"worker_mode": "process", "host_cores": 4,
           "sustained_evals_per_s_by_workers": {"1": 10.0, "2": 18.0}}
    scaled = check_worker_scaling(doc)["status"]
    flat = check_worker_scaling(
        {**doc, "sustained_evals_per_s_by_workers":
         {"1": 10.0, "2": 12.0}})["status"]
    threaded = check_worker_scaling(
        {**doc, "worker_mode": "thread"})["status"]
    onecore = check_worker_scaling({**doc, "host_cores": 1})["status"]
    print(f"worker scaling band: 1.8x={scaled} 1.2x={flat} "
          f"thread={threaded} one-core={onecore}")
    ok &= (scaled == "ok" and flat == "fail"
           and threaded == "skip" and onecore == "skip")
    print(f"perfcheck self-check: {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="compare fresh bench/soak JSON against the "
                    "checked-in trajectory with tolerance bands")
    ap.add_argument("--kind", choices=("bench", "soak", "workers"),
                    default="bench",
                    help="workers: judge a --workers N A/B doc alone "
                         "(process-scaling band + absolute gates; no "
                         "baseline needed)")
    ap.add_argument("--fresh", help="fresh summary JSON to judge")
    ap.add_argument("--baseline",
                    help="baseline JSON (default: newest BENCH_r*.json"
                         " / SOAK_r01.json)")
    ap.add_argument("--band", action="append", default=[],
                    metavar="METRIC=REL_TOL",
                    help="override a metric's relative tolerance")
    ap.add_argument("--allow-scale-mismatch", action="store_true",
                    help="compare across different workload shapes "
                         "anyway (exploration, not gating)")
    ap.add_argument("--json", default="",
                    help="also write the verdict doc to this path")
    ap.add_argument("--self-check", action="store_true",
                    help="validate the comparator against the "
                         "checked-in baselines (CI wiring test)")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.fresh:
        ap.error("--fresh is required (or use --self-check)")
    if args.kind == "workers":
        try:
            fresh = _load(args.fresh)
        except (OSError, ValueError) as e:
            print(f"cannot load inputs: {e}", file=sys.stderr)
            return 2
        verdict = compare_workers(fresh)
        verdict["fresh_path"] = args.fresh
        out = json.dumps(verdict, indent=2, sort_keys=True)
        print(out)
        if args.json:
            with open(args.json, "w") as f:
                f.write(out + "\n")
        return 0 if verdict["verdict"] == "pass" else 1
    baseline = args.baseline
    if not baseline:
        baseline = (_latest_bench_baseline() if args.kind == "bench"
                    else os.path.join(ROOT, "SOAK_r01.json"))
    if not baseline or not os.path.exists(baseline):
        print(f"no baseline found ({baseline!r})", file=sys.stderr)
        return 2
    try:
        base, fresh = _load(baseline), _load(args.fresh)
    except (OSError, ValueError) as e:
        print(f"cannot load inputs: {e}", file=sys.stderr)
        return 2
    if args.kind == "bench":
        bands = _parse_band_overrides(args.band, BENCH_BANDS)
        verdict = compare_bench(base, fresh, bands,
                                args.allow_scale_mismatch)
    else:
        verdict = compare_soak(base, fresh)
    verdict["baseline_path"] = os.path.relpath(baseline, ROOT)
    verdict["fresh_path"] = args.fresh
    out = json.dumps(verdict, indent=2, sort_keys=True)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
