#!/usr/bin/env python
"""Federation smoke: the cluster-observability acceptance run
(ISSUE 20) against three REAL agent processes.

Three `nomad-tpu agent` servers are spawned as separate OS processes —
separate interpreters mean separate process-global tracers, so a
stitched trace that spans origins here is genuinely cross-node, not an
in-process artifact (in-process multi-agent tests share one TRACER and
satisfy the >= 2-origins shape structurally).  The run asserts, in
order:

  1. raft converges on a leader all three servers agree on
  2. a job registered through a NON-leader completes, and the stitched
     trace (GET /v1/trace/<eval>?cluster=true) spans >= 2 origins: the
     forwarding hop's rpc.forward span on the non-leader plus the
     commit/schedule spans on the leader
  3. the leader's federation puller converges: every peer row Ok, zero
     scrape failures, nomad.cluster.* families in the prometheus
     exposition, /v1/operator/cluster-health green, and the
     `nomad cluster status` / `trace status -cluster` CLI verdicts
  4. the leader process is SIGKILLed; the survivors elect a new leader
     whose own puller re-converges to a green cluster-health verdict

The measured scrape duty cycle, peer scrape p99, and stitch latency
land in a JSON doc for perfcheck's federation-kind gates (overhead
<= 0.1%, peer scrape p99 <= 50ms, scrape_failures == 0 — failures are
sampled BEFORE the kill, on the healthy cluster)."""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> List[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _get_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _get_bytes(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _put_json(url: str, doc, timeout: float = 30.0):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _wait(fn, timeout: float, what: str, interval: float = 0.25):
    deadline = time.time() + timeout
    last: Optional[BaseException] = None
    while time.time() < deadline:
        try:
            got = fn()
        except Exception as e:          # endpoint not up yet
            last = e
            got = None
        if got is not None:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}"
                         + (f" (last error: {last})" if last else ""))


class Cluster:
    def __init__(self, n: int = 3) -> None:
        ports = _free_ports(4 * n)
        self.http = ports[0:n]
        self.rpc = ports[n:2 * n]
        self.raft = ports[2 * n:3 * n]
        self.serf = ports[3 * n:4 * n]
        self.names = [f"fed-s{i + 1}" for i in range(n)]
        self.dirs = [tempfile.mkdtemp(prefix=f"fedsmoke-{nm}-")
                     for nm in self.names]
        self.procs: List[Optional[subprocess.Popen]] = [None] * n

    def url(self, i: int) -> str:
        return f"http://127.0.0.1:{self.http[i]}"

    def spawn(self, i: int) -> None:
        argv = [sys.executable, "-m", "nomad_tpu", "agent",
                "-server-name", self.names[i],
                "-bootstrap-expect", "3",
                "-bind", f"127.0.0.1:{self.http[i]}",
                "-rpc-port", str(self.rpc[i]),
                "-raft-port", str(self.raft[i]),
                "-serf-port", str(self.serf[i]),
                "-data-dir", self.dirs[i],
                "-clients", "1", "-workers", "1"]
        if i > 0:
            argv += ["-join", f"127.0.0.1:{self.serf[0]}"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        self.procs[i] = subprocess.Popen(
            argv, cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def leader_index(self) -> Optional[int]:
        """Index every live server agrees is the raft leader."""
        seen = set()
        for i, p in enumerate(self.procs):
            if p is None or p.poll() is not None:
                continue
            got = _get_json(self.url(i) + "/v1/status/leader")
            if not got:
                return None
            seen.add(got)
        if len(seen) != 1:
            return None
        port = int(next(iter(seen)).rsplit(":", 1)[1])
        return self.rpc.index(port) if port in self.rpc else None

    def kill(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait(timeout=30)
        self.procs[i] = None

    def shutdown(self) -> None:
        for i in range(len(self.procs)):
            self.kill(i)
        for d in self.dirs:
            shutil.rmtree(d, ignore_errors=True)


def _cli(address: str, *argv: str) -> "subprocess.CompletedProcess":
    return subprocess.run(
        [sys.executable, "-m", "nomad_tpu", "-address", address,
         *argv],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=60)


def _p99_ms(samples_ms: List[float]) -> float:
    ordered = sorted(samples_ms)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default="",
                    help="write the federation measurement doc here "
                         "(perfcheck --kind federation input)")
    ap.add_argument("--boot-timeout", type=float, default=120.0)
    args = ap.parse_args(argv)

    cluster = Cluster(3)
    try:
        for i in range(3):
            cluster.spawn(i)
        for i in range(3):
            _wait(lambda i=i: _get_json(
                cluster.url(i) + "/v1/agent/self"),
                args.boot_timeout, f"{cluster.names[i]} HTTP up")
        leader = _wait(lambda: cluster.leader_index(),
                       args.boot_timeout, "agreed raft leader")
        others = [i for i in range(3) if i != leader]
        print(f"fedsmoke: leader {cluster.names[leader]}, "
              f"registering through {cluster.names[others[0]]}")

        # --- forwarded registration through a NON-leader ------------
        sys.path.insert(0, REPO)
        from nomad_tpu import mock
        from nomad_tpu.structs import codec
        job = mock.batch_job()
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].config = {"run_for_s": 300}
        eval_id = _put_json(cluster.url(others[0]) + "/v1/jobs",
                            {"Job": codec.encode(job)})["EvalID"]
        assert eval_id, "forwarded register returned no eval"

        def stitched():
            doc = _get_json(cluster.url(others[0])
                            + f"/v1/trace/{eval_id}?cluster=true")
            return doc if len(doc["Origins"]) >= 2 else None
        trace = _wait(stitched, 60.0, "stitched trace >= 2 origins")
        span_names = {s["Name"] for s in trace["Spans"]}
        assert "rpc.forward" in span_names, sorted(span_names)
        print(f"fedsmoke: stitched trace {eval_id[:8]} spans "
              f"{trace['SpanCount']} across origins "
              f"{trace['Origins']}")

        # --- federation convergence on the leader -------------------
        def converged():
            doc = _get_json(cluster.url(leader)
                            + "/v1/operator/cluster-health")
            fed = doc.get("Federation") or {}
            rows = fed.get("Origins") or {}
            if (doc["Healthy"] and fed.get("Scrapes", 0) >= 2
                    and len(rows) == 2
                    and all(r["Ok"] for r in rows.values())):
                return doc
            return None
        health0 = _wait(converged, 60.0, "green cluster-health")
        fed0 = health0["Federation"]
        assert fed0["Failures"] == 0, fed0
        text = _get_bytes(cluster.url(leader)
                          + "/v1/metrics?format=prometheus").decode()
        for fam in ("nomad_cluster_peers", "nomad_cluster_peers_ok",
                    "nomad_cluster_applied_index",
                    "nomad_cluster_healthy", "nomad_cluster_scrapes"):
            assert fam in text, f"missing cluster family {fam}"

        # --- CLI verdicts -------------------------------------------
        r = _cli(cluster.url(leader), "cluster", "status")
        assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
        assert "fed-s" in r.stdout, r.stdout
        r = _cli(cluster.url(others[0]), "trace", "status",
                 "-cluster", eval_id)
        assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
        assert "rpc.forward" in r.stdout, r.stdout
        r = _cli(cluster.url(leader), "health", "-json")
        assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
        assert json.loads(r.stdout)["Healthy"], r.stdout
        print("fedsmoke: cluster status / trace -cluster / health "
              "-json verdicts ok")

        # --- measurements (healthy cluster, pre-failover) -----------
        # overhead = CPU the puller thread burns over the wall window
        # (wall duty cycle is reported too, but it is dominated by peer
        # socket waits that block nothing — the tick scrapes outside
        # its lock); both deltas span >= 2 further cycles
        t0 = time.time()
        busy0 = fed0["ScrapeTotalSeconds"]
        cpu0 = fed0["ScrapeCPUSeconds"]
        scrapes0 = fed0["Scrapes"]

        samples = []
        for i in others:
            url = (cluster.url(i)
                   + "/v1/agent/self?compact=1&since_seq=0")
            for _ in range(25):
                t = time.perf_counter()
                _get_bytes(url)
                samples.append((time.perf_counter() - t) * 1000.0)
        peer_p99 = round(_p99_ms(samples), 3)

        stitches = []
        for _ in range(5):
            t = time.perf_counter()
            _get_json(cluster.url(others[0])
                      + f"/v1/trace/{eval_id}?cluster=true")
            stitches.append((time.perf_counter() - t) * 1000.0)
        stitch_ms = round(sorted(stitches)[len(stitches) // 2], 3)

        def two_more():
            doc = _get_json(cluster.url(leader)
                            + "/v1/operator/cluster-health")
            fed = doc["Federation"]
            return fed if fed["Scrapes"] >= scrapes0 + 2 else None
        fed1 = _wait(two_more, 60.0, "two further scrape cycles")
        elapsed = time.time() - t0
        overhead = (fed1["ScrapeCPUSeconds"] - cpu0) / elapsed
        duty = (fed1["ScrapeTotalSeconds"] - busy0) / elapsed
        assert fed1["Failures"] == 0, fed1

        out = {"schema": "nomad-tpu.fedsmoke.v1",
               "peers": len(fed1["Origins"]),
               "scrapes": fed1["Scrapes"],
               "scrape_failures": fed1["Failures"],
               "peer_scrape_p99_ms": peer_p99,
               "peer_scrape_samples": len(samples),
               "federation_overhead_fraction": round(overhead, 6),
               "scrape_duty_fraction": round(duty, 6),
               "stitch_ms": stitch_ms,
               "trace_origins": trace["Origins"],
               "trace_spans": trace["SpanCount"]}
        print(f"fedsmoke: scrapes={out['scrapes']} "
              f"peer_p99={peer_p99}ms stitch={stitch_ms}ms "
              f"cpu_overhead={out['federation_overhead_fraction']} "
              f"wall_duty={out['scrape_duty_fraction']}")

        # --- leader partition: kill -9, verdict must re-converge ----
        dead = cluster.names[leader]
        cluster.kill(leader)
        print(f"fedsmoke: killed leader {dead}; waiting for "
              "re-convergence")
        new_leader = _wait(
            lambda: (lambda li: li if li is not None
                     and li != leader else None)(cluster.leader_index()),
            120.0, "new raft leader among survivors")

        def reconverged():
            doc = _get_json(cluster.url(new_leader)
                            + "/v1/operator/cluster-health")
            fed = doc.get("Federation") or {}
            rows = fed.get("Origins") or {}
            # the dead peer must have aged OUT of the target set (not
            # sit as a permanently-failing row) and the breach-shaped
            # rules must have recovered: delta-based, so one bad
            # interval during gossip detection is allowed to pass
            if (doc["Healthy"] and fed.get("Scrapes", 0) > 0
                    and rows and all(r["Ok"] for r in rows.values())
                    and dead not in rows):
                return doc
            return None
        _wait(reconverged, 120.0, "green cluster-health on new leader")
        out["failover_reconverged"] = True
        print(f"fedsmoke: new leader {cluster.names[new_leader]} "
              "re-converged green after kill -9")

        if args.json:
            with open(args.json, "w") as f:
                json.dump(out, f, indent=2, sort_keys=True)
        print("fedsmoke ok")
        return 0
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    sys.exit(main())
