#!/usr/bin/env bash
# CI pipeline — the single command that reproduces CI locally
# (reference: .github/workflows/test-core.yaml).  Stages:
#   lint     — scripts/lint.py (AST checks: syntax, unused imports,
#              stray prints, whitespace; no external linters required)
#   analyze  — scripts/analyze.py (scripts/analysis/ package): the
#              eight project-invariant passes (lock discipline,
#              COW/snapshot isolation, JAX purity/donation, thread
#              hygiene, injected-timebase, lock-order graph +
#              blocking-under-lock, canonical-plane determinism, wire
#              proto/struct drift); selftest first (each pass must
#              catch its injected violations), then a repo-wide clean
#              run with stale-suppression accounting strict and the
#              findings archived as JSON
#   test     — the full pytest suite on the 8-virtual-device CPU mesh
#              (tests/conftest.py forces JAX_PLATFORMS=cpu +
#              xla_force_host_platform_device_count=8, so the sharded
#              kernels run everywhere)
#   smoke    — bench.py at reduced scale on the CPU backend: the whole
#              broker -> batched-worker -> plan-queue -> applier
#              pipeline must place every alloc (the run asserts
#              completeness internally; a scheduling regression fails
#              the run)
#   soak     — virtual-time production soak (chaos/soak.py): a seeded
#              cluster-day replayed through the real HTTP API on a
#              VirtualClock, byte-identical on same-seed replay, gated
#              on chaos invariants AND live SLOs (zero watchdog
#              breaches, p99 plan-queue, zone balance / fill gauges)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint selftest (injected undefined name must be caught) =="
python scripts/lint.py --selftest

echo "== lint =="
# covers every file under nomad_tpu/ (core/wavepipe.py included),
# tests/, scripts/, bench.py
python scripts/lint.py

echo "== analyze selftest (each pass must catch its injected violations) =="
python scripts/analyze.py --selftest

echo "== analyze (lock/cow/purity/thread/rawtime/lockorder/determinism/wireproto) =="
python scripts/analyze.py --strict-suppressions --json analyze_findings.json

echo "== wavepipe fast smoke (pipelined engine, CPU mesh) =="
# the async dispatch/collect path first and fast: a regression in the
# wave pipeline (chained launches, refute-repair, columnar commit)
# fails tier-1 here in seconds instead of deep in the full suite
python -m pytest tests/test_wavepipe.py -q -m 'not slow'

echo "== tests (8-virtual-device CPU mesh, tier-1: not slow) =="
python -m pytest tests/ -q -m 'not slow'

echo "== telemetry smoke (dev agent: prometheus scrape + trace fetch) =="
# boot a real dev agent over HTTP, run one job, validate the prometheus
# exposition grammar, and fetch the job's eval trace — the end-to-end
# observability contract (core/telemetry.py) in one pass
JAX_PLATFORMS=cpu python - <<'EOF'
import re
import time

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.structs import codec

agent = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600).start()
api = APIClient(address=agent.address)
try:
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].config = {"run_for_s": 120}
    eval_id = api.jobs.register(codec.encode(job))["EvalID"]
    assert eval_id, "register returned no eval"

    want = {"eval", "broker.wait", "worker.schedule",
            "plan.queue_wait", "plan.apply", "client.alloc_start"}
    deadline = time.time() + 30
    names = set()
    while time.time() < deadline and not want <= names:
        try:
            names = {s["Name"] for s in api.agent.trace(eval_id)["Spans"]}
        except Exception:
            pass
        time.sleep(0.2)
    assert want <= names, f"trace incomplete: {sorted(names)}"

    text = api.agent.metrics(format="prometheus")
    type_re = re.compile(
        r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$")
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?'
        r' -?[0-9]+(\.[0-9]+)?([eE][-+][0-9]+)?$')
    n = 0
    for line in text.strip().splitlines():
        ok = (type_re.match(line) if line.startswith("#")
              else sample_re.match(line))
        assert ok, f"bad exposition line: {line!r}"
        n += 1
    for fam in ("nomad_broker_wait_seconds_bucket",
                "nomad_worker_schedule_seconds_p99",
                "nomad_plan_apply_seconds_sum"):
        assert fam in text, f"missing family {fam}"

    # placement explainability: an unplaceable job must explain WHICH
    # dimension blocked it via /v1/eval/<id>/explain, and the quality
    # gauges must ride the same exposition (ISSUE 5)
    huge = mock.batch_job()
    huge.task_groups[0].count = 1
    huge.task_groups[0].tasks[0].resources.memory_mb = 1 << 24
    huge_eval = api.jobs.register(codec.encode(huge))["EvalID"]
    deadline = time.time() + 30
    doc = {}
    while time.time() < deadline and not doc.get("BlockedEval"):
        doc = api.evaluations.explain(huge_eval)
        time.sleep(0.2)
    assert doc.get("BlockedEval"), f"never blocked: {doc}"
    tg = doc["TaskGroups"][huge.task_groups[0].name]
    assert tg["Metric"]["DimensionExhausted"].get("memory"), doc
    assert "memory" in tg["Cause"], doc
    pf = api.jobs.placement_failures(huge.id)
    assert pf["Blocked"] and "memory" in pf["Cause"], pf
    text = api.agent.metrics(format="prometheus")
    for fam in ("nomad_quality_nodes_in_use",
                "nomad_quality_zone_balance_max_over_min",
                "nomad_quality_binpack_fill"):
        assert fam in text, f"missing quality family {fam}"
    print(f"explain smoke ok: eval {huge_eval[:8]} blocked on "
          f"{sorted(tg['Metric']['DimensionExhausted'])}")

    # memory ledger rides the same observability contract (ISSUE 19):
    # the operator doc, the debug bundle's Memory + unified Evictions
    # keys, and the nomad.mem.* families in the exposition
    mem = api.operator.memory()
    assert mem["Schema"] == "nomad-tpu.memory.v1", mem
    assert mem["RSSBytes"] > 0 and mem["TrackedBytes"] > 0, mem
    assert {"state", "journal", "flight"} <= set(mem["Planes"]), mem
    dbg = api.operator.debug()
    assert dbg["Memory"]["RSSBytes"] > 0, sorted(dbg)
    assert "journal" in dbg["Evictions"], sorted(dbg["Evictions"])
    text = api.agent.metrics(format="prometheus")
    for fam in ("nomad_mem_rss_bytes", "nomad_mem_plane_bytes"):
        assert fam in text, f"missing memory family {fam}"
    print(f"memory smoke ok: rss={mem['RSSBytes']} "
          f"tracked={mem['TrackedBytes']} planes={len(mem['Planes'])}")
    print(f"telemetry smoke ok: {n} exposition lines, trace {eval_id[:8]}"
          f" spans={sorted(names)}")
finally:
    agent.shutdown()
EOF

echo "== health smoke (unmeetable SLO -> breach + dump bundle) =="
# the dump-on-anomaly plane (core/flightrec.py): boot a dev agent with
# a deliberately-unmeetable plan-queue SLO, drive a workload, and
# assert /v1/operator/health reports the breach, the retained dump
# bundle validates against the schema, and the HealthBreach event
# replays from the stream buffer
JAX_PLATFORMS=cpu python - <<'EOF'
import json
import time
import urllib.request

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.structs import codec

agent = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600,
              slo={"p99_plan_queue_ms": 1e-9, "interval_s": 0.0}).start()
api = APIClient(address=agent.address)
try:
    job = mock.batch_job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].config = {"run_for_s": 120}
    api.jobs.register(codec.encode(job))
    deadline = time.time() + 30
    doc = {}
    while time.time() < deadline:
        doc = api.operator.health(dumps=True)
        if not doc["Healthy"]:
            break
        time.sleep(0.2)
    assert not doc["Healthy"], doc
    bad = {r["Rule"] for r in doc["Rules"] if not r["Ok"]}
    assert "p99_plan_queue_ms" in bad, doc["Rules"]
    bundles = doc["DumpBundles"]
    assert bundles, "breach produced no dump bundle"
    for key in ("Schema", "At", "Breaches", "Verdicts", "SLO",
                "FlightRecorder", "Windows", "Traces", "Spans", "Logs"):
        assert key in bundles[0], sorted(bundles[0])
    assert bundles[0]["Schema"] == "nomad-tpu.health-dump.v1"
    assert any(b["Rule"] == "p99_plan_queue_ms"
               for b in bundles[0]["Breaches"])
    assert "nomad.plan.queue_wait_s" in bundles[0]["Windows"]
    assert bundles[0]["FlightRecorder"]["Evals"], "flight ring empty"
    # the breach rode the event stream: replay from the buffer
    url = agent.address + "/v1/event/stream?topic=HealthBreach:*&index=0"
    got = None
    with urllib.request.urlopen(url, timeout=10) as resp:
        for line in resp:
            line = line.strip()
            if not line or line == b"{}":
                continue
            for e in json.loads(line).get("Events", []):
                if e["Topic"] == "HealthBreach":
                    got = e
                    break
            if got:
                break
    assert got and got["Key"] == "p99_plan_queue_ms", got
    # the CLI verdict exits non-zero on breach (scriptable health check)
    from nomad_tpu.cli import main
    rc = main(["-address", agent.address, "health"])
    assert rc == 1, rc
    print(f"health smoke ok: breach={sorted(bad)} "
          f"dumps={len(bundles)} event={got['Key']}")
finally:
    agent.shutdown()
EOF

echo "== executor smoke (device-resident worker loop, jax backend) =="
# boot a dev agent on the default JAX device executor, push a
# multi-wave workload through the REAL eval-driven path, and assert
# the resident usage chain actually carried across waves
# (nomad.executor.resident_waves > 0) — plus a scoped run of the
# invariant analyzer's JAX purity/donation pass over the new module
JAX_PLATFORMS=cpu python - <<'EOF'
import pathlib
import sys
import time

sys.path.insert(0, "scripts")
from analyze import analyze_source

src = pathlib.Path("nomad_tpu/ops/executor.py").read_text()
findings = analyze_source(src, path="nomad_tpu/ops/executor.py",
                          passes=("purity",))
assert not findings, f"purity/donation findings in executor: {findings}"

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.structs import codec

agent = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600,
              device_executor="jax").start()
api = APIClient(address=agent.address)
try:
    def wave():
        evals = []
        for _ in range(8):
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 2
            # long-running tasks: completions would free capacity and
            # (correctly) invalidate the chain mid-smoke
            tg.tasks[0].config = {"run_for_s": 300}
            tg.tasks[0].resources.cpu = 20
            tg.tasks[0].resources.memory_mb = 16
            evals.append(api.jobs.register(codec.encode(job))["EvalID"])
        deadline = time.time() + 30
        while time.time() < deadline:
            done = sum(1 for e in evals
                       if api.evaluations.info(e).get("Status")
                       in ("complete", "failed"))
            if done == len(evals):
                return
            time.sleep(0.1)
        raise AssertionError("executor smoke wave never completed")

    resident = 0
    for _ in range(4):          # multi-wave; stop at the first chain hit
        wave()
        m = api.agent.metrics()
        resident = m.get("nomad.executor.resident_waves", 0)
        if resident > 0:
            break
    assert resident > 0, (
        "no launch rode the resident chain: "
        f"{ {k: v for k, v in m.items() if 'executor' in k} }")
    assert m.get("nomad.executor.uploads", 0) > 0
    print(f"executor smoke ok: resident_waves={resident} "
          f"uploads={m['nomad.executor.uploads']} "
          f"upload_bytes={m['nomad.executor.upload_bytes']}")
finally:
    agent.shutdown()
EOF

echo "== profile smoke (continuous profiling plane, capture bundle) =="
# boot a dev agent under load, take a short on-demand capture through
# POST /v1/operator/profile, and validate the bundle schema: compile
# ledger populated (the agent just compiled its kernels), HBM
# watermark nonzero, h2d split by cause, >= 90% of sampled thread
# time in a named bucket, sampler overhead within the 2% budget
JAX_PLATFORMS=cpu python - <<'EOF'
import time

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.structs import codec

agent = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600,
              device_executor="jax").start()
api = APIClient(address=agent.address)
try:
    evals = []
    for _ in range(8):
        job = mock.batch_job()
        tg = job.task_groups[0]
        tg.count = 2
        tg.tasks[0].config = {"run_for_s": 300}
        tg.tasks[0].resources.cpu = 20
        tg.tasks[0].resources.memory_mb = 16
        evals.append(api.jobs.register(codec.encode(job))["EvalID"])
    deadline = time.time() + 30
    while time.time() < deadline:
        done = sum(1 for e in evals
                   if api.evaluations.info(e).get("Status")
                   in ("complete", "failed"))
        if done == len(evals):
            break
        time.sleep(0.1)

    st = api.operator.profile_status()
    assert st["running"], "sampler must be always-on by default"
    b = api.operator.profile(duration_s=1.5)
    assert b["schema"] == "nomad-tpu.profile.v1", b["schema"]
    assert b["samples"] > 0, b["samples"]
    assert b["attributed_fraction"] >= 0.90, b["attributed_fraction"]
    assert b["overhead_fraction"] <= 0.02, b["overhead_fraction"]
    comp = b["compile_ledger"]
    assert comp["misses"] > 0 and comp["sites"], comp
    led = b["device_ledger"]
    assert led and led["hbm_high_watermark_bytes"] > 0, led
    assert led["upload_bytes_by_cause"], led
    assert b["folded"], "capture carried no folded stacks"
    assert b["flight_recorder"] is not None
    # retained + addressable by id, and folded into the debug bundle
    assert api.operator.profile_capture(b["id"])["id"] == b["id"]
    dbg = api.operator.debug()
    assert "Profiler" in dbg and "DeviceLedger" in dbg, sorted(dbg)
    print(f"profile smoke ok: {b['id']} samples={b['samples']} "
          f"attributed={b['attributed_fraction']:.3f} "
          f"overhead={b['overhead_fraction']:.5f} "
          f"compile_sites={len(comp['sites'])} "
          f"hbm_watermark={led['hbm_high_watermark_bytes']}")
finally:
    agent.shutdown()
EOF

echo "== timeline smoke (retrospective plane: breach post-mortem + HTTP) =="
# the retrospective timeline plane (core/timeline.py): a seeded
# flap-storm soak with a zero-tolerance heartbeat SLO must produce a
# breach whose post-mortem report pins the storm's own traffic.node.*
# annotation (not merely the nearest-in-time noise); then a live dev
# agent must serve clock-aligned history over GET /v1/operator/timeline
# and render it through `nomad timeline` / `nomad report`
JAX_PLATFORMS=cpu python - <<'EOF'
from nomad_tpu.chaos.soak import run_soak
from nomad_tpu.chaos.traffic import TrafficProfile
from nomad_tpu.core.timeline import build_report, render_report_md

r = run_soak(seed=7, profile=TrafficProfile(
    hours=0.05, n_nodes=4, n_zones=2, service_per_hour=40,
    batch_per_hour=40, drains_per_hour=0.0, flap_storms_per_hour=20.0,
    flap_storm_nodes=2, preempt_storms_per_hour=0.0,
    chaos_scenarios=()), slo={"heartbeat_misses": 0.0})
rep = build_report(r.timeline)
breaches = [i for i in rep["Incidents"]
            if i["Kind"] == "breach" and i["Rule"] == "heartbeat_misses"]
assert breaches, rep["AnnotationKinds"]
attributed = [a for i in breaches for a in i["Attribution"]]
assert any(a["Kind"].startswith("traffic.node.")
           for a in attributed), attributed
md = render_report_md(rep)
assert "heartbeat_misses" in md and "traffic.node." in md
assert len(r.summary["timeline_digest"]) == 64
print(f"timeline report smoke ok: {len(breaches)} heartbeat breach(es)"
      f" attributed to the flap storm, digest"
      f" {r.summary['timeline_digest'][:16]}")
EOF
JAX_PLATFORMS=cpu python - <<'EOF'
import time

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.cli import main
from nomad_tpu.structs import codec

agent = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600).start()
api = APIClient(address=agent.address)
try:
    job = mock.batch_job()
    job.task_groups[0].count = 2
    api.jobs.register(codec.encode(job))
    deadline = time.time() + 30
    doc = {}
    while time.time() < deadline:
        doc = api.operator.timeline()
        if doc["Points"] > 1 and doc["Annotations"]:
            break
        time.sleep(0.2)
    assert doc["Schema"] == "nomad-tpu.timeline.v1", doc["Schema"]
    assert doc["Points"] > 1, doc
    kinds = {a["Kind"] for a in doc["Annotations"]}
    assert "leadership.established" in kinds, sorted(kinds)
    sub = api.operator.timeline(series=["evals_per_s"], step=5.0)
    assert set(sub["Series"]) == {"evals_per_s"}, sorted(sub["Series"])
    assert "Timeline" in api.operator.debug(), "debug bundle lost it"
    assert main(["-address", agent.address, "timeline"]) == 0
    assert main(["-address", agent.address, "report"]) == 0
    print(f"timeline http smoke ok: {doc['Points']} points,"
          f" kinds={sorted(kinds)}")
finally:
    agent.shutdown()
EOF

echo "== perfcheck (trajectory gate comparator, self-check) =="
# the bench/soak tolerance-band comparator must pass the checked-in
# baselines against themselves and catch injected regressions before
# anything trusts its verdicts (the analyze.py --selftest posture)
python scripts/perfcheck.py --self-check

echo "== multichip (8-device virtual mesh: parity, scale soak, bench) =="
# the sharded production path (ISSUE 7): engine-level sharded-vs-single
# parity + padded-row properties, the resident-chain sharded parity
# suite, the >=200k-node quality soak, then a 64k-node sharded bench
# smoke that must report the full 8-way mesh with zero plan refutes.
# (pytest runs already ride the 8-virtual-device mesh via conftest;
# bench.py forces it itself with --mesh 8.)
JAX_PLATFORMS=cpu python -m pytest tests/test_engine_sharded.py -q
JAX_PLATFORMS=cpu python -m pytest tests/test_wavepipe.py -q \
    -k "Resident or Sharded or sharded"
JAX_PLATFORMS=cpu python -m pytest tests/test_multichip_scale.py -q -m slow
JAX_PLATFORMS=cpu python bench.py --nodes 64000 --evals 16 \
    --placements 4000 --iters 1 --mesh 8 --quick | python -c '
import json, sys
out = json.load(sys.stdin)
assert out["n_devices"] == 8, out
assert out["plan_refute_rate"] == 0, out
assert out["sharded_parity_checked"], out
assert out["collective_bytes_per_wave"] > 0, out
print("multichip smoke ok:", out["value"], out["unit"],
      "n_devices", out["n_devices"],
      "collective_bytes_per_wave", out["collective_bytes_per_wave"])'

echo "== chaos (seeded fault-injection scenarios on the virtual clock) =="
# the full chaos suite: every scenario in tests/test_chaos.py with its
# pinned seed (partition / split-brain / flap storm / lossy raft /
# heartbeat expiry), the seed-determinism double-run, and the
# trace-replay check — plus the wall-clock cluster tests the virtual-
# clock scenarios superseded in tier-1
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q
JAX_PLATFORMS=cpu python -m pytest tests/test_cluster.py -q -m slow

echo "== soak (virtual-time cluster-day replay, gated on live SLOs) =="
# the production soak (chaos/soak.py + chaos/traffic.py): a seeded
# schedule of service/batch/system jobs, rolling deploys, autoscaling
# churn, drains, flap storms, and preemption storms drives a REAL
# agent through the HTTP API on a VirtualClock.  The quick profile
# runs twice and must be byte-identical (same seed, same bytes); the
# summary JSON lands next to the bench JSONs, and the slow marker run
# is the acceptance shape: >=2h virtual, green, zero breaches, <90s
# wall
JAX_PLATFORMS=cpu python -m nomad_tpu soak -quick -check-determinism \
    -json SOAK_ci.json
python - <<'EOF'
import json
out = json.load(open("SOAK_ci.json"))
for k in ("soak_virtual_hours", "soak_evals", "soak_breaches",
          "converged_fingerprint", "trace_digest", "determinism_ok"):
    assert k in out, f"missing summary field {k}"
assert out["ok"] and out["determinism_ok"], out
assert out["soak_breaches"] == 0, out
print("soak summary ok:", out["soak_virtual_hours"], "virtual hours,",
      out["soak_evals"], "evals, fingerprint",
      out["converged_fingerprint"][:16])
EOF
JAX_PLATFORMS=cpu python -m pytest tests/test_soak_sim.py -q -m slow

echo "== networked (port parity gate, churn soak, bench smoke) =="
# batched columnar port assignment (ISSUE 8): the pytest suite runs the
# batched-vs-sequential parity gate + the NetworkIndex edge cases + the
# place->kill->replace churn soak, then a --networked --quick bench
# smoke must report zero (node, port) collisions, a parity-gated run,
# and a networked rate within the acceptance band of the columnar rate
JAX_PLATFORMS=cpu python -m pytest tests/test_ports.py -q
JAX_PLATFORMS=cpu python bench.py --networked --quick | python -c '
import json, sys
out = json.load(sys.stdin)
assert out["port_collisions"] == 0, out
assert out["port_parity_checked"], out
assert out["placed"] == out["want"], out
assert out["port_batched_rows"] > 0, out
# ratio floor: networked must stay within 3x of the columnar rate at
# the same shape (the pre-batch per-alloc path sat ~25x under it);
# CPU-host smoke noise gets a little slack on top of the acceptance
assert out["networked_vs_columnar_ratio"] <= 4.0, out
print("networked smoke ok:", out["value"], out["unit"],
      "ratio", out["networked_vs_columnar_ratio"],
      "collisions", out["port_collisions"])'

echo "== multiproc (process worker plane: pool suite + scaling A/B) =="
# the multi-process worker plane (ISSUE 14): state export/delta
# replica round-trips, device submission front-end serialization,
# sharded dynamic-port cursors, the spawn-based 2-worker integration
# (networked waves complete with zero plan refutes) and worker-crash
# recovery — then a process-mode --workers 2 bench A/B whose scaling
# band perfcheck gates (>= 1.7x over 1 worker on multi-core hosts;
# a single-core host skips the scaling gate HONESTLY, never silently:
# the verdict names the skip and still checks refutes + JSON shape)
JAX_PLATFORMS=cpu python -m pytest tests/test_workerpool.py -q
JAX_PLATFORMS=cpu python bench.py --config 5 --nodes 400 --evals 8 \
    --placements 384 --batch 8 --iters 1 --quick \
    --workers 2 --worker-mode process --mesh off > BENCH_pool.json
python scripts/perfcheck.py --kind workers --fresh BENCH_pool.json

echo "== fanout (read-path plane: hub/ring/follower suite + watcher smoke) =="
# the read-path fanout plane (ISSUE 18): the WatchHub coalescing /
# EventRing cursor / ReadFollower no-stale-reads suite, then a
# --watchers --quick smoke (in-run asserts already fail the run on any
# stale wake or undelivered stream round) judged by the watchers-kind
# perfcheck gates: scale-aware p99 wake band, O(rounds) eval
# coalescing, zero drops, and the parked-vs-idle write-throughput
# ratio floor that stands in for "scheduler throughput must not
# regress under a parked 10k fleet"
JAX_PLATFORMS=cpu python -m pytest tests/test_fanout.py -q
JAX_PLATFORMS=cpu python bench.py --watchers --quick > BENCH_watchers.json
python scripts/perfcheck.py --kind watchers --fresh BENCH_watchers.json

echo "== memory (footprint plane: ledger suite + RSS-gated soak, both directions) =="
# the memory & footprint observability plane (ISSUE 19): the ledger /
# compaction-equivalence / floor-fallback / idle-reap suite, then a
# quick churn soak under a generous RSS ceiling judged by the
# memory-kind perfcheck gates (RSS high-water, floor-fallbacks == 0,
# eviction budget, ledger overhead <= 0.1% of soak wall), and finally
# the fail direction: an absurdly small ceiling must trip the gate
# and exit non-zero (a gate that cannot fail is not a gate)
JAX_PLATFORMS=cpu python -m pytest tests/test_memledger.py -q
JAX_PLATFORMS=cpu python -m nomad_tpu soak -quick -rss-ceiling-mb 8192 \
    -json SOAK_mem.json
python - <<'EOF'
import json
out = json.load(open("SOAK_mem.json"))
for k in ("rss_peak_bytes", "journal_bytes", "journal_compactions",
          "journal_floor_fallbacks", "ring_evictions",
          "mem_scrape_us", "mem_overhead_fraction"):
    assert k in out, f"missing summary field {k}"
assert out["ok"], out
assert out["rss_peak_bytes"] > 0, out
assert out["journal_floor_fallbacks"] == 0, out
print("memory summary ok: rss_peak",
      round(out["rss_peak_bytes"] / 1048576.0, 1), "MiB, journal",
      out["journal_bytes"], "B, overhead",
      out["mem_overhead_fraction"])
EOF
python scripts/perfcheck.py --kind memory --fresh SOAK_mem.json
if JAX_PLATFORMS=cpu python -m nomad_tpu soak -quick \
    -rss-ceiling-mb 1 >/dev/null 2>&1; then
    echo "memory gate FAILED OPEN: 1 MiB RSS ceiling did not trip" >&2
    exit 1
fi
echo "memory gate fail-direction ok: 1 MiB ceiling tripped as expected"

echo "== federation (cluster observability: 3-process cluster, stitching, failover) =="
# the cluster-scope observability plane (ISSUE 20): the obsbus /
# snapshot / stitching / puller suite first, then scripts/fedsmoke.py
# boots three REAL agent processes (separate interpreters = separate
# tracers, so the stitched trace crossing origins is genuine) into one
# raft cluster and asserts: a job registered through a NON-leader
# yields a stitched trace spanning >= 2 origins (the rpc.forward hop +
# the leader's commit spans), nomad.cluster.* families ride the
# leader's exposition, /v1/operator/cluster-health and the
# `nomad cluster status` / `trace status -cluster` verdicts are green
# — then the leader is SIGKILLed and the new leader's verdict must
# re-converge.  The measured scrape CPU duty / peer p99 / stitch
# latency land in FED_ci.json, judged by the federation-kind perfcheck
# gates (overhead <= 0.1%, peer scrape p99 <= 50ms, zero failures on
# the healthy cluster)
JAX_PLATFORMS=cpu python -m pytest tests/test_federation.py -q -m 'not slow'
JAX_PLATFORMS=cpu python scripts/fedsmoke.py --json FED_ci.json
python scripts/perfcheck.py --kind federation --fresh FED_ci.json

echo "== bench smoke (CPU backend, reduced scale) =="
JAX_PLATFORMS=cpu python bench.py --nodes 1000 --evals 16 \
    --placements 2000 --iters 1 | python -c '
import json, sys
out = json.load(sys.stdin)
assert out["value"] > 0, out
assert out["slo_breaches"] == 0, out
assert out["wave_device_s_p99"] > 0, out
print("smoke ok:", out["metric"], out["value"], out["unit"],
      "slo_breaches", out["slo_breaches"])'

echo "== CI green =="
