"""Analyzer driver: pass scoping, the repo walk, suppression
accounting, JSON findings output, and the CLI.

Nine passes (suppress a finding with `# analyze: ok <pass>` on its
line, or `# analyze: ok *`):

  lock         lock discipline (*_locked helpers under the lock)
  cow          COW / snapshot-isolation discipline (state_store.py)
  purity       JAX purity & donation (ops/, parallel/, wavepipe)
  thread       thread/process hygiene (top-level handlers, name=)
  rawtime      injected-timebase discipline (core/, chaos/,
               scheduler/, state/, api/)
  lockorder    inter-procedural lock-order graph: deadlock cycles +
               blocking-under-lock (whole nomad_tpu package)
  determinism  canonical-plane drift (set order, global RNG, id/hash
               ordering, fs enumeration) in trace/soak/traffic/
               timeline/wire/codec
  wireproto    RPC op-table parity + payload-key drift (workerpool) +
               the wire-struct manifest/version gate
  obsbus       observability planes must register on the ObsBus
               (core/ modules with a module-level `configure()`)

Stale-suppression accounting: every `# analyze: ok <pass>` comment in
the scoped files must still suppress at least one raw finding of that
pass; dead comments are reported (warning by default,
`--strict-suppressions` fails the run) so the suppression inventory
cannot rot.
"""

from __future__ import annotations

import ast
import json
import sys
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from common import Finding, PASS_NAMES, ROOT, _suppressed
from cowpass import check_cow
from determinism import check_determinism
from lockorder import check_lockorder
from lockpass import check_lock
from obsbuspass import check_obsbus
from puritypass import check_purity
from rawtimepass import check_rawtime
from threadpass import check_thread
import wireproto as _wp

MANIFEST_PATH = Path(__file__).resolve().parent / "wire_manifest.json"

# (path, lineno, pass-token) of a suppression comment that silences
# nothing
Stale = Tuple[str, int, str]


def _scoped_files() -> Dict[str, List[Path]]:
    """pass name -> files it runs over."""
    pkg = ROOT / "nomad_tpu"
    all_py = sorted(p for p in pkg.rglob("*.py")
                    if "__pycache__" not in p.parts)
    purity = sorted((pkg / "ops").glob("*.py")) \
        + sorted((pkg / "parallel").glob("*.py")) \
        + [pkg / "core" / "wavepipe.py"]
    rawtime = sorted((pkg / "core").glob("*.py")) \
        + sorted((pkg / "chaos").glob("*.py")) \
        + sorted((pkg / "scheduler").glob("*.py")) \
        + sorted((pkg / "state").glob("*.py")) \
        + sorted((pkg / "api").glob("*.py"))
    determinism = [pkg / "chaos" / "trace.py",
                   pkg / "chaos" / "soak.py",
                   pkg / "chaos" / "traffic.py",
                   pkg / "core" / "timeline.py",
                   pkg / "core" / "wire.py",
                   pkg / "structs" / "codec.py"]
    wireproto = [pkg / "core" / "workerpool.py"]
    return {
        "lock": all_py,
        "cow": [pkg / "state" / "state_store.py"],
        "purity": purity,
        "thread": all_py,
        "rawtime": rawtime,
        "lockorder": all_py,
        "determinism": determinism,
        "wireproto": wireproto,
        "obsbus": sorted((pkg / "core").glob("*.py")),
    }


def _wire_struct_files() -> List[Path]:
    """Modules whose dataclasses ride the wire codec (the
    register_module set: nomad_tpu.structs, structs.structs,
    ops/engine)."""
    pkg = ROOT / "nomad_tpu"
    return [pkg / "structs" / "__init__.py",
            pkg / "structs" / "structs.py",
            pkg / "ops" / "engine.py"]


def _wire_py() -> Path:
    return ROOT / "nomad_tpu" / "core" / "wire.py"


def load_manifest() -> Optional[dict]:
    if not MANIFEST_PATH.exists():
        return None
    try:
        return json.loads(MANIFEST_PATH.read_text())
    except ValueError:
        return None


def analyze_source(text: str, path: str = "<memory>",
                   passes: Iterable[str] = PASS_NAMES) -> List[Finding]:
    """Run single-module passes over one source blob (selftest + unit
    tests); whole-program passes run in single-module mode."""
    tree = ast.parse(text)
    findings: List[Finding] = []
    for name in passes:
        if name == "lock":
            findings.extend(check_lock(tree, path))
        elif name == "cow":
            findings.extend(check_cow(tree, path))
        elif name == "purity":
            findings.extend(check_purity({path: tree}))
        elif name == "thread":
            findings.extend(check_thread(tree, path))
        elif name == "rawtime":
            findings.extend(check_rawtime(tree, path))
        elif name == "lockorder":
            findings.extend(check_lockorder({path: tree}))
        elif name == "determinism":
            findings.extend(check_determinism(tree, path))
        elif name == "wireproto":
            findings.extend(_wp.check_wireproto({path: tree}))
        elif name == "obsbus":
            findings.extend(check_obsbus(tree, path))
    lines = text.splitlines()
    return sorted({f for f in findings
                   if not _suppressed(lines, f[1], f[2])})


def _collect_suppressions(texts: Dict[str, str]
                          ) -> List[Tuple[str, int, str]]:
    """(path, lineno, pass-token) for every `# analyze: ok ...`
    comment in the analyzed files."""
    out = []
    for path in sorted(texts):
        for i, line in enumerate(texts[path].splitlines(), 1):
            marker = "analyze: ok "
            at = line.find(marker)
            if at < 0:
                continue
            token = line[at + len(marker):].split()
            out.append((path, i, token[0] if token else "*"))
    return out


def analyze_repo_full(root: Path = ROOT
                      ) -> Tuple[List[Finding], List[Stale]]:
    """(active findings, stale suppression comments) repo-wide."""
    scopes = _scoped_files()
    texts: Dict[str, str] = {}
    trees: Dict[str, ast.Module] = {}
    raw: List[Finding] = []

    def load(p: Path) -> Optional[str]:
        key = str(p)
        if key in trees:
            return key
        if not p.exists():
            return None
        texts[key] = p.read_text()
        try:
            trees[key] = ast.parse(texts[key])
        except SyntaxError as e:
            raw.append((key, e.lineno or 0, "parse",
                        f"syntax error: {e.msg}"))
            return None
        return key

    for files in scopes.values():
        for p in files:
            load(p)
    struct_keys = [k for k in (load(p) for p in _wire_struct_files())
                   if k is not None]
    wire_key = load(_wire_py())

    single = {"lock": check_lock, "cow": check_cow,
              "thread": check_thread, "rawtime": check_rawtime,
              "determinism": check_determinism, "obsbus": check_obsbus}
    for name, checker in single.items():
        for p in scopes[name]:
            key = str(p)
            if key in trees:
                raw.extend(checker(trees[key], key))
    purity_files = {str(p): trees[str(p)] for p in scopes["purity"]
                    if str(p) in trees}
    raw.extend(check_purity(purity_files))
    lockorder_files = {str(p): trees[str(p)] for p in scopes["lockorder"]
                       if str(p) in trees}
    raw.extend(check_lockorder(lockorder_files))
    wp_files = {str(p): trees[str(p)] for p in scopes["wireproto"]
                if str(p) in trees}
    raw.extend(_wp.check_wireproto(
        wp_files,
        struct_files={k: trees[k] for k in struct_keys},
        manifest=load_manifest(),
        wire_tree=trees.get(wire_key) if wire_key else None,
        wire_path=str(_wire_py()),
        manifest_path=str(MANIFEST_PATH)))

    active = set()
    suppressed_at: Dict[Tuple[str, int], set] = {}
    for f in raw:
        lines = texts.get(f[0], "").splitlines()
        if _suppressed(lines, f[1], f[2]):
            suppressed_at.setdefault((f[0], f[1]), set()).add(f[2])
        else:
            active.add(f)

    stale: List[Stale] = []
    for path, lineno, token in _collect_suppressions(texts):
        used = suppressed_at.get((path, lineno), set())
        if token == "*":
            if not used:
                stale.append((path, lineno, token))
        elif token not in used:
            stale.append((path, lineno, token))
    return sorted(active), stale


def analyze_repo(root: Path = ROOT) -> List[Finding]:
    return analyze_repo_full(root)[0]


def _rel(path: str) -> str:
    p = Path(path)
    try:
        return str(p.relative_to(ROOT))
    except ValueError:
        return str(p)


def update_manifest() -> int:
    struct_trees: Dict[str, ast.Module] = {}
    for p in _wire_struct_files():
        if p.exists():
            struct_trees[str(p)] = ast.parse(p.read_text())
    wire_tree = ast.parse(_wire_py().read_text())
    wire_ver, _ = _wp.wire_schema_version(wire_tree)
    old = load_manifest()
    fresh = _wp.compute_struct_manifest(struct_trees, wire_ver or 1)
    if old is not None:
        if old.get("structs") == fresh["structs"]:
            fresh["schema_version"] = old.get("schema_version",
                                              fresh["schema_version"])
            print(f"wire manifest unchanged ({len(fresh['structs'])} "
                  f"structs, schema_version={fresh['schema_version']})")
        else:
            fresh["schema_version"] = int(old.get("schema_version", 0)) + 1
            print(f"wire manifest REGENERATED: schema_version -> "
                  f"{fresh['schema_version']} — bump SCHEMA_VERSION in "
                  "core/wire.py to match")
    else:
        print(f"wire manifest created ({len(fresh['structs'])} structs, "
              f"schema_version={fresh['schema_version']})")
    MANIFEST_PATH.write_text(json.dumps(fresh, indent=1, sort_keys=True)
                             + "\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        from selftests import selftest
        return selftest()
    if "--update-manifest" in argv:
        return update_manifest()
    strict = "--strict-suppressions" in argv
    json_path = ""
    if "--json" in argv:
        at = argv.index("--json")
        if at + 1 >= len(argv):
            print("analyze: --json needs a path (or '-')")
            return 2
        json_path = argv[at + 1]

    t0 = time.perf_counter()
    findings, stale = analyze_repo_full()
    elapsed = time.perf_counter() - t0

    for path, lineno, name, msg in findings:
        print(f"{_rel(path)}:{lineno}: [{name}] {msg}")
    for path, lineno, token in stale:
        kind = "error" if strict else "warning"
        print(f"{_rel(path)}:{lineno}: [suppression] {kind}: "
              f"`# analyze: ok {token}` no longer suppresses any "
              "finding — remove it (or fix the pass name)")
    n_files = sum(len(v) for v in _scoped_files().values())
    print(f"analyze: {len(findings)} finding(s), {len(stale)} stale "
          f"suppression(s) over {n_files} pass-file runs in "
          f"{elapsed:.2f}s")

    if json_path:
        doc = {
            "schema": "nomad-tpu.analyze.v1",
            "elapsed_s": round(elapsed, 4),
            "pass_file_runs": n_files,
            "findings": [
                {"path": _rel(p), "line": ln, "pass": nm, "message": m}
                for p, ln, nm, m in findings],
            "stale_suppressions": [
                {"path": _rel(p), "line": ln, "pass": tok}
                for p, ln, tok in stale],
        }
        blob = json.dumps(doc, indent=1, sort_keys=True) + "\n"
        if json_path == "-":
            sys.stdout.write(blob)
        else:
            Path(json_path).write_text(blob)
    if findings:
        return 1
    if stale and strict:
        return 1
    return 0
