"""Invariant analyzer package.

Layout: `common.py` (Finding/helpers/suppression), one module per pass
(lockpass, cowpass, puritypass, threadpass, rawtimepass, lockorder,
determinism, wireproto), `driver.py` (scoping, repo walk, suppression
accounting, CLI), `selftests.py` (injected-violation fixtures).

The pass modules import each other flat (`from common import ...`) so
they also run as plain scripts; this __init__ bootstraps the package
directory onto sys.path before touching them.
"""

import sys
from pathlib import Path

_PKG = Path(__file__).resolve().parent
if str(_PKG) not in sys.path:
    sys.path.insert(0, str(_PKG))

from common import Finding, PASS_NAMES, ROOT
from driver import (analyze_repo, analyze_repo_full, analyze_source,
                    main, update_manifest)
from selftests import selftest

__all__ = ["Finding", "PASS_NAMES", "ROOT", "analyze_repo",
           "analyze_repo_full", "analyze_source", "main", "selftest",
           "update_manifest"]
