"""Pass `lock` — lock discipline.

A `*_locked` / `_writable_*` helper mutates or reads head state that
only the store/broker lock makes consistent — it may only be called
from another such helper or from a lexical `with self._lock:` (or
`.locked()` / condition) scope.  Public entry points must acquire
before delegating.
"""

from __future__ import annotations

import ast
from typing import List, Set

from common import Finding, _callee_name, _functions, _walk_skip_defs

LOCK_ATTRS = {"_lock", "lock", "_cv", "_index_cv", "_apply_cv",
              "_tick_lock"}
LOCKED_PREFIXES = ("_writable_",)


def _is_lock_expr(node: ast.AST, aliases: Set[str]) -> bool:
    """Expressions that acquire the protecting lock when used in
    `with ...:` — the lock/condition attribute itself, a `.locked()`
    accessor, or a local alias of either."""
    if isinstance(node, ast.Attribute) and node.attr in LOCK_ATTRS:
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "locked":
            return True
    if isinstance(node, ast.IfExp):
        return (_is_lock_expr(node.body, aliases)
                or _is_lock_expr(node.orelse, aliases))
    if isinstance(node, ast.Name) and node.id in aliases:
        return True
    return False


def _needs_lock(name) -> bool:
    if not name:
        return False
    return name.endswith("_locked") or name.startswith(LOCKED_PREFIXES)


def check_lock(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        holder = _needs_lock(fn.name)
        aliases = {
            t.id
            for stmt in _walk_skip_defs(fn)
            if isinstance(stmt, ast.Assign)
            and _is_lock_expr(stmt.value, set())
            for t in stmt.targets if isinstance(t, ast.Name)
        }

        # flag calls attached to each statement's own expressions;
        # compound bodies recurse with the updated lock state
        def visit2(stmts, inlock, fn=fn, aliases=aliases, holder=holder):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue      # nested defs get their own analysis
                here = inlock
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    if any(_is_lock_expr(i.context_expr, aliases)
                           for i in stmt.items):
                        here = True
                # expressions attached directly to this statement
                # (excluding nested statement bodies)
                exprs: List[ast.AST] = []
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody",
                                 "handlers"):
                        continue
                    if isinstance(value, ast.AST):
                        exprs.append(value)
                    elif isinstance(value, list):
                        exprs.extend(v for v in value
                                     if isinstance(v, ast.AST))
                if not (holder or here):
                    for e in exprs:
                        for n in [e, *_walk_skip_defs(e)]:
                            if (isinstance(n, ast.Call)
                                    and _needs_lock(_callee_name(n))):
                                out.append((
                                    path, n.lineno, "lock",
                                    f"{_callee_name(n)}() called outside "
                                    "a lock scope (hold the store lock "
                                    "or be *_locked yourself)"))
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit2(sub, here)
                for h in getattr(stmt, "handlers", ()):
                    visit2(h.body, here)

        visit2(fn.body, False)
    return out
