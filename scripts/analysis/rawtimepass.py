"""Pass `rawtime` — injected-timebase discipline (nomad_tpu/core/,
chaos/, scheduler/, state/, api/).

A raw `time.time()` / `time.monotonic()` / `time.sleep()` call in the
cluster plane bypasses the chaos Clock seam (chaos/clock.py), so a
virtual-time soak silently mixes wall and virtual timelines —
heartbeat TTLs fire early, SLO windows span the wrong samples, and the
same seed stops replaying.  Route through `self.clock` / a module-level
bound Clock instead (`time.perf_counter()` stays legal: host-side
duration measurement is not cluster time).

The alias table is hoisted over the WHOLE module before any call is
checked, so both re-import shapes are caught no matter where the import
statement sits (module top or nested inside a function body):

  - `from time import time as _t` / `from time import monotonic` —
    from-import aliases of the banned callables
  - `import time as _clock` — a module alias; `_clock.time()` is the
    same raw call wearing a different root name
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from common import Finding

# cluster-plane time must flow through the injected chaos Clock; these
# raw calls each pin a timeline to the wall clock.  perf_counter is
# deliberately absent: host-side duration measurement (wavepipe stage
# timers) is not cluster time and stays legal.
_RAWTIME_BANNED = ("time", "monotonic", "sleep")


def check_rawtime(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    # hoisted alias tables: one ast.walk sees every import statement in
    # the module, INCLUDING ones nested in function bodies (a lazy
    # `import time as _t` inside a helper is the shape the pre-package
    # pass missed — its call check only matched the literal root name
    # `time`)
    from_imports: Dict[str, str] = {}    # local name -> banned callable
    mod_aliases: Set[str] = {"time"}     # names bound to the time module
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            for a in n.names:
                if a.name in _RAWTIME_BANNED:
                    from_imports[a.asname or a.name] = a.name
        elif isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or a.name)
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        fn = n.func
        banned = ""
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mod_aliases
                and fn.attr in _RAWTIME_BANNED):
            banned = fn.attr
        elif isinstance(fn, ast.Name) and fn.id in from_imports:
            banned = from_imports[fn.id]
        if banned:
            out.append((path, n.lineno, "rawtime",
                        f"raw `time.{banned}()` bypasses the injected "
                        "Clock — a virtual-time soak mixes wall and "
                        "virtual timelines; route through the bound "
                        "chaos Clock (clock.time()/monotonic()/sleep())"))
    return out
