"""Pass `wireproto` — codec / RPC drift detection.

Three sub-checks:

  1. RPC op-table parity (core/workerpool.py): every op name a child
     channel sends (`chan.call("op", ...)` / `chan.notify("op", ...)`)
     must have a matching `if op == "op":` arm in the parent dispatch,
     and every dispatch arm must have at least one sender — a dead arm
     is a renamed/removed op waiting to desync a mixed build.
  2. Payload-key drift: for each op whose send sites build a dict
     literal, every key the handler reads STRICTLY (`payload["k"]`,
     following one level into `self._handle_*` helpers) must be
     provided by some send site.  `.get("k")` reads are tolerant by
     contract and exempt.
  3. Wire-struct manifest: the field set of every dataclass that rides
     the wire codec (nomad_tpu.structs + ops/engine — the modules
     `register_module` feeds) is pinned in
     scripts/analysis/wire_manifest.json.  Field drift without
     regenerating the manifest fails; regeneration bumps the manifest
     version, which must then match `SCHEMA_VERSION` in core/wire.py —
     so a field-set change cannot land without a frame version bump.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from common import Finding, _dotted, _functions


# ------------------------------------------------- RPC table parity

def _dispatch_funcs(tree: ast.Module):
    """Functions that dispatch on an `op` parameter."""
    for fn in _functions(tree):
        args = [a.arg for a in fn.args.args]
        if "op" in args:
            yield fn, args


def _op_arms(fn: ast.AST) -> List[Tuple[str, ast.If]]:
    """(op literal, If node) for every `op == "lit"` compare arm."""
    arms = []
    for n in ast.walk(fn):
        if not isinstance(n, ast.If):
            continue
        tests = [n.test]
        if isinstance(n.test, ast.BoolOp):
            tests = list(n.test.values)
        for t in tests:
            if not (isinstance(t, ast.Compare)
                    and isinstance(t.left, ast.Name)
                    and t.left.id == "op"
                    and len(t.ops) == 1):
                continue
            cmp = t.comparators[0]
            if (isinstance(t.ops[0], ast.Eq)
                    and isinstance(cmp, ast.Constant)
                    and isinstance(cmp.value, str)):
                arms.append((cmp.value, n))
            elif (isinstance(t.ops[0], ast.In)
                    and isinstance(cmp, (ast.Tuple, ast.List, ast.Set))):
                # `op in ("ready", "pull"):` — one arm per member
                for el in cmp.elts:
                    if (isinstance(el, ast.Constant)
                            and isinstance(el.value, str)):
                        arms.append((el.value, n))
    return arms


def _send_sites(tree: ast.Module) -> List[Tuple[str, ast.Call]]:
    """(op literal, call node) for chan.call / chan.notify sends."""
    sites = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("call", "notify")):
            continue
        recv = (_dotted(f.value) or "").lower()
        if "chan" not in recv:
            continue
        if n.args and isinstance(n.args[0], ast.Constant) \
                and isinstance(n.args[0].value, str):
            sites.append((n.args[0].value, n))
    return sites


def _strict_payload_reads(body: List[ast.AST], payload_name: str,
                          tree: ast.Module, funcs: Dict[str, ast.AST],
                          depth: int = 0) -> List[Tuple[str, int]]:
    """Keys read as `payload["k"]` in an arm body, following one level
    into `self._handle_*(…, payload)` helper calls."""
    reads: List[Tuple[str, int]] = []
    for stmt in body:
        for n in ast.walk(stmt):
            if (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == payload_name
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)):
                reads.append((n.slice.value, n.lineno))
            if depth == 0 and isinstance(n, ast.Call):
                cn = None
                if isinstance(n.func, ast.Attribute):
                    cn = n.func.attr
                elif isinstance(n.func, ast.Name):
                    cn = n.func.id
                helper = funcs.get(cn or "")
                if helper is None:
                    continue
                # position of the forwarded payload among the args
                for i, a in enumerate(n.args):
                    if (isinstance(a, ast.Name)
                            and a.id == payload_name):
                        params = [p.arg for p in helper.args.args]
                        if params and params[0] == "self":
                            params = params[1:]
                        if i < len(params):
                            reads.extend(_strict_payload_reads(
                                helper.body, params[i], tree, funcs,
                                depth=1))
    return reads


def _check_rpc(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    funcs = {f.name: f for f in _functions(tree)}
    handled: Dict[str, Tuple[ast.AST, List[ast.AST], str]] = {}
    for fn, args in _dispatch_funcs(tree):
        payload_name = "payload" if "payload" in args else ""
        for op, arm in _op_arms(fn):
            prev = handled.get(op)
            body = list(arm.body)
            if prev is not None:
                prev[1].extend(body)
            else:
                handled[op] = (arm, body, payload_name)
    sites = _send_sites(tree)
    if not handled and not sites:
        return out
    sent_ops: Dict[str, List[ast.Call]] = {}
    for op, call in sites:
        sent_ops.setdefault(op, []).append(call)

    for op, calls in sorted(sent_ops.items()):
        if op not in handled:
            out.append((path, calls[0].lineno, "wireproto",
                        f"RPC op {op!r} is sent but has no dispatch "
                        "arm — the receiver will reject or drop it"))
    for op, (arm, _, _) in sorted(handled.items()):
        if op not in sent_ops:
            out.append((path, arm.lineno, "wireproto",
                        f"RPC dispatch arm for op {op!r} has no send "
                        "site — dead handler or renamed sender"))

    # payload-key drift (only for ops with at least one dict-literal
    # send — a variable payload is opaque to static analysis)
    for op, calls in sorted(sent_ops.items()):
        info = handled.get(op)
        if info is None or not info[2]:
            continue
        arm, body, payload_name = info
        sent_keys: Set[str] = set()
        opaque = True
        for c in calls:
            if len(c.args) < 2:
                continue
            d = c.args[1]
            if isinstance(d, ast.Dict):
                opaque = False
                for k in d.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        sent_keys.add(k.value)
                    else:
                        opaque = True   # **spread / computed key
            else:
                opaque = True
        if opaque:
            continue
        for key, lineno in _strict_payload_reads(body, payload_name,
                                                 tree, funcs):
            if key not in sent_keys:
                out.append((path, lineno, "wireproto",
                            f"handler for op {op!r} reads "
                            f"payload[{key!r}] but no send site "
                            "provides that key — KeyError on the "
                            "attendant thread at runtime"))
    return out


# ---------------------------------------------- wire-struct manifest

def _dataclass_fields(tree: ast.Module) -> Dict[str, List[str]]:
    """ClassName -> sorted field names for every @dataclass in the
    module (annotated class-level assignments; ClassVar excluded)."""
    structs: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        is_dc = False
        for d in node.decorator_list:
            name = None
            if isinstance(d, ast.Name):
                name = d.id
            elif isinstance(d, ast.Attribute):
                name = d.attr
            elif isinstance(d, ast.Call):
                f = d.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
            if name == "dataclass":
                is_dc = True
        if not is_dc:
            continue
        fields: List[str] = []
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                ann = ast.dump(stmt.annotation)
                if "ClassVar" in ann:
                    continue
                fields.append(stmt.target.id)
        structs[node.name] = sorted(fields)
    return structs


def compute_struct_manifest(struct_files: Dict[str, ast.Module],
                            version: int) -> dict:
    structs: Dict[str, List[str]] = {}
    for _, tree in sorted(struct_files.items()):
        for name, fields in _dataclass_fields(tree).items():
            structs.setdefault(name, fields)
    return {"schema_version": version,
            "structs": {k: structs[k] for k in sorted(structs)}}


def wire_schema_version(wire_tree: ast.Module) -> Tuple[int, int]:
    """(value, lineno) of `SCHEMA_VERSION = <int>` in core/wire.py, or
    (0, 0) when absent."""
    for node in wire_tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "SCHEMA_VERSION"
                        for t in node.targets)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            return node.value.value, node.lineno
    return 0, 0


def check_manifest(struct_files: Dict[str, ast.Module],
                   manifest: Optional[dict],
                   wire_tree: Optional[ast.Module],
                   wire_path: str,
                   manifest_path: str) -> List[Finding]:
    out: List[Finding] = []
    if manifest is None:
        anchor = sorted(struct_files)[0] if struct_files else wire_path
        out.append((anchor, 1, "wireproto",
                    f"wire-struct manifest missing at {manifest_path} "
                    "— run analyze.py --update-manifest"))
        return out
    pinned = manifest.get("structs", {})
    # class def line index for anchoring drift findings
    def_lines: Dict[str, Tuple[str, int]] = {}
    live: Dict[str, List[str]] = {}
    for path, tree in sorted(struct_files.items()):
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                def_lines.setdefault(node.name, (path, node.lineno))
        for name, fields in _dataclass_fields(tree).items():
            live.setdefault(name, fields)
    drift = False
    for name in sorted(set(pinned) | set(live)):
        if name not in live:
            anchor = sorted(struct_files)[0]
            out.append((anchor, 1, "wireproto",
                        f"wire struct {name!r} pinned in the manifest "
                        "no longer exists — run --update-manifest "
                        "(and bump SCHEMA_VERSION in core/wire.py)"))
            drift = True
        elif name not in pinned:
            path, lineno = def_lines[name]
            out.append((path, lineno, "wireproto",
                        f"wire struct {name!r} is not pinned in the "
                        "manifest — run --update-manifest (and bump "
                        "SCHEMA_VERSION in core/wire.py)"))
            drift = True
        elif sorted(pinned[name]) != live[name]:
            path, lineno = def_lines[name]
            added = sorted(set(live[name]) - set(pinned[name]))
            gone = sorted(set(pinned[name]) - set(live[name]))
            out.append((path, lineno, "wireproto",
                        f"wire struct {name!r} field set drifted from "
                        f"the manifest (added={added} removed={gone}) "
                        "— run --update-manifest and bump "
                        "SCHEMA_VERSION in core/wire.py"))
            drift = True
    if wire_tree is not None and not drift:
        ver, lineno = wire_schema_version(wire_tree)
        pin_ver = int(manifest.get("schema_version", 0))
        if ver != pin_ver:
            out.append((wire_path, lineno or 1, "wireproto",
                        f"manifest schema_version={pin_ver} but "
                        f"core/wire.py SCHEMA_VERSION={ver} — the "
                        "struct field sets changed without a frame "
                        "version bump (set them equal)"))
    return out


def check_wireproto(files: Dict[str, ast.Module],
                    struct_files: Optional[Dict[str, ast.Module]] = None,
                    manifest: Optional[dict] = None,
                    wire_tree: Optional[ast.Module] = None,
                    wire_path: str = "",
                    manifest_path: str = "") -> List[Finding]:
    out: List[Finding] = []
    for path in sorted(files):
        out.extend(_check_rpc(files[path], path))
    if struct_files is not None:
        out.extend(check_manifest(struct_files, manifest, wire_tree,
                                  wire_path, manifest_path))
    return out
