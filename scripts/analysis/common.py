"""Shared AST plumbing for the invariant-analyzer passes.

Every pass module imports from here: the `Finding` record shape, the
walk helpers that respect nested-def boundaries, and the suppression
matcher (`# analyze: ok <pass>` / `# analyze: ok *` on a finding's
line).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

ROOT = Path(__file__).resolve().parent.parent.parent

Finding = Tuple[str, int, str, str]        # (path, lineno, pass, message)

PASS_NAMES = ("lock", "cow", "purity", "thread", "rawtime",
              "lockorder", "determinism", "wireproto", "obsbus")


def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    (their bodies run in a different dynamic context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _functions(tree: ast.Module):
    """Every function/method def in the module (flat)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _self_attr(node: ast.AST) -> Optional[str]:
    """The first attribute name hanging off `self` in an access chain
    (`self._allocs[k].x.pop` -> '_allocs'), or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.func if isinstance(node, ast.Call) else node.value
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """The root Name of an access chain (`vol.read_allocs.pop` -> 'vol'),
    or None when the chain roots elsewhere (a call result, self, ...)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted path of a pure Name/Attribute chain ('inp.used0'), else
    None (subscripts and calls are not stable paths)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _suppressed(text_lines: List[str], lineno: int, pass_name: str
                ) -> bool:
    if not (1 <= lineno <= len(text_lines)):
        return False
    line = text_lines[lineno - 1]
    return (f"analyze: ok {pass_name}" in line
            or "analyze: ok *" in line)
