"""Pass `thread` — thread hygiene.

A `threading.Thread(target=...)` target (or a raft `on_leader=` /
`on_follower=` callback, which runs on a daemon thread) without
top-level exception handling dies silently — a leadership callback that
dies on `NotLeaderError` is how state desync starts.  The same rule
covers `multiprocessing.Process(target=...)` (core/workerpool
children): the target needs a top-level handler (an unhandled exception
is only a one-line stderr trace in another process), and the Process
needs a `name=` — unnamed workers are invisible in ps output and crash
triage.
"""

from __future__ import annotations

import ast
from typing import List, Set

from common import Finding, _callee_name, _functions


def _has_toplevel_handler(fn: ast.AST) -> bool:
    """True when the function body protects its thread: a try/except at
    body level, or directly inside While/For/With wrappers (a loop-body
    try = per-iteration protection)."""
    def scan(stmts, depth: int) -> bool:
        for s in stmts:
            if isinstance(s, ast.Try) and s.handlers:
                return True
            if (isinstance(s, (ast.While, ast.For, ast.With,
                               ast.AsyncWith, ast.AsyncFor))
                    and depth < 3 and scan(s.body, depth + 1)):
                return True
        return False
    return scan(fn.body, 0)


def check_thread(tree: ast.Module, path: str) -> List[Finding]:
    funcs = {f.name: f for f in _functions(tree)}
    out: List[Finding] = []
    seen: Set[int] = set()

    def resolve(expr: ast.AST):
        if isinstance(expr, ast.Name):
            return funcs.get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return funcs.get(expr.attr)
        return None

    def require(expr: ast.AST, kind: str) -> None:
        target = resolve(expr)
        if target is None or id(target) in seen:
            return
        seen.add(id(target))
        if not _has_toplevel_handler(target):
            out.append((path, target.lineno, "thread",
                        f"{kind} `{target.name}` has no top-level "
                        "exception handling — an unhandled exception "
                        "kills the daemon thread silently"))

    def chaos_managed(call: ast.Call) -> bool:
        """Thread(..., name="chaos-...") wrappers are scenario-managed:
        the chaos runner joins them with a timeout and surfaces failure
        through failed_ops / the convergence verdict, so "dies silently"
        does not apply — the death IS observed."""
        for kw in call.keywords:
            if kw.arg != "name":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return v.value.startswith("chaos-")
            if isinstance(v, ast.JoinedStr) and v.values:
                head = v.values[0]
                return (isinstance(head, ast.Constant)
                        and isinstance(head.value, str)
                        and head.value.startswith("chaos-"))
        return False

    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        cn = _callee_name(n)
        if cn == "Thread" and not chaos_managed(n):
            for kw in n.keywords:
                if kw.arg == "target":
                    require(kw.value, "thread target")
        if cn == "Process":
            if not any(kw.arg == "name" for kw in n.keywords):
                out.append((path, n.lineno, "thread",
                            "Process(...) without a name= — unnamed "
                            "worker processes are invisible in ps "
                            "output and crash triage"))
            for kw in n.keywords:
                if kw.arg == "target":
                    require(kw.value, "process target")
        for kw in n.keywords:
            if kw.arg in ("on_leader", "on_follower"):
                require(kw.value, f"daemon callback ({kw.arg}=)")
    return out
