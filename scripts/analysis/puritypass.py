"""Pass `purity` — JAX purity & donation (ops/, parallel/,
core/wavepipe.py).

Host-sync calls (`block_until_ready`, host `np.*`, `float()` / `bool()`
on traced values, `.item()`) inside jit-traced code break async
dispatch; heavy `jnp` compute in non-jit host paths pays per-op
dispatch in the hot loop; and a buffer passed at a `donate_argnums`
position is DEAD after the call — XLA reuses its memory, so any later
read of the same expression reads garbage.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Set, Tuple

from common import (Finding, _callee_name, _dotted, _functions,
                    _root_name, _walk_skip_defs)

HEAVY_JNP = {"where", "sum", "argsort", "sort", "argmax", "argmin",
             "cumsum", "dot", "matmul", "einsum", "take_along_axis",
             "top_k", "mean", "prod", "nonzero", "unique"}

NP_ALIASES = {"np", "numpy"}
JNP_ALIASES = {"jnp"}


# transforms that TRACE the function they wrap: a Name passed to one of
# these runs under jit/trace semantics, not eagerly on the host
TRACE_WRAPPERS = {"jit", "shard_map", "vmap", "pmap", "scan",
                  "fori_loop", "while_loop", "cond", "remat",
                  "checkpoint", "grad", "value_and_grad"}


def _jit_call(node: ast.AST) -> bool:
    """A call to jax.jit / jit."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    return False


def _trace_wrapper_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _callee_name(node)
    return name in TRACE_WRAPPERS


class _ModuleInfo:
    __slots__ = ("path", "tree", "funcs", "imports", "jit_seeds",
                 "jit_lambdas", "donated")

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        # name -> ALL defs carrying it (mesh.py's jit factories each
        # define a local `f`; a plain dict would keep only one)
        self.funcs: Dict[str, List[ast.AST]] = {}
        for f in _functions(tree):
            self.funcs.setdefault(f.name, []).append(f)
        # local name -> (module stem, source name) for from-imports
        self.imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                stem = node.module.split(".")[-1]
                for a in node.names:
                    if a.name != "*":
                        self.imports[a.asname or a.name] = (stem, a.name)
        self.jit_seeds: Set[str] = set()
        self.jit_lambdas: List[ast.Lambda] = []
        # jitted-callable local name -> donated positional indexes
        self.donated: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if _trace_wrapper_call(node):
                # every Name reachable in the wrapper's args is traced —
                # covers partial(_kernel, ...) indirection too
                for a in node.args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name):
                            self.jit_seeds.add(sub.id)
                        elif isinstance(sub, ast.Lambda):
                            self.jit_lambdas.append(sub)
            if isinstance(node, ast.FunctionDef):
                for d in node.decorator_list:
                    if _jit_call(d) or (
                            isinstance(d, ast.Attribute)
                            and d.attr == "jit") or (
                            isinstance(d, ast.Name) and d.id == "jit"):
                        self.jit_seeds.add(node.name)
            # NAME = jax.jit(fn, donate_argnums=(k,...))
            if isinstance(node, ast.Assign) and _jit_call(node.value):
                dons: Tuple[int, ...] = ()
                for kw in node.value.keywords:
                    if kw.arg == "donate_argnums":
                        vals = []
                        for e in ast.walk(kw.value):
                            if (isinstance(e, ast.Constant)
                                    and isinstance(e.value, int)):
                                vals.append(e.value)
                        dons = tuple(vals)
                if dons:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.donated[t.id] = dons


def _purity_traced_defs(mods: Dict[str, _ModuleInfo]) -> Set[int]:
    """id()s of every function def reachable from a jax.jit seed —
    through any NAME REFERENCE inside traced code, not just direct
    calls: `jax.lax.scan(step, ...)` traces `step` without calling it by
    name, and a helper imported from a sibling kernel module is traced
    when a traced function references it.  Defs nested inside a traced
    def only ever run under trace and count too.  Over-approximation is
    deliberate: marking a host helper traced can only silence the eager
    host-path heuristic, never invent a finding."""
    traced: Set[int] = set()
    work: List[Tuple[str, ast.AST]] = []

    def mark(stem: str, fn: ast.AST) -> None:
        if id(fn) in traced:
            return
        traced.add(id(fn))
        work.append((stem, fn))
        for sub in ast.walk(fn):
            if (sub is not fn
                    and isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))):
                traced.add(id(sub))

    for stem, mi in mods.items():
        for name in mi.jit_seeds:
            for fn in mi.funcs.get(name, ()):
                mark(stem, fn)
    while work:
        stem, fn = work.pop()
        mi = mods[stem]
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)):
                continue
            if n.id in mi.funcs:
                for f2 in mi.funcs[n.id]:
                    mark(stem, f2)
            elif n.id in mi.imports:
                src_stem, src_name = mi.imports[n.id]
                if src_stem in mods:
                    for f2 in mods[src_stem].funcs.get(src_name, ()):
                        mark(src_stem, f2)
    return traced


def _branch_paths(fn: ast.AST) -> Dict[int, Tuple]:
    """id(node) -> tuple of (id(branch stmt), arm) ancestors — two nodes
    whose paths first differ on the same statement with different arms
    can never execute in the same pass (if/else, try/except)."""
    paths: Dict[int, Tuple] = {}

    def go(node: ast.AST, path: Tuple) -> None:
        for field, value in ast.iter_fields(node):
            kids = value if isinstance(value, list) else [value]
            for k in kids:
                if not isinstance(k, ast.AST):
                    continue
                sub = path
                if (isinstance(node, ast.If)
                        and field in ("body", "orelse")):
                    sub = path + ((id(node), field),)
                elif (isinstance(node, ast.Try)
                        and field in ("body", "handlers", "orelse")):
                    sub = path + ((id(node), field),)
                paths[id(k)] = sub
                go(k, sub)

    paths[id(fn)] = ()
    go(fn, ())
    return paths


def _exclusive(p1: Tuple, p2: Tuple) -> bool:
    for e1, e2 in zip(p1, p2):
        if e1 == e2:
            continue
        return e1[0] == e2[0] and e1[1] != e2[1]
    return False


def check_purity(files: Dict[str, ast.Module]) -> List[Finding]:
    mods: Dict[str, _ModuleInfo] = {}
    for path, tree in files.items():
        stem = Path(path).stem
        mods[stem] = _ModuleInfo(path, tree)
    traced = _purity_traced_defs(mods)
    # donated callables visible across the scoped modules by import
    donated_global: Dict[Tuple[str, str], Tuple[int, ...]] = {}
    for stem, mi in mods.items():
        for name, dons in mi.donated.items():
            donated_global[(stem, name)] = dons
    out: List[Finding] = []

    def check_traced_body(body: ast.AST, path: str) -> None:
        for n in ast.walk(body):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if (isinstance(f, ast.Attribute)
                    and _root_name(f) in NP_ALIASES):
                out.append((path, n.lineno, "purity",
                            f"host numpy call np.{f.attr}(...) inside "
                            "jit-traced code (silent device->host sync "
                            "or constant fold)"))
            if (isinstance(f, ast.Attribute)
                    and f.attr in ("item", "tolist")):
                out.append((path, n.lineno, "purity",
                            f".{f.attr}() inside jit-traced code forces "
                            "a host sync"))
            if (isinstance(f, ast.Name) and f.id in ("float", "bool")
                    and n.args
                    and not all(isinstance(a, ast.Constant)
                                for a in n.args)):
                out.append((path, n.lineno, "purity",
                            f"{f.id}() on a traced value forces a host "
                            "sync inside jit"))

    for stem, mi in mods.items():
        path = mi.path
        all_defs = [f for fns in mi.funcs.values() for f in fns]
        # 1. block_until_ready anywhere in the hot-path modules: the
        # pipeline's ONE deliberate sync point lives in collect() and
        # carries a suppression; anything else is a stall in disguise
        for n in ast.walk(mi.tree):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "block_until_ready"):
                out.append((path, n.lineno, "purity",
                            "block_until_ready() in the pipeline hot "
                            "path — host sync defeats async dispatch"))
        # 2. traced-code checks (outermost traced defs only: their walk
        # already covers defs nested inside them)
        nested_in_traced: Set[int] = set()
        for fn in all_defs:
            if id(fn) not in traced:
                continue
            for sub in ast.walk(fn):
                if (sub is not fn
                        and isinstance(sub, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))):
                    nested_in_traced.add(id(sub))
        for fn in all_defs:
            if id(fn) in traced and id(fn) not in nested_in_traced:
                check_traced_body(fn, path)
        for lam in mi.jit_lambdas:
            check_traced_body(lam, path)
        # 3. heavy eager jnp in host (non-traced) functions
        for fn in all_defs:
            if id(fn) in traced:
                continue
            for n in _walk_skip_defs(fn):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in HEAVY_JNP
                        and _root_name(n.func) in JNP_ALIASES):
                    out.append((path, n.lineno, "purity",
                                f"eager jnp.{n.func.attr}(...) in a "
                                "non-jit host path (per-op dispatch in "
                                "the hot loop; move it under jit)"))
        # 4. donated-buffer reuse: a read of the donated expression
        # AFTER the donating call (same execution path only — an
        # exclusive if/elif arm cannot observe the other arm's donation)
        for fn in all_defs:
            calls: List[Tuple[int, str, Tuple]] = []
            paths_by_id = None
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                cn = n.func.id if isinstance(n.func, ast.Name) else None
                if cn is None:
                    continue
                dons = mi.donated.get(cn)
                if dons is None and cn in mi.imports:
                    dons = donated_global.get(mi.imports[cn])
                if not dons:
                    continue
                if paths_by_id is None:
                    paths_by_id = _branch_paths(fn)
                for k in dons:
                    if k < len(n.args):
                        p = _dotted(n.args[k])
                        if p:
                            end = getattr(n, "end_lineno", n.lineno)
                            calls.append((end, p,
                                          paths_by_id.get(id(n), ())))
            if not calls:
                continue
            loads: List[Tuple[int, str, Tuple]] = []
            stores: List[Tuple[int, str]] = []
            for n in ast.walk(fn):
                p = None
                if isinstance(n, (ast.Name, ast.Attribute)):
                    p = _dotted(n)
                if p is None:
                    continue
                if isinstance(n.ctx, ast.Load):
                    loads.append((n.lineno, p,
                                  paths_by_id.get(id(n), ())))
                elif isinstance(n.ctx, ast.Store):
                    stores.append((n.lineno, p))
            for call_end, pth, cpath in calls:
                for ln, p, lpath in loads:
                    if p != pth or ln <= call_end:
                        continue
                    if _exclusive(cpath, lpath):
                        continue
                    rebound = any(call_end < s_ln <= ln and s_p == pth
                                  for s_ln, s_p in stores)
                    if not rebound:
                        out.append((path, ln, "purity",
                                    f"`{pth}` read after being DONATED "
                                    f"to a chained dispatch on line "
                                    f"{call_end} — the buffer is dead "
                                    "(XLA reuses its memory)"))
    return out
