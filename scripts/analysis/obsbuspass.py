"""Pass `obsbus` — observability planes must register on the ObsBus
(nomad_tpu/core/).

A core module that defines a module-level `configure(...)` seam is an
observability plane by convention (telemetry, flightrec, timeline,
logging, identity, memledger, profiling all follow it).  Before the bus
(core/obsbus.py), every such plane needed a hand-written call in
`Server.__init__` AND the soak's `_rebind_clock` — and a forgotten call
meant a plane silently stuck on the wall clock while the rest of the
process ran virtual time.  The bus replaces the call litany with
import-time registration; this pass closes the loop by flagging any
core module that defines `configure()` without a matching
`OBSBUS.register(...)` call, so a NEW plane cannot ship half-wired.

Matching is name-based on the call chain: any call whose dotted path
ends in `.register` rooted at a name containing `OBSBUS`/`obsbus`
counts (covers `OBSBUS.register(...)`, `obsbus.OBSBUS.register(...)`,
and a locally aliased bus).  `core/obsbus.py` itself is exempt — the
bus is the seam, not a plane.
"""

from __future__ import annotations

import ast
from typing import List

from common import Finding, _dotted


def _registers_on_bus(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        dotted = _dotted(n.func)
        if not dotted or not dotted.endswith(".register"):
            continue
        root = dotted.split(".", 1)[0]
        if "obsbus" in root.lower():
            return True
    return False


def check_obsbus(tree: ast.Module, path: str) -> List[Finding]:
    if path.replace("\\", "/").endswith("core/obsbus.py"):
        return []
    configure_def = None
    for n in tree.body:                    # module level only
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == "configure":
            configure_def = n
            break
    if configure_def is None:
        return []
    if _registers_on_bus(tree):
        return []
    return [(path, configure_def.lineno, "obsbus",
             "module-level `configure()` marks an observability plane, "
             "but the module never calls `OBSBUS.register(...)` — the "
             "ObsBus clock rebind and debug capture will skip it; "
             "register (name, configure, snapshot, reset) hooks at "
             "module bottom (see core/obsbus.py)")]
