"""Injected-violation fixtures for every analyzer pass.

Each fixture plants known violations next to clean shapes; `selftest()`
asserts the exact finding count and a marker substring per pass, plus
that a `# analyze: ok <pass>` annotation silences its line.  CI runs
this before the repo-wide sweep so a broken pass can never silently
pass the tree.
"""

from __future__ import annotations

SELFTEST_LOCK = '''
class StateStore:
    def upsert_thing(self, x):
        with self._lock:
            self._insert_thing_locked(x)      # ok: under the lock

    def _merge_locked(self, x):
        self._insert_thing_locked(x)          # ok: *_locked caller

    def broken_entry(self, x):
        self._insert_thing_locked(x)          # VIOLATION: no lock

    def broken_helper(self, key):
        vol = self._writable_claim_vol(key)   # VIOLATION: no lock
        return vol


class MetricsRegistry:
    # the telemetry registry's locked paths (core/telemetry.py): the
    # histogram mutator is *_locked and every caller must hold the
    # registry lock — a bare call is exactly the unsynchronized
    # stats-dict increment this PR removed from broker/worker
    def observe(self, key, value):
        with self._lock:
            self._observe_locked(key, value)  # ok: under the lock

    def broken_observe(self, key, value):
        self._observe_locked(key, value)      # VIOLATION: no lock
'''

SELFTEST_COW = '''
class StateStore:
    def _materialize_block_locked(self, block):
        key = (block.namespace, block.source)
        vol = self._csi_volumes.get(key)          # snapshot-shared
        if vol is None or block.id not in vol.read_blocks:
            return
        vol.read_blocks.pop(block.id, None)       # VIOLATION (the leak)
        vol.read_allocs.update({a: "" for a in block.ids})  # VIOLATION

    def _claim_ok_locked(self, key, alloc):
        vol = self._writable_claim_vol(key)       # head-private copy
        if vol is None:
            return
        vol.read_allocs[alloc.id] = alloc.node_id  # ok: blessed

    def delete_thing(self, key):
        self._csi_volumes.pop(key, None)          # VIOLATION: direct

    def _release_claims_locked(self, key, aid):
        import dataclasses
        vol = self._csi_volumes.get(key)
        v = dataclasses.replace(vol)              # shallow: dicts shared
        v.modify_index = 7                        # ok: fresh outer object
        v.read_allocs.pop(aid, None)              # VIOLATION: inner dict

    def snapshot_restore(self, doc):
        self._csi_volumes = {}
        self._csi_volumes[("ns", "v")] = doc      # ok: fresh rebind
'''

SELFTEST_PURITY = '''
import jax
import jax.numpy as jnp
import numpy as np


def kernel(used, cap):
    free = cap - used
    total = np.asarray(free)                  # VIOLATION: np inside jit
    return jnp.sum(free) + float(total.sum())  # VIOLATION: float(traced)


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    best = jnp.argmax(out)                    # VIOLATION: eager jnp
    stale = used + 1                          # VIOLATION: donated reuse
    return best, stale


def collect(buf):
    buf.block_until_ready()                   # VIOLATION: host sync
    return buf
'''

SELFTEST_THREAD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        self.establish_leadership()           # VIOLATION: dies silently

    def _guarded_loop(self):
        while True:
            try:
                self.tick()
            except Exception:
                pass

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
        threading.Thread(target=self._guarded_loop).start()   # ok

    def run_scenario(self):
        # ok: chaos-managed wrapper (runner joins it and surfaces the
        # death via failed_ops), recognized by its name= prefix
        threading.Thread(target=self._workload_loop, daemon=True,
                         name=f"chaos-workload-{self.name}").start()

    def _workload_loop(self):
        self.drive()                          # no handler, but managed
'''

SELFTEST_PROC = '''
import multiprocessing as mp


def pool_main(idx):
    run(idx)                                  # VIOLATION: no handler


def pool_main_ok(idx):
    try:
        run(idx)
    except Exception:
        pass


class Pool:
    def spawn(self, ctx):
        ctx.Process(target=pool_main).start()         # VIOLATION: unnamed
        p = mp.Process(target=pool_main_ok,
                       name="pool-worker-0")          # ok: named + handled
        p.start()
'''

SELFTEST_RAWTIME = '''
import time
from time import monotonic as mono


class HeartbeatTimers:
    def expire(self, now=None):
        t = now if now is not None else time.time()   # VIOLATION
        return t

    def backoff(self):
        time.sleep(0.25)                              # VIOLATION

    def deadline(self):
        return mono() + 30.0                          # VIOLATION: alias

    def lazy_from_alias(self):
        from time import time as _t
        return _t()                  # VIOLATION: nested from-import alias

    def lazy_mod_alias(self):
        import time as _clock
        return _clock.time()         # VIOLATION: nested module alias

    def ok_paths(self):
        start = time.perf_counter()                   # ok: host duration
        t = self.clock.time()                         # ok: injected seam
        self.clock.sleep(0.1)                         # ok: injected seam
        return start, t
'''

SELFTEST_LOCKORDER = '''
import threading


class Alpha:
    def __init__(self, beta):
        self._lock = threading.Lock()
        self.beta = beta

    def enter_alpha(self):
        with self._lock:
            return 1

    def step(self):
        with self._lock:
            # VIOLATION x2: closes the 3-lock cycle AND transitively
            # re-enters Alpha._lock (non-reentrant) via the chain
            self.beta.enter_beta()


class Beta:
    def __init__(self, gamma):
        self._lock = threading.Lock()
        self.gamma = gamma

    def enter_beta(self):
        with self._lock:
            self.gamma.enter_gamma()          # edge Beta -> Gamma


class Gamma:
    def __init__(self, alpha):
        self._lock = threading.Lock()
        self.alpha = alpha

    def enter_gamma(self):
        with self._lock:
            self.alpha.enter_alpha()          # edge Gamma -> Alpha


class Sender:
    def __init__(self, conn):
        self._lock = threading.Lock()
        self._conn = conn

    def send_under_lock(self, buf):
        with self._lock:
            self._conn.send_bytes(buf)        # VIOLATION: blocks held

    def send_clean(self, buf):
        with self._lock:
            payload = self._pack(buf)
        self._conn.send_bytes(payload)        # ok: lock released first
'''

SELFTEST_LOCKORDER_CLEAN = '''
import threading


class Ordered:
    def __init__(self, store):
        self._lock = threading.Lock()
        self.store = store

    def step(self):
        with self._lock:
            self.compute_step()               # ok: A -> B, one direction

    def compute_step(self):
        return 1


class Waiter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []

    def dequeue(self, timeout):
        with self._cv:
            while not self._items:
                self._cv.wait(timeout)        # ok: waits on its OWN lock
            return self._items.pop()
'''

SELFTEST_DETERMINISM = '''
import os
import random


def canonical_trace(events, tags, path):
    order = set(tags)
    for t in order:                           # VIOLATION: set iteration
        events.append(t)
    names = ",".join({e.name for e in events})  # VIOLATION: set join
    jitter = random.random()                  # VIOLATION: global RNG
    events.sort(key=id)                       # VIOLATION: id-keyed sort
    files = os.listdir(path)                  # VIOLATION: fs order
    return names, jitter, files


def canonical_clean(events, tags, path, rng):
    for t in sorted(set(tags)):               # ok: sorted first
        events.append(t)
    jitter = rng.random()                     # ok: explicit instance
    files = sorted(os.listdir(path))          # ok: sorted enumeration
    return jitter, files
'''

SELFTEST_WIREPROTO = '''
class Pool:
    def _handle(self, child, op, payload):
        if op == "deq":
            return self._handle_deq(child, payload)
        if op == "ack":
            return payload["job"]     # VIOLATION: senders provide "id"
        if op == "ghost":                     # VIOLATION: dead arm
            return None
        return None

    def _handle_deq(self, child, payload):
        return payload["n"]                   # ok: senders provide "n"


class Proxy:
    def __init__(self, chan):
        self._chan = chan

    def deq(self):
        return self._chan.call("deq", {"n": 4})

    def ack(self):
        return self._chan.call("ack", {"id": 7})

    def drop(self):
        self._chan.notify("orphan", {})       # VIOLATION: no arm
'''

SELFTEST_WIREPROTO_CLEAN = '''
class Pool:
    def _handle(self, child, op, payload):
        if op == "deq":
            return self._handle_deq(child, payload)
        if op == "ack":
            return payload.get("job")         # ok: tolerant read
        return None

    def _handle_deq(self, child, payload):
        return payload["n"]


class Proxy:
    def __init__(self, chan):
        self._chan = chan

    def deq(self):
        return self._chan.call("deq", {"n": 4})

    def ack(self):
        return self._chan.call("ack", {"id": 7})
'''


SELFTEST_OBSBUS = '''
from nomad_tpu.chaos.clock import Clock, SystemClock

_CLOCK = SystemClock()


def configure(clock):                         # VIOLATION: unregistered
    global _CLOCK
    _CLOCK = clock


def snapshot():
    return {"clock": type(_CLOCK).__name__}
'''

SELFTEST_OBSBUS_CLEAN = '''
from nomad_tpu.chaos.clock import Clock, SystemClock
from nomad_tpu.core.obsbus import OBSBUS

_CLOCK = SystemClock()


def configure(clock):
    global _CLOCK
    _CLOCK = clock


OBSBUS.register("fixture", configure=configure)
'''


def selftest() -> int:
    from driver import analyze_source
    ok = True

    def expect(name: str, text: str, want: int, must_contain: str = ""
               ) -> None:
        nonlocal ok
        got = [f for f in analyze_source(text, passes=(name,))
               if f[2] == name]
        if len(got) != want:
            print(f"analyze selftest FAILED [{name}]: expected {want} "
                  f"finding(s), got {len(got)}: {got}")
            ok = False
            return
        if must_contain and not any(must_contain in f[3] for f in got):
            print(f"analyze selftest FAILED [{name}]: no finding "
                  f"mentions {must_contain!r}: {got}")
            ok = False

    expect("lock", SELFTEST_LOCK, 3, "outside")
    expect("cow", SELFTEST_COW, 4, "_writable_")
    expect("purity", SELFTEST_PURITY, 5, "DONATED")
    expect("thread", SELFTEST_THREAD, 1, "_on_raft_leader")
    expect("thread", SELFTEST_PROC, 2, "name=")
    expect("rawtime", SELFTEST_RAWTIME, 5, "bypasses the injected")
    expect("lockorder", SELFTEST_LOCKORDER, 3, "lock-order cycle")
    expect("lockorder", SELFTEST_LOCKORDER, 3, "blocking call")
    expect("lockorder", SELFTEST_LOCKORDER, 3, "re-acquired")
    expect("lockorder", SELFTEST_LOCKORDER_CLEAN, 0)
    expect("determinism", SELFTEST_DETERMINISM, 5, "unordered set")
    expect("determinism", SELFTEST_DETERMINISM, 5, "filesystem")
    expect("wireproto", SELFTEST_WIREPROTO, 3, "no dispatch")
    expect("wireproto", SELFTEST_WIREPROTO, 3, "no send")
    expect("wireproto", SELFTEST_WIREPROTO_CLEAN, 0)
    expect("obsbus", SELFTEST_OBSBUS, 1, "OBSBUS.register")
    expect("obsbus", SELFTEST_OBSBUS_CLEAN, 0)
    # suppression: the same violations annotated away must go quiet
    suppressed = SELFTEST_THREAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok thread")
    expect("thread", suppressed, 0)
    suppressed_lo = SELFTEST_LOCKORDER.replace(
        "self._conn.send_bytes(buf)        # VIOLATION: blocks held",
        "self._conn.send_bytes(buf)  # analyze: ok lockorder")
    expect("lockorder", suppressed_lo, 2)
    suppressed_ob = SELFTEST_OBSBUS.replace(
        "def configure(clock):                         "
        "# VIOLATION: unregistered",
        "def configure(clock):  # analyze: ok obsbus")
    expect("obsbus", suppressed_ob, 0)
    if ok:
        print("analyze selftest ok: every pass caught its injected "
              "violations (lock=3 cow=4 purity=5 thread=1+2 rawtime=5 "
              "lockorder=3 determinism=5 wireproto=3 obsbus=1, "
              "suppression honored)")
        return 0
    return 1
