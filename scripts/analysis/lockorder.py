"""Pass `lockorder` — inter-procedural lock-acquisition graph.

Builds a whole-program graph over the repo's lock identities (the store
RLock, broker lock, plan-queue lock, submission front-end lock,
`_tick_lock`, the registry/flight/timeline singleton locks, module-level
locks like wire's replay-cache lock — seeded from the lock pass's
LOCK_ATTRS plus `threading.Lock/RLock/Condition` constructor sites) and
reports:

  - lock-order cycles: lock A held while acquiring B somewhere, B held
    while acquiring A somewhere else — a potential deadlock the moment
    two threads interleave (exactly the hazard of admitting N workers'
    plans through one fenced applier pass);
  - blocking-under-lock: a call that can block indefinitely — socket /
    pipe send+recv, `wire` RPC round-trips, `queue.get` / `join`,
    `block_until_ready` / device fetches, subprocess waits, sleeps —
    made while a lock is held, directly or through a resolved callee.

Call resolution is deliberately conservative: `self.m()` resolves inside
the class, other receivers only when the method name is defined by
exactly one class in the analyzed set and is not a generic container /
stdlib name.  `Condition(self._lock)` aliases collapse onto the wrapped
lock, so `with self._cv:` and `with self._lock:` are one graph node.
`cond.wait()` under its OWN lock is the blessed condition-variable
pattern and is exempt; waiting on anything while holding a DIFFERENT
lock is flagged (the wait releases only its own lock).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from common import Finding, _dotted
from lockpass import LOCK_ATTRS

_LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock",
                   "Condition": "Condition", "Semaphore": "Lock",
                   "BoundedSemaphore": "Lock"}

# attribute calls that block regardless of receiver
_BLOCKING_ATTRS = {"send_bytes", "recv_bytes", "sendall", "accept",
                   "connect", "communicate", "block_until_ready",
                   "device_get", "check_call", "check_output"}

# receiver hints: a `.recv()` on one of these roots is a pipe/socket
_PIPEY = ("conn", "sock", "chan", "pipe")

# method names too generic to resolve across classes (dict.get, list
# mutators, file IO, str ops, lock primitives): resolving them by
# unique definition name would invent edges out of container calls
_SKIP_METHODS = {
    "get", "put", "pop", "add", "remove", "discard", "append",
    "appendleft", "extend", "update", "clear", "copy", "items", "keys",
    "values", "setdefault", "sort", "join", "split", "strip", "close",
    "open", "read", "write", "send", "recv", "encode", "decode", "pack",
    "unpack", "start", "run", "wait", "notify", "notify_all", "acquire",
    "release", "set", "is_set", "cancel", "result", "done", "flush",
    "lower", "upper", "replace", "format", "count", "index", "insert",
    "popitem", "group", "match", "search", "next", "stop",
}


class _Cls:
    __slots__ = ("name", "stem", "lock_attrs", "cond_wraps", "methods")

    def __init__(self, name: str, stem: str):
        self.name = name
        self.stem = stem
        self.lock_attrs: Dict[str, str] = {}    # attr -> kind
        self.cond_wraps: Dict[str, str] = {}    # cv attr -> wrapped attr
        self.methods: Dict[str, ast.AST] = {}

    def canon(self, attr: str) -> str:
        seen = set()
        while attr in self.cond_wraps and attr not in seen:
            seen.add(attr)
            attr = self.cond_wraps[attr]
        return attr

    def node(self, attr: str) -> str:
        return f"{self.name}.{self.canon(attr)}"


class _Fn:
    __slots__ = ("node", "cls", "stem", "path", "acquires", "blocks",
                 "callees", "aliases")

    def __init__(self, node: ast.AST, cls: Optional[_Cls], stem: str,
                 path: str):
        self.node = node
        self.cls = cls
        self.stem = stem
        self.path = path
        self.acquires: Set[str] = set()
        # (description, exempt lock node or "", lineno)
        self.blocks: Set[Tuple[str, str]] = set()
        self.callees: Set[int] = set()
        self.aliases: Dict[str, str] = {}


def _factory_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return _LOCK_FACTORIES.get(name or "")


def check_lockorder(files: Dict[str, ast.Module]) -> List[Finding]:
    # ---------------------------------------------------- harvest
    classes: List[_Cls] = []
    fns: Dict[int, _Fn] = {}
    methods_by_name: Dict[str, List[_Fn]] = {}
    module_funcs: Dict[Tuple[str, str], _Fn] = {}
    module_locks: Dict[str, Dict[str, str]] = {}   # stem -> name -> node

    for path in sorted(files):
        tree = files[path]
        stem = Path(path).stem
        mlocks: Dict[str, str] = {}
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                kind = _factory_kind(stmt.value)
                if kind:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            mlocks[t.id] = f"{stem}.{t.id}"
        module_locks[stem] = mlocks
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = _Fn(stmt, None, stem, path)
                fns[id(stmt)] = f
                module_funcs[(stem, stmt.name)] = f
            elif isinstance(stmt, ast.ClassDef):
                ci = _Cls(stmt.name, stem)
                classes.append(ci)
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ci.methods[sub.name] = sub
                        f = _Fn(sub, ci, stem, path)
                        fns[id(sub)] = f
                        methods_by_name.setdefault(sub.name,
                                                   []).append(f)
                # lock attributes: self.X = threading.Lock()/RLock()/
                # Condition(self._Y) anywhere in the class body
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Assign):
                        continue
                    kind = _factory_kind(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        wrapped = None
                        if kind == "Condition" and sub.value.args:
                            a0 = sub.value.args[0]
                            if (isinstance(a0, ast.Attribute)
                                    and isinstance(a0.value, ast.Name)
                                    and a0.value.id == "self"):
                                wrapped = a0.attr
                        if wrapped:
                            ci.cond_wraps[t.attr] = wrapped
                        else:
                            ci.lock_attrs.setdefault(t.attr, kind)

    kind_of: Dict[str, str] = {}
    for ci in classes:
        for attr, kind in ci.lock_attrs.items():
            kind_of[ci.node(attr)] = kind

    # `.locked()` context accessor: when exactly one analyzed class
    # defines it, any `with obj.locked():` acquires that class's lock
    locked_node = ""
    owners = methods_by_name.get("locked", [])
    if len(owners) == 1 and owners[0].cls is not None:
        locked_node = owners[0].cls.node("_lock")

    def lock_node_of(expr: ast.AST, fn: _Fn) -> str:
        """Lock identity acquired by `with <expr>:`, or ''."""
        if isinstance(expr, ast.Name):
            if expr.id in fn.aliases:
                return fn.aliases[expr.id]
            return module_locks.get(fn.stem, {}).get(expr.id, "")
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and fn.cls is not None):
                attr = expr.attr
                if (attr in fn.cls.lock_attrs
                        or attr in fn.cls.cond_wraps
                        or attr in LOCK_ATTRS):
                    return fn.cls.node(attr)
            return ""
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr == "locked":
                return locked_node
        if isinstance(expr, ast.IfExp):
            return (lock_node_of(expr.body, fn)
                    or lock_node_of(expr.orelse, fn))
        return ""

    def resolve_call(call: ast.Call, fn: _Fn) -> Optional[_Fn]:
        f = call.func
        if isinstance(f, ast.Name):
            g = module_funcs.get((fn.stem, f.id))
            if g is not None:
                return g
            hits = [v for (_, n), v in module_funcs.items() if n == f.id]
            return hits[0] if len(hits) == 1 else None
        if isinstance(f, ast.Attribute):
            name = f.attr
            if (isinstance(f.value, ast.Name) and f.value.id == "self"
                    and fn.cls is not None and name in fn.cls.methods):
                return fns[id(fn.cls.methods[name])]
            if name in _SKIP_METHODS:
                return None
            hits = methods_by_name.get(name, [])
            return hits[0] if len(hits) == 1 else None
        return None

    def blocking_desc(call: ast.Call, fn: _Fn) -> Tuple[str, str]:
        """(description, exempt-lock-node) for a potentially-blocking
        call, or ('', '')."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return "", ""
        a = f.attr
        recv = _dotted(f.value) or ""
        low = recv.lower()
        if a in _BLOCKING_ATTRS:
            return f"{recv or '?'}.{a}()", ""
        if a == "recv" and any(h in low for h in _PIPEY):
            return f"{recv}.recv()", ""
        if a in ("call", "notify") and "chan" in low:
            return f"wire RPC {recv}.{a}()", ""
        if a == "get":
            # match whole queue-ish names only: `self._dequeues.get(k, 0)`
            # is a dict of delivery counters, not a Queue — a substring
            # test on "queue" would flag it
            last = low.rsplit(".", 1)[-1].lstrip("_")
            if (last in ("q", "queue", "logq", "inbox", "subq", "workq")
                    or last.endswith("queue") or last.endswith("_q")):
                return f"{recv}.get()", ""
        if a == "join" and not call.args:
            # thread/process join; str.join always has a positional arg
            return f"{recv or '?'}.join()", ""
        if a == "sleep":
            return f"{recv or '?'}.sleep()", ""
        if a == "wait":
            held = lock_node_of(f.value, fn)
            if held:
                # cond.wait(): releases its OWN lock while waiting —
                # blessed under that lock, a hazard under any other
                return f"{recv}.wait()", held
            return f"{recv or '?'}.wait()", ""
        return "", ""

    # ------------------------------------------- per-function harvest
    for fn in fns.values():
        body = fn.node
        # local lock aliases (lk = self._lock / guard = store.locked())
        for n in ast.walk(body):
            if isinstance(n, ast.Assign):
                tgt_names = [t.id for t in n.targets
                             if isinstance(t, ast.Name)]
                if not tgt_names:
                    continue
                node = lock_node_of(n.value, fn)
                if node:
                    for nm in tgt_names:
                        fn.aliases[nm] = node
        for n in ast.walk(body):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    node = lock_node_of(item.context_expr, fn)
                    if node:
                        fn.acquires.add(node)
            elif isinstance(n, ast.Call):
                desc, exempt = blocking_desc(n, fn)
                if desc:
                    fn.blocks.add((desc, exempt))
                g = resolve_call(n, fn)
                if g is not None and g is not fn:
                    fn.callees.add(id(g.node))

    # ------------------------------------------------------ fixpoint
    acq_all: Dict[int, Set[str]] = {
        fid: set(f.acquires) for fid, f in fns.items()}
    blk_all: Dict[int, Set[Tuple[str, str]]] = {
        fid: set(f.blocks) for fid, f in fns.items()}
    changed = True
    while changed:
        changed = False
        for fid, f in fns.items():
            for cid in f.callees:
                if not acq_all[cid] <= acq_all[fid]:
                    acq_all[fid] |= acq_all[cid]
                    changed = True
                if not blk_all[cid] <= blk_all[fid]:
                    blk_all[fid] |= blk_all[cid]
                    changed = True

    # ------------------------- lexical walk: edges + blocking findings
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    out: List[Finding] = []
    reported_blocks: Set[Tuple[str, int, str]] = set()

    def note_edge(h: str, a: str, path: str, lineno: int,
                  via: str) -> None:
        edges.setdefault((h, a), (path, lineno, via))

    def check_call(call: ast.Call, held: List[str], fn: _Fn) -> None:
        desc, exempt = blocking_desc(call, fn)
        if desc:
            bad = sorted(h for h in held if h != exempt)
            if bad:
                key = (fn.path, call.lineno, desc)
                if key not in reported_blocks:
                    reported_blocks.add(key)
                    out.append((fn.path, call.lineno, "lockorder",
                                f"blocking call {desc} while holding "
                                f"lock {bad[0]} — the lock is pinned "
                                "for the full stall"))
        g = resolve_call(call, fn)
        if g is None or not held:
            return
        gid = id(g.node)
        gname = g.node.name
        for a in acq_all.get(gid, ()):
            for h in held:
                note_edge(h, a, fn.path, call.lineno,
                          f"via {gname}()")
        for bdesc, bexempt in blk_all.get(gid, ()):
            bad = sorted(h for h in held if h != bexempt)
            if bad:
                key = (fn.path, call.lineno, bdesc)
                if key not in reported_blocks:
                    reported_blocks.add(key)
                    out.append((fn.path, call.lineno, "lockorder",
                                f"call into {gname}() may block "
                                f"({bdesc}) while holding lock "
                                f"{bad[0]}"))

    def visit(stmts, held: List[str], fn: _Fn) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            here = list(held)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    node = lock_node_of(item.context_expr, fn)
                    if node:
                        for h in here:
                            note_edge(h, node, fn.path, stmt.lineno, "")
                        here.append(node)
            # expressions attached directly to this statement
            for field, value in ast.iter_fields(stmt):
                if field in ("body", "orelse", "finalbody", "handlers"):
                    continue
                vals = value if isinstance(value, list) else [value]
                for v in vals:
                    if not isinstance(v, ast.AST):
                        continue
                    stack = [v]
                    while stack:
                        n = stack.pop()
                        if isinstance(n, ast.Call):
                            # a With item's own call runs BEFORE the
                            # lock is taken, so use the OUTER held set
                            chk = held if isinstance(
                                stmt, (ast.With, ast.AsyncWith)) else here
                            if chk:
                                check_call(n, chk, fn)
                        if not isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef, ast.Lambda)):
                            stack.extend(ast.iter_child_nodes(n))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    visit(sub, here if field == "body" or not isinstance(
                        stmt, (ast.With, ast.AsyncWith)) else held, fn)
            for h in getattr(stmt, "handlers", ()):
                visit(h.body, here, fn)

    for fn in fns.values():
        visit(fn.node.body, [], fn)

    # ------------------------------------------------ cycle detection
    adj: Dict[str, Set[str]] = {}
    for (h, a) in edges:
        adj.setdefault(h, set()).add(a)
        adj.setdefault(a, set())

    # self-loops: re-acquiring a non-reentrant Lock deadlocks instantly
    for (h, a), (path, lineno, via) in sorted(edges.items()):
        if h == a and kind_of.get(h, "") == "Lock":
            out.append((path, lineno, "lockorder",
                        f"non-reentrant Lock {h} may be re-acquired "
                        f"while already held{' ' + via if via else ''} "
                        "— instant deadlock"))

    # Tarjan SCC over the acquired-while-holding graph
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstk: Set[str] = set()
    stk: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stk.append(v)
        onstk.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stk.append(w)
                    onstk.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in onstk:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stk.pop()
                    onstk.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        if len(comp) < 2:
            continue
        comp = sorted(comp)
        cyc_edges = sorted((h, a) for (h, a) in edges
                           if h in comp and a in comp and h != a)
        where = [f"{h}->{a} at "
                 f"{Path(edges[(h, a)][0]).name}:{edges[(h, a)][1]}"
                 + (f" {edges[(h, a)][2]}" if edges[(h, a)][2] else "")
                 for h, a in cyc_edges]
        path, lineno, _ = edges[cyc_edges[0]]
        out.append((path, lineno, "lockorder",
                    "lock-order cycle (potential deadlock): "
                    + " <-> ".join(comp) + "; " + "; ".join(where)))
    return out
