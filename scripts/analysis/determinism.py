"""Pass `determinism` — canonical-plane drift detection.

The canonical/volatile boundary (PR 13): canonical traces, converged
fingerprints, timeline canonical dumps, and wire frames must be
byte-identical for the same seed across reruns and hosts.  In the
modules feeding those outputs this pass flags the classic sources of
silent drift:

  - iteration over an unordered `set` (for / list / tuple / join /
    enumerate on a set-typed value) — CPython set order varies with
    PYTHONHASHSEED and insertion history; wrap in sorted();
  - unseeded process-global randomness (`random.*` module calls,
    `np.random.*` legacy global state) — seed an explicit
    `random.Random(seed)` / `np.random.default_rng(seed)` instead;
  - `id()` / builtin `hash()` used for ordering — both vary per process
    (hash randomization, allocator layout), so any sort keyed on them
    reorders canonical output between runs;
  - filesystem enumeration order (`listdir` / `glob` / `rglob` /
    `iterdir` / `scandir` not wrapped directly in sorted()) — readdir
    order is filesystem-dependent.

dict iteration is deliberately NOT flagged: CPython dicts are
insertion-ordered, and the planes already lean on that (wire's replay
cache eviction, ordered journal tables).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from common import Finding, _callee_name, _dotted, _functions

_FS_ENUM = {"listdir", "iterdir", "glob", "rglob", "scandir"}
_SET_FACTORIES = {"set", "frozenset"}
_ORDER_SINKS = {"list", "tuple", "enumerate"}
_SORTERS = {"sorted", "sort", "min", "max"}
_RNG_OK = {"Random", "SystemRandom", "default_rng", "RandomState",
           "Generator", "seed"}


def _is_set_expr(node: ast.AST, set_names: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in _SET_FACTORIES):
        return True
    if isinstance(node, ast.Name) and node.id in set_names:
        return True
    return False


def check_determinism(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []

    parent: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for ch in ast.iter_child_nodes(node):
            parent[id(ch)] = node

    has_random = any(
        isinstance(n, ast.Import) and any(a.name == "random"
                                          for a in n.names)
        for n in ast.walk(tree))
    random_froms: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "random":
            for a in n.names:
                if a.name not in _RNG_OK:
                    random_froms.add(a.asname or a.name)

    # ------------------------------------------ per-scope set typing
    scopes = [tree] + list(_functions(tree))
    for scope in scopes:
        set_names: Set[str] = set()
        stmts = list(ast.iter_child_nodes(scope)) if isinstance(
            scope, ast.Module) else scope.body
        flat = []
        stack = list(stmts)
        while stack:
            s = stack.pop()
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            flat.append(s)
            stack.extend(ast.iter_child_nodes(s))
        for _ in range(2):
            for s in flat:
                if isinstance(s, ast.Assign):
                    if _is_set_expr(s.value, set_names):
                        for t in s.targets:
                            if isinstance(t, ast.Name):
                                set_names.add(t.id)
                    else:
                        for t in s.targets:
                            if isinstance(t, ast.Name):
                                set_names.discard(t.id)
                elif (isinstance(s, ast.AugAssign)
                        and isinstance(s.target, ast.Name)
                        and _is_set_expr(s.value, set_names)):
                    set_names.add(s.target.id)

        def flag_iter(node: ast.AST, how: str) -> None:
            out.append((path, node.lineno, "determinism",
                        f"{how} iterates an unordered set — order "
                        "varies per process (hash randomization); "
                        "wrap in sorted() before it can reach "
                        "canonical output"))

        for s in flat:
            if (isinstance(s, (ast.For, ast.AsyncFor))
                    and _is_set_expr(s.iter, set_names)):
                flag_iter(s.iter, "for loop")
            if isinstance(s, ast.Call):
                cn = _callee_name(s)
                if (isinstance(s.func, ast.Name)
                        and cn in _ORDER_SINKS and s.args
                        and _is_set_expr(s.args[0], set_names)):
                    flag_iter(s, f"{cn}()")
                if (isinstance(s.func, ast.Attribute)
                        and cn == "join" and s.args
                        and _is_set_expr(s.args[0], set_names)):
                    flag_iter(s, ".join()")

    # -------------------------------------- global randomness + order
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        cn = _callee_name(n)
        if (has_random and isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "random" and cn not in _RNG_OK):
            out.append((path, n.lineno, "determinism",
                        f"process-global random.{cn}() — unseeded "
                        "(or cross-thread-shared) RNG state breaks "
                        "seeded replay; use an explicit "
                        "random.Random(seed) instance"))
        if (isinstance(f, ast.Attribute)
                and _dotted(f.value) in ("np.random", "numpy.random")
                and cn not in _RNG_OK):
            out.append((path, n.lineno, "determinism",
                        f"legacy global np.random.{cn}() — seed an "
                        "explicit np.random.default_rng(seed)"))
        if isinstance(f, ast.Name) and f.id in random_froms:
            out.append((path, n.lineno, "determinism",
                        f"process-global random {f.id}() (from-import) "
                        "— use an explicit random.Random(seed)"))
        if isinstance(f, ast.Name) and f.id == "hash" and n.args:
            out.append((path, n.lineno, "determinism",
                        "builtin hash() varies per process "
                        "(PYTHONHASHSEED) — canonical planes need a "
                        "stable digest (hashlib) or a total key"))
        # id()/hash inside a sort: ordering keyed on process layout
        if cn in _SORTERS:
            for kw in n.keywords:
                if (kw.arg == "key" and isinstance(kw.value, ast.Name)
                        and kw.value.id in ("id", "hash")):
                    out.append((path, n.lineno, "determinism",
                                f"sort keyed on builtin {kw.value.id} — "
                                "per-process ordering leaks into "
                                "canonical output"))
            for sub in ast.walk(n):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "id"):
                    out.append((path, n.lineno, "determinism",
                                "id() inside a sort expression — "
                                "per-process ordering leaks into "
                                "canonical output"))
        # filesystem enumeration not immediately sorted
        if cn in _FS_ENUM:
            p = parent.get(id(n))
            sorted_wrapped = (isinstance(p, ast.Call)
                              and isinstance(p.func, ast.Name)
                              and p.func.id == "sorted")
            if not sorted_wrapped:
                out.append((path, n.lineno, "determinism",
                            f"{cn}() order is filesystem-dependent — "
                            "wrap the enumeration in sorted()"))
    return out
