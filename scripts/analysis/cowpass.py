"""Pass `cow` — COW / snapshot-isolation discipline (state_store.py).

Objects reachable from a snapshot are immutable: in-place writes to the
claim-vol / alloc / block / eval tables (or to objects fetched from
them) must flow through a `_writable_*` helper whose returned copy is
private to the head for this snapshot cycle.  Mutating a table object
obtained any other way — or a `dataclasses.replace` shallow copy, whose
inner dicts are still shared — is exactly the
`_materialize_block_locked` snapshot leak fixed twice before this pass
existed.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from common import (Finding, _callee_name, _functions, _root_name,
                    _self_attr, _walk_skip_defs)

# tables reachable from a StateSnapshot (or published like them): the
# head may only mutate PRIVATE copies of these
SNAP_TABLES = {
    "_nodes", "_jobs", "_job_versions", "_evals", "_allocs",
    "_deployments", "_namespaces", "_node_pools", "_csi_volumes",
    "_acl_policies", "_acl_tokens", "_acl_by_secret",
    "_acl_auth_methods", "_acl_binding_rules", "_variables", "_services",
    "_allocs_by_node", "_allocs_by_job", "_evals_by_job",
    "_alloc_blocks", "_blocks_by_job", "_blocks_by_node",
}

MUTATORS = {"pop", "update", "setdefault", "clear", "add", "remove",
            "discard", "append", "extend", "insert", "popitem"}

FRESH_CALLS = {"dict", "list", "set", "frozenset", "sorted"}


def _is_fresh_expr(node: ast.AST) -> bool:
    """A brand-new container private to this frame."""
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in FRESH_CALLS):
        return True
    return False


def _is_writable_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _callee_name(node) is not None
            and _callee_name(node).startswith("_writable_"))


def _is_replace_call(node: ast.AST) -> bool:
    """dataclasses.replace(...) — a SHALLOW copy: inner claim dicts are
    still the snapshot's unless explicitly replaced, so the result stays
    snapshot-tainted for in-place mutation purposes."""
    return (isinstance(node, ast.Call)
            and _callee_name(node) == "replace")


def _snap_rooted(node: ast.AST) -> bool:
    """Expression that reads out of a snapshot-shared table:
    self.<SNAP>..., self.<SNAP>.get(...), self.<SNAP>.values(), ..."""
    attr = _self_attr(node)
    return attr in SNAP_TABLES


def check_cow(tree: ast.Module, path: str) -> List[Finding]:
    """Two taint grades: `snap` objects came straight out of a
    snapshot-shared table (NO mutation allowed), `shallow` objects are
    dataclasses.replace copies — a fresh outer object whose inner
    containers are still the snapshot's, so scalar attribute writes are
    fine but inner-container mutation is the leak."""
    out: List[Finding] = []
    for fn in _functions(tree):
        blessed: Set[str] = set()
        tainted: Set[str] = set()       # snap grade
        shallow: Set[str] = set()
        fresh_attrs: Set[str] = set()

        stmts = list(_walk_skip_defs(fn))
        # attributes wholesale-reassigned to a fresh container in this
        # function (snapshot_restore's reset-then-fill shape): in-place
        # writes to them cannot reach a snapshot taken before the call
        for s in stmts:
            if isinstance(s, ast.Assign) and _is_fresh_expr(s.value):
                for t in s.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        fresh_attrs.add(t.attr)
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Dict):
                for t in s.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        fresh_attrs.add(t.attr)

        def classify(value: ast.AST) -> Optional[str]:
            if _is_writable_call(value) or _is_fresh_expr(value):
                return "blessed"
            if _is_replace_call(value):
                return "shallow"
            if _snap_rooted(value):
                return "tainted"
            root = _root_name(value)
            if isinstance(value, ast.Call):
                return None          # other call results: neutral copy
            if root in blessed:
                return "blessed"
            if root in tainted:
                return "tainted"
            if root in shallow:
                return "shallow"
            return None

        def bind(target: ast.AST, klass: Optional[str]) -> None:
            names = [n.id for n in ast.walk(target)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Store)]
            for nm in names:
                if klass == "blessed":
                    blessed.add(nm)
                    tainted.discard(nm)
                    shallow.discard(nm)
                elif klass == "tainted" and nm not in blessed:
                    tainted.add(nm)
                elif klass == "shallow" and nm not in blessed:
                    shallow.add(nm)

        # fixed-point propagation over the function's assignments
        for _ in range(4):
            before = (len(blessed), len(tainted), len(shallow))
            for s in stmts:
                if isinstance(s, ast.Assign):
                    k = classify(s.value)
                    for t in s.targets:
                        bind(t, k)
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    it = s.iter
                    k = None
                    if _snap_rooted(it):
                        k = "tainted"
                    elif (_root_name(it) in tainted
                          and not isinstance(it, ast.Call)):
                        k = "tainted"
                    elif (isinstance(it, ast.Call)
                          and _root_name(it.func) in tainted):
                        k = "tainted"       # tainted.values()/.items()
                    bind(s.target, k)
            if (len(blessed), len(tainted), len(shallow)) == before:
                break

        def flag(node: ast.AST, what: str) -> None:
            out.append((path, node.lineno, "cow",
                        f"{what} — snapshot-shared state must be "
                        "mutated only through a _writable_* copy"))

        for n in stmts:
            # subscript / attribute stores
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        root = _root_name(t.value)
                        if (attr in SNAP_TABLES
                                and attr not in fresh_attrs):
                            flag(t, f"direct write into self.{attr}[...]")
                        elif root in tainted:
                            flag(t, "item write on a snapshot-fetched "
                                    "object")
                        elif (root in shallow
                              and isinstance(t.value, ast.Attribute)):
                            flag(t, "item write into an inner container "
                                    "of a dataclasses.replace shallow "
                                    "copy (still the snapshot's dict)")
                    elif isinstance(t, ast.Attribute):
                        if _root_name(t.value) in tainted:
                            flag(t, "attribute write on a "
                                    "snapshot-fetched object")
            if isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr in SNAP_TABLES and attr not in fresh_attrs:
                            flag(t, f"del on self.{attr}[...]")
                        elif _root_name(t.value) in tainted:
                            flag(t, "del on a snapshot-fetched object")
            # mutator method calls
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in MUTATORS):
                obj = n.func.value
                attr = _self_attr(obj)
                root = _root_name(obj)
                if attr in SNAP_TABLES and attr not in fresh_attrs:
                    flag(n, f"self.{attr}.{n.func.attr}(...) in place")
                elif root in tainted:
                    flag(n, f".{n.func.attr}(...) on a snapshot-fetched "
                            "object")
                elif (root in shallow
                      and isinstance(obj, (ast.Attribute, ast.Subscript))):
                    flag(n, f".{n.func.attr}(...) on an inner container "
                            "of a dataclasses.replace shallow copy "
                            "(still the snapshot's dict)")
    return out
