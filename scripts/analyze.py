"""Static invariant analyzer — compatibility shim.

The implementation lives in scripts/analysis/ (one module per pass plus
common.py, driver.py, selftests.py).  This shim keeps the historical
entry points working unchanged:

    python scripts/analyze.py [--selftest] [--json PATH]
                              [--strict-suppressions] [--update-manifest]

    sys.path.insert(0, "scripts"); from analyze import analyze_source

    importlib.util.spec_from_file_location("analyze", ".../analyze.py")

Passes: lock, cow, purity, thread, rawtime, lockorder, determinism,
wireproto.  Suppress a finding with `# analyze: ok <pass>` (or
`# analyze: ok *`) on its line; stale suppressions are reported and
fail the run under --strict-suppressions.
"""

from __future__ import annotations

import sys
from pathlib import Path

_ANALYSIS = Path(__file__).resolve().parent / "analysis"
if str(_ANALYSIS) not in sys.path:
    sys.path.insert(0, str(_ANALYSIS))

import common as _common
import driver as _driver
import selftests as _selftests

ROOT = _common.ROOT
Finding = _common.Finding
PASS_NAMES = _common.PASS_NAMES
analyze_source = _driver.analyze_source
analyze_repo = _driver.analyze_repo
analyze_repo_full = _driver.analyze_repo_full
update_manifest = _driver.update_manifest
main = _driver.main
selftest = _selftests.selftest
_scoped_files = _driver._scoped_files

if __name__ == "__main__":
    sys.exit(main())
