#!/usr/bin/env python
"""Invariant analyzer: project-specific static-analysis passes over the
repo's ASTs (zero external dependencies, like scripts/lint.py — lint
catches generic mistakes, THIS tool encodes the invariants whose
violations keep recurring as real bugs here).

Passes (suppress a finding with `# analyze: ok <pass>` on its line):

  lock    Lock discipline.  A `*_locked` / `_writable_*` helper mutates
          or reads head state that only the store/broker lock makes
          consistent — it may only be called from another such helper or
          from a lexical `with self._lock:` (or `.locked()` / condition)
          scope.  Public entry points must acquire before delegating.

  cow     COW / snapshot-isolation discipline (state_store.py).  Objects
          reachable from a snapshot are immutable: in-place writes to
          the claim-vol / alloc / block / eval tables (or to objects
          fetched from them) must flow through a `_writable_*` helper
          whose returned copy is private to the head for this snapshot
          cycle.  Mutating a table object obtained any other way — or a
          `dataclasses.replace` shallow copy, whose inner dicts are
          still shared — is exactly the `_materialize_block_locked`
          snapshot leak fixed twice before this pass existed.

  purity  JAX purity & donation (ops/, parallel/, core/wavepipe.py).
          Host-sync calls (`block_until_ready`, host `np.*`, `float()` /
          `bool()` on traced values, `.item()`) inside jit-traced code
          break async dispatch; heavy `jnp` compute in non-jit host
          paths pays per-op dispatch in the hot loop; and a buffer
          passed at a `donate_argnums` position is DEAD after the call —
          XLA reuses its memory, so any later read of the same
          expression reads garbage.

  thread  Thread hygiene.  A `threading.Thread(target=...)` target (or a
          raft `on_leader=` / `on_follower=` callback, which runs on a
          daemon thread) without top-level exception handling dies
          silently — a leadership callback that dies on `NotLeaderError`
          is how state desync starts (VERDICT weak #6).  The same rule
          covers `multiprocessing.Process(target=...)` (core/workerpool
          children): the target needs a top-level handler (an unhandled
          exception is only a one-line stderr trace in another process),
          and the Process needs a `name=` — unnamed workers are
          invisible in ps output and crash triage.

  rawtime Injected-timebase discipline (nomad_tpu/core/).  A raw
          `time.time()` / `time.monotonic()` / `time.sleep()` call in
          the cluster plane bypasses the chaos Clock seam
          (chaos/clock.py), so a virtual-time soak silently mixes wall
          and virtual timelines — heartbeat TTLs fire early, SLO
          windows span the wrong samples, and the same seed stops
          replaying.  Route through `self.clock` / a module-level bound
          Clock instead (`time.perf_counter()` stays legal: host-side
          duration measurement is not cluster time).

`--selftest` runs every pass against an injected violation of its exact
bug class and exits 0 only when each pass catches its own and stays
quiet on the clean shapes — the CI stage proving the net has no hole.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

ROOT = Path(__file__).resolve().parent.parent

Finding = Tuple[str, int, str, str]        # (path, lineno, pass, message)

PASS_NAMES = ("lock", "cow", "purity", "thread", "rawtime")


# --------------------------------------------------------------- helpers

def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    (their bodies run in a different dynamic context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _functions(tree: ast.Module):
    """Every function/method def in the module (flat)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _self_attr(node: ast.AST) -> Optional[str]:
    """The first attribute name hanging off `self` in an access chain
    (`self._allocs[k].x.pop` -> '_allocs'), or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.func if isinstance(node, ast.Call) else node.value
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """The root Name of an access chain (`vol.read_allocs.pop` -> 'vol'),
    or None when the chain roots elsewhere (a call result, self, ...)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted path of a pure Name/Attribute chain ('inp.used0'), else
    None (subscripts and calls are not stable paths)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


# ------------------------------------------------------- pass A: lock

LOCK_ATTRS = {"_lock", "lock", "_cv", "_index_cv", "_apply_cv",
              "_tick_lock"}
LOCKED_PREFIXES = ("_writable_",)


def _is_lock_expr(node: ast.AST, aliases: Set[str]) -> bool:
    """Expressions that acquire the protecting lock when used in
    `with ...:` — the lock/condition attribute itself, a `.locked()`
    accessor, or a local alias of either."""
    if isinstance(node, ast.Attribute) and node.attr in LOCK_ATTRS:
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "locked":
            return True
    if isinstance(node, ast.IfExp):
        return (_is_lock_expr(node.body, aliases)
                or _is_lock_expr(node.orelse, aliases))
    if isinstance(node, ast.Name) and node.id in aliases:
        return True
    return False


def _needs_lock(name: Optional[str]) -> bool:
    if not name:
        return False
    return name.endswith("_locked") or name.startswith(LOCKED_PREFIXES)


def check_lock(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        holder = _needs_lock(fn.name)
        aliases = {
            t.id
            for stmt in _walk_skip_defs(fn)
            if isinstance(stmt, ast.Assign)
            and _is_lock_expr(stmt.value, set())
            for t in stmt.targets if isinstance(t, ast.Name)
        }

        # flag calls attached to each statement's own expressions;
        # compound bodies recurse with the updated lock state
        def visit2(stmts, inlock, fn=fn, aliases=aliases, holder=holder):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue      # nested defs get their own analysis
                here = inlock
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    if any(_is_lock_expr(i.context_expr, aliases)
                           for i in stmt.items):
                        here = True
                # expressions attached directly to this statement
                # (excluding nested statement bodies)
                exprs: List[ast.AST] = []
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody",
                                 "handlers"):
                        continue
                    if isinstance(value, ast.AST):
                        exprs.append(value)
                    elif isinstance(value, list):
                        exprs.extend(v for v in value
                                     if isinstance(v, ast.AST))
                if not (holder or here):
                    for e in exprs:
                        for n in [e, *_walk_skip_defs(e)]:
                            if (isinstance(n, ast.Call)
                                    and _needs_lock(_callee_name(n))):
                                out.append((
                                    path, n.lineno, "lock",
                                    f"{_callee_name(n)}() called outside "
                                    "a lock scope (hold the store lock "
                                    "or be *_locked yourself)"))
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit2(sub, here)
                for h in getattr(stmt, "handlers", ()):
                    visit2(h.body, here)

        visit2(fn.body, False)
    return out


# -------------------------------------------------------- pass B: cow

# tables reachable from a StateSnapshot (or published like them): the
# head may only mutate PRIVATE copies of these
SNAP_TABLES = {
    "_nodes", "_jobs", "_job_versions", "_evals", "_allocs",
    "_deployments", "_namespaces", "_node_pools", "_csi_volumes",
    "_acl_policies", "_acl_tokens", "_acl_by_secret",
    "_acl_auth_methods", "_acl_binding_rules", "_variables", "_services",
    "_allocs_by_node", "_allocs_by_job", "_evals_by_job",
    "_alloc_blocks", "_blocks_by_job", "_blocks_by_node",
}

MUTATORS = {"pop", "update", "setdefault", "clear", "add", "remove",
            "discard", "append", "extend", "insert", "popitem"}

FRESH_CALLS = {"dict", "list", "set", "frozenset", "sorted"}


def _is_fresh_expr(node: ast.AST) -> bool:
    """A brand-new container private to this frame."""
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in FRESH_CALLS):
        return True
    return False


def _is_writable_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _callee_name(node) is not None
            and _callee_name(node).startswith("_writable_"))


def _is_replace_call(node: ast.AST) -> bool:
    """dataclasses.replace(...) — a SHALLOW copy: inner claim dicts are
    still the snapshot's unless explicitly replaced, so the result stays
    snapshot-tainted for in-place mutation purposes."""
    return (isinstance(node, ast.Call)
            and _callee_name(node) == "replace")


def _snap_rooted(node: ast.AST) -> bool:
    """Expression that reads out of a snapshot-shared table:
    self.<SNAP>..., self.<SNAP>.get(...), self.<SNAP>.values(), ..."""
    attr = _self_attr(node)
    return attr in SNAP_TABLES


def check_cow(tree: ast.Module, path: str) -> List[Finding]:
    """Two taint grades: `snap` objects came straight out of a
    snapshot-shared table (NO mutation allowed), `shallow` objects are
    dataclasses.replace copies — a fresh outer object whose inner
    containers are still the snapshot's, so scalar attribute writes are
    fine but inner-container mutation is the leak."""
    out: List[Finding] = []
    for fn in _functions(tree):
        blessed: Set[str] = set()
        tainted: Set[str] = set()       # snap grade
        shallow: Set[str] = set()
        fresh_attrs: Set[str] = set()

        stmts = list(_walk_skip_defs(fn))
        # attributes wholesale-reassigned to a fresh container in this
        # function (snapshot_restore's reset-then-fill shape): in-place
        # writes to them cannot reach a snapshot taken before the call
        for s in stmts:
            if isinstance(s, ast.Assign) and _is_fresh_expr(s.value):
                for t in s.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        fresh_attrs.add(t.attr)
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Dict):
                for t in s.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        fresh_attrs.add(t.attr)

        def classify(value: ast.AST) -> Optional[str]:
            if _is_writable_call(value) or _is_fresh_expr(value):
                return "blessed"
            if _is_replace_call(value):
                return "shallow"
            if _snap_rooted(value):
                return "tainted"
            root = _root_name(value)
            if isinstance(value, ast.Call):
                return None          # other call results: neutral copy
            if root in blessed:
                return "blessed"
            if root in tainted:
                return "tainted"
            if root in shallow:
                return "shallow"
            return None

        def bind(target: ast.AST, klass: Optional[str]) -> None:
            names = [n.id for n in ast.walk(target)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Store)]
            for nm in names:
                if klass == "blessed":
                    blessed.add(nm)
                    tainted.discard(nm)
                    shallow.discard(nm)
                elif klass == "tainted" and nm not in blessed:
                    tainted.add(nm)
                elif klass == "shallow" and nm not in blessed:
                    shallow.add(nm)

        # fixed-point propagation over the function's assignments
        for _ in range(4):
            before = (len(blessed), len(tainted), len(shallow))
            for s in stmts:
                if isinstance(s, ast.Assign):
                    k = classify(s.value)
                    for t in s.targets:
                        bind(t, k)
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    it = s.iter
                    k = None
                    if _snap_rooted(it):
                        k = "tainted"
                    elif (_root_name(it) in tainted
                          and not isinstance(it, ast.Call)):
                        k = "tainted"
                    elif (isinstance(it, ast.Call)
                          and _root_name(it.func) in tainted):
                        k = "tainted"       # tainted.values()/.items()
                    bind(s.target, k)
            if (len(blessed), len(tainted), len(shallow)) == before:
                break

        def flag(node: ast.AST, what: str) -> None:
            out.append((path, node.lineno, "cow",
                        f"{what} — snapshot-shared state must be "
                        "mutated only through a _writable_* copy"))

        for n in stmts:
            # subscript / attribute stores
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        root = _root_name(t.value)
                        if (attr in SNAP_TABLES
                                and attr not in fresh_attrs):
                            flag(t, f"direct write into self.{attr}[...]")
                        elif root in tainted:
                            flag(t, "item write on a snapshot-fetched "
                                    "object")
                        elif (root in shallow
                              and isinstance(t.value, ast.Attribute)):
                            flag(t, "item write into an inner container "
                                    "of a dataclasses.replace shallow "
                                    "copy (still the snapshot's dict)")
                    elif isinstance(t, ast.Attribute):
                        if _root_name(t.value) in tainted:
                            flag(t, "attribute write on a "
                                    "snapshot-fetched object")
            if isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr in SNAP_TABLES and attr not in fresh_attrs:
                            flag(t, f"del on self.{attr}[...]")
                        elif _root_name(t.value) in tainted:
                            flag(t, "del on a snapshot-fetched object")
            # mutator method calls
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in MUTATORS):
                obj = n.func.value
                attr = _self_attr(obj)
                root = _root_name(obj)
                if attr in SNAP_TABLES and attr not in fresh_attrs:
                    flag(n, f"self.{attr}.{n.func.attr}(...) in place")
                elif root in tainted:
                    flag(n, f".{n.func.attr}(...) on a snapshot-fetched "
                            "object")
                elif (root in shallow
                      and isinstance(obj, (ast.Attribute, ast.Subscript))):
                    flag(n, f".{n.func.attr}(...) on an inner container "
                            "of a dataclasses.replace shallow copy "
                            "(still the snapshot's dict)")
    return out


# ----------------------------------------------------- pass C: purity

HEAVY_JNP = {"where", "sum", "argsort", "sort", "argmax", "argmin",
             "cumsum", "dot", "matmul", "einsum", "take_along_axis",
             "top_k", "mean", "prod", "nonzero", "unique"}

NP_ALIASES = {"np", "numpy"}
JNP_ALIASES = {"jnp"}


# transforms that TRACE the function they wrap: a Name passed to one of
# these runs under jit/trace semantics, not eagerly on the host
TRACE_WRAPPERS = {"jit", "shard_map", "vmap", "pmap", "scan",
                  "fori_loop", "while_loop", "cond", "remat",
                  "checkpoint", "grad", "value_and_grad"}


def _jit_call(node: ast.AST) -> bool:
    """A call to jax.jit / jit."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    return False


def _trace_wrapper_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _callee_name(node)
    return name in TRACE_WRAPPERS


class _ModuleInfo:
    __slots__ = ("path", "tree", "funcs", "imports", "jit_seeds",
                 "jit_lambdas", "donated")

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        # name -> ALL defs carrying it (mesh.py's jit factories each
        # define a local `f`; a plain dict would keep only one)
        self.funcs: Dict[str, List[ast.AST]] = {}
        for f in _functions(tree):
            self.funcs.setdefault(f.name, []).append(f)
        # local name -> (module stem, source name) for from-imports
        self.imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                stem = node.module.split(".")[-1]
                for a in node.names:
                    if a.name != "*":
                        self.imports[a.asname or a.name] = (stem, a.name)
        self.jit_seeds: Set[str] = set()
        self.jit_lambdas: List[ast.Lambda] = []
        # jitted-callable local name -> donated positional indexes
        self.donated: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if _trace_wrapper_call(node):
                # every Name reachable in the wrapper's args is traced —
                # covers partial(_kernel, ...) indirection too
                for a in node.args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name):
                            self.jit_seeds.add(sub.id)
                        elif isinstance(sub, ast.Lambda):
                            self.jit_lambdas.append(sub)
            if isinstance(node, ast.FunctionDef):
                for d in node.decorator_list:
                    if _jit_call(d) or (
                            isinstance(d, ast.Attribute)
                            and d.attr == "jit") or (
                            isinstance(d, ast.Name) and d.id == "jit"):
                        self.jit_seeds.add(node.name)
            # NAME = jax.jit(fn, donate_argnums=(k,...))
            if isinstance(node, ast.Assign) and _jit_call(node.value):
                dons: Tuple[int, ...] = ()
                for kw in node.value.keywords:
                    if kw.arg == "donate_argnums":
                        vals = []
                        for e in ast.walk(kw.value):
                            if (isinstance(e, ast.Constant)
                                    and isinstance(e.value, int)):
                                vals.append(e.value)
                        dons = tuple(vals)
                if dons:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.donated[t.id] = dons


def _purity_traced_defs(mods: Dict[str, _ModuleInfo]) -> Set[int]:
    """id()s of every function def reachable from a jax.jit seed —
    through any NAME REFERENCE inside traced code, not just direct
    calls: `jax.lax.scan(step, ...)` traces `step` without calling it by
    name, and a helper imported from a sibling kernel module is traced
    when a traced function references it.  Defs nested inside a traced
    def only ever run under trace and count too.  Over-approximation is
    deliberate: marking a host helper traced can only silence the eager
    host-path heuristic, never invent a finding."""
    traced: Set[int] = set()
    work: List[Tuple[str, ast.AST]] = []

    def mark(stem: str, fn: ast.AST) -> None:
        if id(fn) in traced:
            return
        traced.add(id(fn))
        work.append((stem, fn))
        for sub in ast.walk(fn):
            if (sub is not fn
                    and isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))):
                traced.add(id(sub))

    for stem, mi in mods.items():
        for name in mi.jit_seeds:
            for fn in mi.funcs.get(name, ()):
                mark(stem, fn)
    while work:
        stem, fn = work.pop()
        mi = mods[stem]
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)):
                continue
            if n.id in mi.funcs:
                for f2 in mi.funcs[n.id]:
                    mark(stem, f2)
            elif n.id in mi.imports:
                src_stem, src_name = mi.imports[n.id]
                if src_stem in mods:
                    for f2 in mods[src_stem].funcs.get(src_name, ()):
                        mark(src_stem, f2)
    return traced


def _branch_paths(fn: ast.AST) -> Dict[int, Tuple]:
    """id(node) -> tuple of (id(branch stmt), arm) ancestors — two nodes
    whose paths first differ on the same statement with different arms
    can never execute in the same pass (if/else, try/except)."""
    paths: Dict[int, Tuple] = {}

    def go(node: ast.AST, path: Tuple) -> None:
        for field, value in ast.iter_fields(node):
            kids = value if isinstance(value, list) else [value]
            for k in kids:
                if not isinstance(k, ast.AST):
                    continue
                sub = path
                if (isinstance(node, ast.If)
                        and field in ("body", "orelse")):
                    sub = path + ((id(node), field),)
                elif (isinstance(node, ast.Try)
                        and field in ("body", "handlers", "orelse")):
                    sub = path + ((id(node), field),)
                paths[id(k)] = sub
                go(k, sub)

    paths[id(fn)] = ()
    go(fn, ())
    return paths


def _exclusive(p1: Tuple, p2: Tuple) -> bool:
    for e1, e2 in zip(p1, p2):
        if e1 == e2:
            continue
        return e1[0] == e2[0] and e1[1] != e2[1]
    return False


def check_purity(files: Dict[str, ast.Module]) -> List[Finding]:
    mods: Dict[str, _ModuleInfo] = {}
    for path, tree in files.items():
        stem = Path(path).stem
        mods[stem] = _ModuleInfo(path, tree)
    traced = _purity_traced_defs(mods)
    # donated callables visible across the scoped modules by import
    donated_global: Dict[Tuple[str, str], Tuple[int, ...]] = {}
    for stem, mi in mods.items():
        for name, dons in mi.donated.items():
            donated_global[(stem, name)] = dons
    out: List[Finding] = []

    def check_traced_body(body: ast.AST, path: str) -> None:
        for n in ast.walk(body):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if (isinstance(f, ast.Attribute)
                    and _root_name(f) in NP_ALIASES):
                out.append((path, n.lineno, "purity",
                            f"host numpy call np.{f.attr}(...) inside "
                            "jit-traced code (silent device->host sync "
                            "or constant fold)"))
            if (isinstance(f, ast.Attribute)
                    and f.attr in ("item", "tolist")):
                out.append((path, n.lineno, "purity",
                            f".{f.attr}() inside jit-traced code forces "
                            "a host sync"))
            if (isinstance(f, ast.Name) and f.id in ("float", "bool")
                    and n.args
                    and not all(isinstance(a, ast.Constant)
                                for a in n.args)):
                out.append((path, n.lineno, "purity",
                            f"{f.id}() on a traced value forces a host "
                            "sync inside jit"))

    for stem, mi in mods.items():
        path = mi.path
        all_defs = [f for fns in mi.funcs.values() for f in fns]
        # 1. block_until_ready anywhere in the hot-path modules: the
        # pipeline's ONE deliberate sync point lives in collect() and
        # carries a suppression; anything else is a stall in disguise
        for n in ast.walk(mi.tree):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "block_until_ready"):
                out.append((path, n.lineno, "purity",
                            "block_until_ready() in the pipeline hot "
                            "path — host sync defeats async dispatch"))
        # 2. traced-code checks (outermost traced defs only: their walk
        # already covers defs nested inside them)
        nested_in_traced: Set[int] = set()
        for fn in all_defs:
            if id(fn) not in traced:
                continue
            for sub in ast.walk(fn):
                if (sub is not fn
                        and isinstance(sub, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))):
                    nested_in_traced.add(id(sub))
        for fn in all_defs:
            if id(fn) in traced and id(fn) not in nested_in_traced:
                check_traced_body(fn, path)
        for lam in mi.jit_lambdas:
            check_traced_body(lam, path)
        # 3. heavy eager jnp in host (non-traced) functions
        for fn in all_defs:
            if id(fn) in traced:
                continue
            for n in _walk_skip_defs(fn):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in HEAVY_JNP
                        and _root_name(n.func) in JNP_ALIASES):
                    out.append((path, n.lineno, "purity",
                                f"eager jnp.{n.func.attr}(...) in a "
                                "non-jit host path (per-op dispatch in "
                                "the hot loop; move it under jit)"))
        # 4. donated-buffer reuse: a read of the donated expression
        # AFTER the donating call (same execution path only — an
        # exclusive if/elif arm cannot observe the other arm's donation)
        for fn in all_defs:
            calls: List[Tuple[int, str, Tuple]] = []
            paths_by_id = None
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                cn = n.func.id if isinstance(n.func, ast.Name) else None
                if cn is None:
                    continue
                dons = mi.donated.get(cn)
                if dons is None and cn in mi.imports:
                    dons = donated_global.get(mi.imports[cn])
                if not dons:
                    continue
                if paths_by_id is None:
                    paths_by_id = _branch_paths(fn)
                for k in dons:
                    if k < len(n.args):
                        p = _dotted(n.args[k])
                        if p:
                            end = getattr(n, "end_lineno", n.lineno)
                            calls.append((end, p,
                                          paths_by_id.get(id(n), ())))
            if not calls:
                continue
            loads: List[Tuple[int, str, Tuple]] = []
            stores: List[Tuple[int, str]] = []
            for n in ast.walk(fn):
                p = None
                if isinstance(n, (ast.Name, ast.Attribute)):
                    p = _dotted(n)
                if p is None:
                    continue
                if isinstance(n.ctx, ast.Load):
                    loads.append((n.lineno, p,
                                  paths_by_id.get(id(n), ())))
                elif isinstance(n.ctx, ast.Store):
                    stores.append((n.lineno, p))
            for call_end, pth, cpath in calls:
                for ln, p, lpath in loads:
                    if p != pth or ln <= call_end:
                        continue
                    if _exclusive(cpath, lpath):
                        continue
                    rebound = any(call_end < s_ln <= ln and s_p == pth
                                  for s_ln, s_p in stores)
                    if not rebound:
                        out.append((path, ln, "purity",
                                    f"`{pth}` read after being DONATED "
                                    f"to a chained dispatch on line "
                                    f"{call_end} — the buffer is dead "
                                    "(XLA reuses its memory)"))
    return out


# ----------------------------------------------------- pass D: thread

def _has_toplevel_handler(fn: ast.AST) -> bool:
    """True when the function body protects its thread: a try/except at
    body level, or directly inside While/For/With wrappers (a loop-body
    try = per-iteration protection)."""
    def scan(stmts, depth: int) -> bool:
        for s in stmts:
            if isinstance(s, ast.Try) and s.handlers:
                return True
            if (isinstance(s, (ast.While, ast.For, ast.With,
                               ast.AsyncWith, ast.AsyncFor))
                    and depth < 3 and scan(s.body, depth + 1)):
                return True
        return False
    return scan(fn.body, 0)


def check_thread(tree: ast.Module, path: str) -> List[Finding]:
    funcs = {f.name: f for f in _functions(tree)}
    out: List[Finding] = []
    seen: Set[int] = set()

    def resolve(expr: ast.AST):
        if isinstance(expr, ast.Name):
            return funcs.get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return funcs.get(expr.attr)
        return None

    def require(expr: ast.AST, kind: str) -> None:
        target = resolve(expr)
        if target is None or id(target) in seen:
            return
        seen.add(id(target))
        if not _has_toplevel_handler(target):
            out.append((path, target.lineno, "thread",
                        f"{kind} `{target.name}` has no top-level "
                        "exception handling — an unhandled exception "
                        "kills the daemon thread silently"))

    def chaos_managed(call: ast.Call) -> bool:
        """Thread(..., name="chaos-...") wrappers are scenario-managed:
        the chaos runner joins them with a timeout and surfaces failure
        through failed_ops / the convergence verdict, so "dies silently"
        does not apply — the death IS observed."""
        for kw in call.keywords:
            if kw.arg != "name":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return v.value.startswith("chaos-")
            if isinstance(v, ast.JoinedStr) and v.values:
                head = v.values[0]
                return (isinstance(head, ast.Constant)
                        and isinstance(head.value, str)
                        and head.value.startswith("chaos-"))
        return False

    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        cn = _callee_name(n)
        if cn == "Thread" and not chaos_managed(n):
            for kw in n.keywords:
                if kw.arg == "target":
                    require(kw.value, "thread target")
        if cn == "Process":
            if not any(kw.arg == "name" for kw in n.keywords):
                out.append((path, n.lineno, "thread",
                            "Process(...) without a name= — unnamed "
                            "worker processes are invisible in ps "
                            "output and crash triage"))
            for kw in n.keywords:
                if kw.arg == "target":
                    require(kw.value, "process target")
        for kw in n.keywords:
            if kw.arg in ("on_leader", "on_follower"):
                require(kw.value, f"daemon callback ({kw.arg}=)")
    return out


# ---------------------------------------------------- pass E: rawtime

# cluster-plane time must flow through the injected chaos Clock; these
# raw calls each pin a timeline to the wall clock.  perf_counter is
# deliberately absent: host-side duration measurement (wavepipe stage
# timers) is not cluster time and stays legal.
_RAWTIME_BANNED = ("time", "monotonic", "sleep")


def check_rawtime(tree: ast.Module, path: str) -> List[Finding]:
    out: List[Finding] = []
    # names pulled in via `from time import ...` (aliases included)
    from_imports: Dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            for a in n.names:
                if a.name in _RAWTIME_BANNED:
                    from_imports[a.asname or a.name] = a.name
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        fn = n.func
        banned = ""
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr in _RAWTIME_BANNED):
            banned = fn.attr
        elif isinstance(fn, ast.Name) and fn.id in from_imports:
            banned = from_imports[fn.id]
        if banned:
            out.append((path, n.lineno, "rawtime",
                        f"raw `time.{banned}()` bypasses the injected "
                        "Clock — a virtual-time soak mixes wall and "
                        "virtual timelines; route through the bound "
                        "chaos Clock (clock.time()/monotonic()/sleep())"))
    return out


# ----------------------------------------------------------- plumbing

def _scoped_files() -> Dict[str, List[Path]]:
    """pass name -> files it runs over."""
    pkg = ROOT / "nomad_tpu"
    all_py = sorted(p for p in pkg.rglob("*.py")
                    if "__pycache__" not in p.parts)
    purity = sorted((pkg / "ops").glob("*.py")) \
        + sorted((pkg / "parallel").glob("*.py")) \
        + [pkg / "core" / "wavepipe.py"]
    return {
        "lock": all_py,
        "cow": [pkg / "state" / "state_store.py"],
        "purity": purity,
        "thread": all_py,
        "rawtime": sorted((pkg / "core").glob("*.py")),
    }


def _suppressed(text_lines: List[str], lineno: int, pass_name: str
                ) -> bool:
    if not (1 <= lineno <= len(text_lines)):
        return False
    line = text_lines[lineno - 1]
    return (f"analyze: ok {pass_name}" in line
            or "analyze: ok *" in line)


def analyze_source(text: str, path: str = "<memory>",
                   passes: Iterable[str] = PASS_NAMES) -> List[Finding]:
    """Run single-module passes over one source blob (selftest + unit
    tests); `purity` runs in single-module mode."""
    tree = ast.parse(text)
    findings: List[Finding] = []
    for name in passes:
        if name == "lock":
            findings.extend(check_lock(tree, path))
        elif name == "cow":
            findings.extend(check_cow(tree, path))
        elif name == "purity":
            findings.extend(check_purity({path: tree}))
        elif name == "thread":
            findings.extend(check_thread(tree, path))
        elif name == "rawtime":
            findings.extend(check_rawtime(tree, path))
    lines = text.splitlines()
    return sorted({f for f in findings
                   if not _suppressed(lines, f[1], f[2])})


def analyze_repo(root: Path = ROOT) -> List[Finding]:
    scopes = _scoped_files()
    texts: Dict[str, str] = {}
    trees: Dict[str, ast.Module] = {}
    findings: List[Finding] = []
    for files in scopes.values():
        for p in files:
            key = str(p)
            if key in trees or not p.exists():
                continue
            texts[key] = p.read_text()
            try:
                trees[key] = ast.parse(texts[key])
            except SyntaxError as e:
                findings.append((key, e.lineno or 0, "parse",
                                 f"syntax error: {e.msg}"))
    single = {"lock": check_lock, "cow": check_cow,
              "thread": check_thread, "rawtime": check_rawtime}
    for name, checker in single.items():
        for p in scopes[name]:
            key = str(p)
            if key not in trees:
                continue
            findings.extend(checker(trees[key], key))
    purity_files = {str(p): trees[str(p)] for p in scopes["purity"]
                    if str(p) in trees}
    findings.extend(check_purity(purity_files))
    out = set()
    for f in findings:
        lines = texts.get(f[0], "").splitlines()
        if not _suppressed(lines, f[1], f[2]):
            out.add(f)
    return sorted(out)


# ----------------------------------------------------------- selftest

SELFTEST_LOCK = '''
class StateStore:
    def upsert_thing(self, x):
        with self._lock:
            self._insert_thing_locked(x)      # ok: under the lock

    def _merge_locked(self, x):
        self._insert_thing_locked(x)          # ok: *_locked caller

    def broken_entry(self, x):
        self._insert_thing_locked(x)          # VIOLATION: no lock

    def broken_helper(self, key):
        vol = self._writable_claim_vol(key)   # VIOLATION: no lock
        return vol


class MetricsRegistry:
    # the telemetry registry's locked paths (core/telemetry.py): the
    # histogram mutator is *_locked and every caller must hold the
    # registry lock — a bare call is exactly the unsynchronized
    # stats-dict increment this PR removed from broker/worker
    def observe(self, key, value):
        with self._lock:
            self._observe_locked(key, value)  # ok: under the lock

    def broken_observe(self, key, value):
        self._observe_locked(key, value)      # VIOLATION: no lock
'''

SELFTEST_COW = '''
class StateStore:
    def _materialize_block_locked(self, block):
        key = (block.namespace, block.source)
        vol = self._csi_volumes.get(key)          # snapshot-shared
        if vol is None or block.id not in vol.read_blocks:
            return
        vol.read_blocks.pop(block.id, None)       # VIOLATION (the leak)
        vol.read_allocs.update({a: "" for a in block.ids})  # VIOLATION

    def _claim_ok_locked(self, key, alloc):
        vol = self._writable_claim_vol(key)       # head-private copy
        if vol is None:
            return
        vol.read_allocs[alloc.id] = alloc.node_id  # ok: blessed

    def delete_thing(self, key):
        self._csi_volumes.pop(key, None)          # VIOLATION: direct

    def _release_claims_locked(self, key, aid):
        import dataclasses
        vol = self._csi_volumes.get(key)
        v = dataclasses.replace(vol)              # shallow: dicts shared
        v.modify_index = 7                        # ok: fresh outer object
        v.read_allocs.pop(aid, None)              # VIOLATION: inner dict

    def snapshot_restore(self, doc):
        self._csi_volumes = {}
        self._csi_volumes[("ns", "v")] = doc      # ok: fresh rebind
'''

SELFTEST_PURITY = '''
import jax
import jax.numpy as jnp
import numpy as np


def kernel(used, cap):
    free = cap - used
    total = np.asarray(free)                  # VIOLATION: np inside jit
    return jnp.sum(free) + float(total.sum())  # VIOLATION: float(traced)


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    best = jnp.argmax(out)                    # VIOLATION: eager jnp
    stale = used + 1                          # VIOLATION: donated reuse
    return best, stale


def collect(buf):
    buf.block_until_ready()                   # VIOLATION: host sync
    return buf
'''

SELFTEST_THREAD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        self.establish_leadership()           # VIOLATION: dies silently

    def _guarded_loop(self):
        while True:
            try:
                self.tick()
            except Exception:
                pass

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
        threading.Thread(target=self._guarded_loop).start()   # ok

    def run_scenario(self):
        # ok: chaos-managed wrapper (runner joins it and surfaces the
        # death via failed_ops), recognized by its name= prefix
        threading.Thread(target=self._workload_loop, daemon=True,
                         name=f"chaos-workload-{self.name}").start()

    def _workload_loop(self):
        self.drive()                          # no handler, but managed
'''

SELFTEST_PROC = '''
import multiprocessing as mp


def pool_main(idx):
    run(idx)                                  # VIOLATION: no handler


def pool_main_ok(idx):
    try:
        run(idx)
    except Exception:
        pass


class Pool:
    def spawn(self, ctx):
        ctx.Process(target=pool_main).start()         # VIOLATION: unnamed
        p = mp.Process(target=pool_main_ok,
                       name="pool-worker-0")          # ok: named + handled
        p.start()
'''

SELFTEST_RAWTIME = '''
import time
from time import monotonic as mono


class HeartbeatTimers:
    def expire(self, now=None):
        t = now if now is not None else time.time()   # VIOLATION
        return t

    def backoff(self):
        time.sleep(0.25)                              # VIOLATION

    def deadline(self):
        return mono() + 30.0                          # VIOLATION: alias

    def ok_paths(self):
        start = time.perf_counter()                   # ok: host duration
        t = self.clock.time()                         # ok: injected seam
        self.clock.sleep(0.1)                         # ok: injected seam
        return start, t
'''


def selftest() -> int:
    ok = True

    def expect(name: str, text: str, want: int, must_contain: str = ""
               ) -> None:
        nonlocal ok
        got = [f for f in analyze_source(text, passes=(name,))
               if f[2] == name]
        if len(got) != want:
            print(f"analyze selftest FAILED [{name}]: expected {want} "
                  f"finding(s), got {len(got)}: {got}")
            ok = False
            return
        if must_contain and not any(must_contain in f[3] for f in got):
            print(f"analyze selftest FAILED [{name}]: no finding "
                  f"mentions {must_contain!r}: {got}")
            ok = False

    expect("lock", SELFTEST_LOCK, 3, "outside")
    expect("cow", SELFTEST_COW, 4, "_writable_")
    expect("purity", SELFTEST_PURITY, 5, "DONATED")
    expect("thread", SELFTEST_THREAD, 1, "_on_raft_leader")
    expect("thread", SELFTEST_PROC, 2, "name=")
    expect("rawtime", SELFTEST_RAWTIME, 3, "bypasses the injected")
    # suppression: the same violations annotated away must go quiet
    suppressed = SELFTEST_THREAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok thread")
    expect("thread", suppressed, 0)
    if ok:
        print("analyze selftest ok: every pass caught its injected "
              "violation (lock=3 cow=4 purity=5 thread=1+2 rawtime=3, "
              "suppression honored)")
        return 0
    return 1


def main() -> int:
    if "--selftest" in sys.argv:
        return selftest()
    findings = analyze_repo()
    for path, lineno, name, msg in findings:
        rel = str(Path(path)) if not str(path).startswith(str(ROOT)) \
            else str(Path(path).relative_to(ROOT))
        print(f"{rel}:{lineno}: [{name}] {msg}")
    n_files = sum(len(v) for v in _scoped_files().values())
    print(f"analyze: {len(findings)} finding(s) over {n_files} "
          "pass-file runs")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
