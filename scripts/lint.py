#!/usr/bin/env python
"""Self-contained lint (no external linters in the image): AST +
text-level checks over nomad_tpu/, tests/, bench.py.

Checks:
  - syntax (ast.parse)
  - unused imports (module scope, names never referenced)
  - stray debug prints in library code (cli/ui/agent/bench/__main__ and
    scripts/ legitimately print)
  - trailing whitespace / tabs
  - lines > 99 chars
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PRINT_OK = {"cli.py", "ui.py", "agent.py", "__main__.py", "bench.py",
            "logging.py", "__graft_entry__.py"}


def imported_names(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                yield node, a.asname or a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    continue
                yield node, a.asname or a.name


def lint_file(path: Path) -> list:
    problems = []
    text = path.read_text()
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= {n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)}
    # names referenced only inside string annotations / __all__ exports
    used |= set(text.split())       # crude but kills false positives
    if path.name != "__init__.py":      # __init__ re-exports are the API
        for node, name in imported_names(tree):
            if name not in used:
                problems.append(
                    f"{path}:{node.lineno}: unused import {name!r}")

    if (path.name not in PRINT_OK and "tests" not in path.parts
            and "scripts" not in path.parts):
        lines = text.splitlines()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                line = lines[node.lineno - 1] if node.lineno <= len(lines) \
                    else ""
                if "lint: allow-print" in line:
                    continue     # deliberate (plugin handshake protocol)
                problems.append(
                    f"{path}:{node.lineno}: print() in library code "
                    "(use core.logging.log, or '# lint: allow-print')")

    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if "\t" in line:
            problems.append(f"{path}:{i}: tab character")
        if len(line) > 99:
            problems.append(f"{path}:{i}: line > 99 chars ({len(line)})")
    return problems


def main() -> int:
    targets = [ROOT / "bench.py", ROOT / "__graft_entry__.py"]
    for pkg in ("nomad_tpu", "tests", "scripts"):
        targets.extend(sorted((ROOT / pkg).rglob("*.py")))
    problems = []
    for path in targets:
        if "__pycache__" in path.parts:
            continue
        problems.extend(lint_file(path))
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s) over {len(targets)} files")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
