#!/usr/bin/env python
"""Self-contained lint (no external linters in the image): AST +
text-level checks over nomad_tpu/, tests/, bench.py.

Checks:
  - syntax (ast.parse)
  - UNDEFINED NAMES: pyflakes-class lexical-scope name resolution
    (two-pass: collect bindings per scope, then resolve every Name load
    through the function-scope chain + module + builtins; class bodies
    don't leak into nested scopes; star-imports poison the whole module
    honestly) — round-5 verdict #9
  - unused function-local variables (assigned once, never read,
    non-underscore)
  - unused imports (module scope, names never referenced)
  - stray debug prints in library code (cli/ui/agent/bench/__main__ and
    scripts/ legitimately print)
  - trailing whitespace / tabs
  - lines > 99 chars

`--selftest` lints an injected undefined-name snippet and exits 0 only
if the checker catches it (the CI stage proving the net has no hole).
"""

from __future__ import annotations

import ast
import builtins
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PRINT_OK = {"cli.py", "ui.py", "agent.py", "__main__.py", "bench.py",
            "logging.py", "__graft_entry__.py"}

BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
}


class _Scope:
    __slots__ = ("kind", "parent", "bindings", "globals", "nonlocals",
                 "wild", "loads", "stores", "reads")

    def __init__(self, kind: str, parent: "_Scope | None"):
        self.kind = kind                 # module | function | class | comp
        self.parent = parent
        self.bindings: set = set()
        self.globals: set = set()
        self.nonlocals: set = set()
        self.wild = parent.wild if parent else False   # star-import taint
        self.loads: list = []            # (name, lineno)
        self.stores: dict = {}           # name -> [linenos] (simple assigns)
        self.reads: set = set()          # names loaded in this scope


class _ScopeBuilder(ast.NodeVisitor):
    """Pass 1: build the scope tree, record bindings and loads."""

    def __init__(self):
        self.module = _Scope("module", None)
        self.cur = self.module
        self.scopes = [self.module]

    # -- helpers -----------------------------------------------------

    def _push(self, kind):
        s = _Scope(kind, self.cur)
        self.scopes.append(s)
        self.cur = s
        return s

    def _pop(self):
        self.cur = self.cur.parent

    def _bind(self, name):
        if name in self.cur.globals:
            self.module.bindings.add(name)
        else:
            self.cur.bindings.add(name)

    def _bind_target(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self._bind(n.id)

    # -- bindings ----------------------------------------------------

    def visit_Import(self, node):
        for a in node.names:
            self._bind(a.asname or a.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for a in node.names:
            if a.name == "*":
                self.cur.wild = True
                # taint descendants created later via _Scope.__init__;
                # existing module scope is the usual case
            else:
                self._bind(a.asname or a.name)

    def visit_Global(self, node):
        self.cur.globals.update(node.names)

    def visit_Nonlocal(self, node):
        self.cur.nonlocals.update(node.names)

    def _visit_func(self, node):
        self._bind(node.name)
        for d in node.decorator_list:
            self.visit(d)
        a = node.args
        for dflt in list(a.defaults) + [d for d in a.kw_defaults if d]:
            self.visit(dflt)
        s = self._push("function")
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            s.bindings.add(arg.arg)
            if arg.annotation:
                self.visit(arg.annotation)
        if node.returns:
            self.visit(node.returns)
        for stmt in node.body:
            self.visit(stmt)
        self._pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._bind(node.name)
        for d in node.decorator_list:
            self.visit(d)
        for b in node.bases + node.keywords:
            self.visit(b.value if isinstance(b, ast.keyword) else b)
        self._push("class")
        for stmt in node.body:
            self.visit(stmt)
        self._pop()

    def visit_Lambda(self, node):
        a = node.args
        for dflt in list(a.defaults) + [d for d in a.kw_defaults if d]:
            self.visit(dflt)
        s = self._push("function")
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            s.bindings.add(arg.arg)
        self.visit(node.body)
        self._pop()

    def _visit_comp(self, node):
        # first iterable evaluates in the enclosing scope
        self.visit(node.generators[0].iter)
        self._push("comp")               # py3 comprehension scope
        for i, gen in enumerate(node.generators):
            self._bind_target(gen.target)
            if i:
                self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self._pop()

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self._record_simple_store(t, node.lineno)
            self._bind_target(t)
            self.visit(t)

    def _record_simple_store(self, target, lineno):
        if (isinstance(target, ast.Name)
                and self.cur.kind == "function"):
            self.cur.stores.setdefault(target.id, []).append(lineno)

    def visit_AnnAssign(self, node):
        if node.value:
            self.visit(node.value)
        self.visit(node.annotation)
        self._bind_target(node.target)
        self.visit(node.target)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        # target is read+written: record the load
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                self.cur.loads.append((n.id, n.lineno))
                self.cur.reads.add(n.id)
        self._bind_target(node.target)
        self.visit(node.target)

    def visit_For(self, node):
        self.visit(node.iter)
        self._bind_target(node.target)
        self.visit(node.target)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_withitem(self, node):
        self.visit(node.context_expr)
        if node.optional_vars is not None:
            self._bind_target(node.optional_vars)
            self.visit(node.optional_vars)

    def visit_ExceptHandler(self, node):
        if node.type:
            self.visit(node.type)
        if node.name:
            self._bind(node.name)
        for stmt in node.body:
            self.visit(stmt)

    def visit_NamedExpr(self, node):
        self.visit(node.value)
        # binds in the nearest non-comprehension scope
        s = self.cur
        while s.kind == "comp":
            s = s.parent
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                s.bindings.add(n.id)

    def visit_MatchAs(self, node):      # match patterns bind names
        if node.pattern:
            self.visit(node.pattern)
        if node.name:
            self._bind(node.name)

    def visit_MatchStar(self, node):
        if node.name:
            self._bind(node.name)

    def visit_MatchMapping(self, node):
        self.generic_visit(node)
        if node.rest:
            self._bind(node.rest)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.cur.loads.append((node.id, node.lineno))
            self.cur.reads.add(node.id)
        elif isinstance(node.ctx, ast.Store):
            self._bind(node.id)

    def visit_Delete(self, node):
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    self.cur.reads.add(n.id)


def _resolves(scope: _Scope, name: str, module: _Scope) -> bool:
    """Lexical resolution: function-scope chain (class bodies skipped for
    enclosed scopes), then module, then builtins."""
    if name in BUILTINS:
        return True
    s = scope
    first = True
    while s is not None:
        if s.wild:
            return True
        if name in s.globals:
            return name in module.bindings or module.wild
        if (first or s.kind != "class") and name in s.bindings:
            return True
        s = s.parent
        first = False
    return False


def check_names(tree: ast.Module) -> list:
    """Undefined-name + unused-local findings: (lineno, message)."""
    b = _ScopeBuilder()
    b.visit(tree)
    out = []
    # child-scope reads: a local assigned in f but read only by a nested
    # scope is still used (closures)
    reads_below: dict = {}
    for s in b.scopes:
        p = s.parent
        while p is not None:
            reads_below.setdefault(id(p), set()).update(s.reads)
            p = p.parent
    for s in b.scopes:
        for name, lineno in s.loads:
            if not _resolves(s, name, b.module):
                out.append((lineno, f"undefined name {name!r}"))
        if s.kind == "function" and not s.wild:
            below = reads_below.get(id(s), set())
            for name, linenos in s.stores.items():
                if (name.startswith("_") or name in s.reads
                        or name in below or name in s.globals
                        or name in s.nonlocals or len(linenos) != 1):
                    continue
                out.append((linenos[0], f"unused variable {name!r}"))
    return out


def imported_names(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                yield node, a.asname or a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    continue
                yield node, a.asname or a.name


def lint_file(path: Path) -> list:
    problems = []
    text = path.read_text()
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    for lineno, msg in check_names(tree):
        problems.append(f"{path}:{lineno}: {msg}")

    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= {n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)}
    # names referenced only inside string annotations / __all__ exports
    used |= set(text.split())       # crude but kills false positives
    if path.name != "__init__.py":      # __init__ re-exports are the API
        for node, name in imported_names(tree):
            if name not in used:
                problems.append(
                    f"{path}:{node.lineno}: unused import {name!r}")

    if (path.name not in PRINT_OK and "tests" not in path.parts
            and "scripts" not in path.parts):
        lines = text.splitlines()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                line = lines[node.lineno - 1] if node.lineno <= len(lines) \
                    else ""
                if "lint: allow-print" in line:
                    continue     # deliberate (plugin handshake protocol)
                problems.append(
                    f"{path}:{node.lineno}: print() in library code "
                    "(use core.logging.log, or '# lint: allow-print')")

    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if "\t" in line:
            problems.append(f"{path}:{i}: tab character")
        if len(line) > 99:
            problems.append(f"{path}:{i}: line > 99 chars ({len(line)})")
    return problems


SELFTEST_SNIPPET = """
import os

def f(x):
    y = x + os.sep
    return y + undefined_name_xyz

class C:
    attr = 1

def g():
    unused_local = 3
    return C().attr
"""


def selftest() -> int:
    """The CI stage proving the checker catches an injected undefined
    name (and an unused local), and stays quiet on the clean parts."""
    findings = check_names(ast.parse(SELFTEST_SNIPPET))
    msgs = [m for _, m in findings]
    want = ["undefined name 'undefined_name_xyz'",
            "unused variable 'unused_local'"]
    missing = [w for w in want if w not in msgs]
    extra = [m for m in msgs if m not in want]
    if missing or extra:
        print(f"lint selftest FAILED: missing={missing} extra={extra}")
        return 1
    print("lint selftest ok: injected undefined name caught")
    return 0


def main() -> int:
    if "--selftest" in sys.argv:
        return selftest()
    targets = [ROOT / "bench.py", ROOT / "__graft_entry__.py"]
    for pkg in ("nomad_tpu", "tests", "scripts"):
        targets.extend(sorted((ROOT / pkg).rglob("*.py")))
    problems = []
    for path in targets:
        if "__pycache__" in path.parts:
            continue
        problems.extend(lint_file(path))
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s) over {len(targets)} files")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
