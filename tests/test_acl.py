"""ACL policies/tokens + enforcement, namespaces, node pools, variables,
operator snapshot (reference: acl/, nomad/acl.go, structs variables,
`nomad operator snapshot`)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.acl import compile_acl, parse_policy
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient, APIException
from nomad_tpu.core import Server
from nomad_tpu.structs import codec

try:                                  # the image may lack the optional
    import cryptography  # noqa: F401 - AEAD/RSA dep (gated, not assumed)
    HAS_CRYPTO = True
except ModuleNotFoundError:
    HAS_CRYPTO = False

requires_crypto = pytest.mark.skipif(
    not HAS_CRYPTO, reason="cryptography not installed in this image")


HCL_POLICY = '''
namespace "default" { policy = "write" }
namespace "ops-*"   { capabilities = ["read-job", "list-jobs"] }
node     { policy = "read" }
operator { policy = "read" }
'''


class TestPolicyParsing:
    def test_hcl_policy(self):
        p = parse_policy(HCL_POLICY)
        assert len(p.namespaces) == 2
        assert p.namespaces[0].policy == "write"
        assert p.node == "read" and p.operator == "read"

    def test_json_policy(self):
        p = parse_policy(
            '{"Namespaces": {"default": {"Policy": "read"}}, '
            '"Agent": "write"}')
        assert p.namespaces[0].policy == "read"
        assert p.agent == "write"

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_policy('namespace "x" { policy = "root" }')
        with pytest.raises(ValueError):
            parse_policy('namespace "x" { capabilities = ["fly"] }')

    def test_compiled_acl_semantics(self):
        acl = compile_acl([parse_policy(HCL_POLICY)])
        assert acl.allow_namespace_operation("default", "submit-job")
        assert acl.allow_namespace_operation("ops-east", "read-job")
        assert not acl.allow_namespace_operation("ops-east", "submit-job")
        assert not acl.allow_namespace_operation("secret", "read-job")
        assert acl.allow_node_read() and not acl.allow_node_write()
        assert acl.allow_operator_read() and not acl.allow_operator_write()
        assert not acl.allow_agent_read()

    def test_glob_longest_match_and_deny(self):
        acl = compile_acl([parse_policy('''
namespace "*"       { policy = "read" }
namespace "secret*" { policy = "deny" }
''')])
        assert acl.allow_namespace_operation("web", "read-job")
        assert not acl.allow_namespace_operation("secret-x", "read-job")


@pytest.fixture(scope="module")
def acl_agent():
    ag = Agent(num_clients=1, heartbeat_ttl=3600, acl_enabled=True)
    ag.start()
    yield ag
    ag.shutdown()


class TestACLEnforcement:
    def test_bootstrap_and_enforcement(self, acl_agent):
        anon = APIClient(address=acl_agent.address)
        with pytest.raises(APIException) as e:
            anon.jobs.list()
        assert e.value.status == 403

        boot = anon.acl.bootstrap()
        mgmt = APIClient(address=acl_agent.address,
                         token=boot["SecretID"])
        assert mgmt.jobs.list() == []

        # second bootstrap rejected
        with pytest.raises(APIException):
            anon.acl.bootstrap()

        # scoped client token: read-only default namespace
        mgmt.acl.upsert_policy(
            "readonly", 'namespace "default" { policy = "read" }')
        tok = mgmt.acl.create_token(name="ro", policies=["readonly"])
        ro = APIClient(address=acl_agent.address, token=tok["SecretID"])
        assert ro.jobs.list() == []
        job = mock.batch_job()
        with pytest.raises(APIException) as e:
            ro.jobs.register(codec.encode(job))
        assert e.value.status == 403
        mgmt.jobs.register(codec.encode(job))      # management can
        assert any(s["ID"] == job.id for s in ro.jobs.list())

        # token list hides secrets
        toks = mgmt.acl.tokens()
        assert all("SecretID" not in t for t in toks)

        # unknown token
        bad = APIClient(address=acl_agent.address, token="nope")
        with pytest.raises(APIException) as e:
            bad.jobs.list()
        assert e.value.status == 403


class TestACLSecurityRegressions:
    @pytest.fixture(scope="class")
    def setup(self):
        ag = Agent(num_clients=1, heartbeat_ttl=3600, acl_enabled=True)
        ag.start()
        anon = APIClient(address=ag.address)
        boot = anon.acl.bootstrap()
        mgmt = APIClient(address=ag.address, token=boot["SecretID"])
        yield ag, mgmt
        ag.shutdown()

    def test_body_namespace_cannot_escape_grant(self, setup):
        ag, mgmt = setup
        mgmt.namespaces.apply("dev")
        mgmt.namespaces.apply("prod2")
        mgmt.acl.upsert_policy(
            "dev-w", 'namespace "dev" { policy = "write" }')
        tok = mgmt.acl.create_token(name="dev", policies=["dev-w"])
        dev = APIClient(address=ag.address, namespace="dev",
                        token=tok["SecretID"])
        wire = codec.encode(mock.batch_job())
        wire["Namespace"] = "prod2"
        with pytest.raises(APIException) as e:
            dev.jobs.register(wire)
        assert e.value.status == 403, \
            "body namespace must not escape the granted namespace"

    def test_by_id_lookup_enforces_object_namespace(self, setup):
        ag, mgmt = setup
        job = mock.batch_job()
        job.namespace = "prod2"
        job.task_groups[0].count = 1
        wire = codec.encode(job)
        mgmt.request("PUT", "/v1/jobs", params={"namespace": "prod2"},
                     body={"Job": wire})
        import time
        deadline = time.time() + 30
        allocs = []
        while time.time() < deadline and not allocs:
            allocs = mgmt.request("GET", f"/v1/job/{job.id}/allocations",
                                  params={"namespace": "prod2"})
            time.sleep(0.3)
        assert allocs, "prod2 job never placed"
        aid = allocs[0]["ID"]
        tok = mgmt.acl.create_token(name="dev2", policies=["dev-w"])
        dev = APIClient(address=ag.address, namespace="dev",
                        token=tok["SecretID"])
        with pytest.raises(APIException) as e:
            dev.allocations.info(aid)
        assert e.value.status == 403
        with pytest.raises(APIException) as e:
            dev.allocations.stop(aid)
        assert e.value.status == 403

    def test_snapshot_requires_management(self, setup):
        ag, mgmt = setup
        mgmt.acl.upsert_policy(
            "op-read", 'operator { policy = "read" }')
        tok = mgmt.acl.create_token(name="op", policies=["op-read"])
        op = APIClient(address=ag.address, token=tok["SecretID"])
        assert op.operator.scheduler_config()    # operator read works
        with pytest.raises(APIException) as e:
            op.operator.snapshot_save()
        assert e.value.status == 403
        assert mgmt.operator.snapshot_save()["ACLTokens"]

    def test_token_rotation_revokes_old_secret(self, setup):
        ag, mgmt = setup
        from nomad_tpu.structs import ACLToken
        s = ag.server
        t = ACLToken(name="rot", policies=["dev-w"])
        s.state.upsert_acl_token(t)
        old_secret = t.secret_id
        import dataclasses
        t2 = dataclasses.replace(t)
        t2.secret_id = "new-" + old_secret
        s.state.upsert_acl_token(t2)
        assert s.state.acl_token_by_secret(old_secret) is None
        assert s.state.acl_token_by_secret(t2.secret_id) is not None

    def test_bootstrap_is_atomic(self):
        import threading
        from nomad_tpu.core import Server
        s = Server(dev_mode=True, acl_enabled=True)
        results = []

        def boot():
            tok, err = s.bootstrap_acl()
            results.append(tok)

        threads = [threading.Thread(target=boot) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(1 for r in results if r is not None) == 1


class TestNamespacesAndPoolsAndVars:
    @pytest.fixture(scope="class")
    def api(self):
        ag = Agent(num_clients=1, heartbeat_ttl=3600)
        ag.start()
        yield APIClient(address=ag.address)
        ag.shutdown()

    def test_namespace_crud(self, api):
        api.namespaces.apply("prod", description="production")
        names = {n["Name"] for n in api.namespaces.list()}
        assert {"default", "prod"} <= names
        api.namespaces.delete("prod")
        assert "prod" not in {n["Name"] for n in api.namespaces.list()}
        with pytest.raises(APIException):
            api.namespaces.delete("default")

    def test_node_pool_crud(self, api):
        api.node_pools.apply("gpu", description="accelerators")
        assert "gpu" in {n["Name"] for n in api.node_pools.list()}
        api.node_pools.delete("gpu")
        with pytest.raises(APIException):
            api.node_pools.delete("all")

    def test_variables_crud(self, api):
        api.variables.write("app/config", {"db": "pg://x", "key": "v"})
        v = api.variables.read("app/config")
        assert v["Items"]["db"] == "pg://x"
        assert [x["Path"] for x in api.variables.list(prefix="app/")] \
            == ["app/config"]
        api.variables.delete("app/config")
        with pytest.raises(APIException):
            api.variables.read("app/config")


class TestSnapshot:
    def test_save_restore_round_trip(self):
        s = Server(dev_mode=True, heartbeat_ttl=10**9)
        s.establish_leadership()
        for _ in range(3):
            s.register_node(mock.node(), now=1000.0)
        job = mock.batch_job()
        job.task_groups[0].count = 4
        s.register_job(job, now=1000.0)
        s.process_all(now=1000.0)
        allocs_before = s.state.allocs_by_job(job.namespace, job.id)
        assert len(allocs_before) == 4

        doc = s.save_snapshot()

        s2 = Server(dev_mode=True, heartbeat_ttl=10**9)
        s2.restore_snapshot(doc)
        snap = s2.state.snapshot()
        assert len(snap.nodes()) == 3
        restored_job = snap.job_by_id(job.namespace, job.id)
        assert restored_job is not None
        allocs = snap.allocs_by_job(job.namespace, job.id)
        assert len(allocs) == 4
        assert all(a.job is not None for a in allocs), \
            "job pointers re-attached"
        # the restored server keeps scheduling: kill a node's allocs
        victim = allocs[0].node_id
        s2.update_node_status(victim, "down", now=2000.0)
        s2.process_all(now=2000.0)
        live = [a for a in
                s2.state.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status() and a.desired_status == "run"]
        assert len(live) == 4, "reschedule works on restored state"
        assert all(a.node_id != victim for a in live)


class TestAuthMethods:
    """JWT auth methods + binding rules (reference: ACL.Login,
    structs.ACLAuthMethod/ACLBindingRule; `nomad login`)."""

    @staticmethod
    def _hs256_jwt(secret, claims):
        import base64 as b64
        import hashlib
        import hmac
        import json as j

        def enc(d):
            return b64.urlsafe_b64encode(
                j.dumps(d, separators=(",", ":")).encode()
            ).rstrip(b"=").decode()

        h = enc({"alg": "HS256", "typ": "JWT"})
        c = enc(claims)
        sig = hmac.new(secret.encode(), f"{h}.{c}".encode(),
                       hashlib.sha256).digest()
        return f"{h}.{c}." + b64.urlsafe_b64encode(
            sig).rstrip(b"=").decode()

    def _setup(self, **cfg):
        import time

        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import ACLAuthMethod, ACLBindingRule
        st = StateStore()
        st.upsert_acl_auth_method(ACLAuthMethod(
            name="gha", type="JWT",
            config={"JWTValidationSecrets": ["top-secret"], **cfg}))
        st.upsert_acl_binding_rule(ACLBindingRule(
            auth_method="gha",
            selector="claims.repo==acme/app",
            bind_type="policy",
            bind_name="deploy-${claims.env}"))
        return st, time.time()

    def test_login_happy_path_binds_policies(self):
        from nomad_tpu.acl.auth_methods import login
        st, now = self._setup()
        jwt = self._hs256_jwt("top-secret", {
            "sub": "runner-1", "repo": "acme/app", "env": "prod",
            "exp": int(now) + 300})
        tok, policies = login(st, "gha", jwt, now=now)
        assert tok.type == "client"
        assert policies == ["deploy-prod"]

    def test_selector_mismatch_refused(self):
        import pytest as _pytest

        from nomad_tpu.acl.auth_methods import AuthError, login
        st, now = self._setup()
        jwt = self._hs256_jwt("top-secret", {
            "repo": "other/repo", "env": "prod", "exp": int(now) + 300})
        with _pytest.raises(AuthError, match="no binding rules"):
            login(st, "gha", jwt, now=now)

    def test_bad_signature_expiry_issuer_audience(self):
        import pytest as _pytest

        from nomad_tpu.acl.auth_methods import AuthError, login
        st, now = self._setup(BoundIssuer="https://ci.example",
                              BoundAudiences=["nomad"])
        ok = {"repo": "acme/app", "env": "x", "iss": "https://ci.example",
              "aud": "nomad", "exp": int(now) + 300}
        # wrong secret
        with _pytest.raises(AuthError, match="signature"):
            login(st, "gha", self._hs256_jwt("wrong", ok), now=now)
        # expired
        with _pytest.raises(AuthError, match="expired"):
            login(st, "gha", self._hs256_jwt(
                "top-secret", {**ok, "exp": int(now) - 10}), now=now)
        # wrong issuer
        with _pytest.raises(AuthError, match="issuer"):
            login(st, "gha", self._hs256_jwt(
                "top-secret", {**ok, "iss": "https://evil"}), now=now)
        # wrong audience
        with _pytest.raises(AuthError, match="audience"):
            login(st, "gha", self._hs256_jwt(
                "top-secret", {**ok, "aud": "other"}), now=now)
        # all bound constraints satisfied -> success
        tok, _ = login(st, "gha", self._hs256_jwt("top-secret", ok),
                       now=now)
        assert tok.policies == ["deploy-x"]

    @requires_crypto
    def test_rs256_via_cryptography(self):
        import base64 as b64
        import json as j
        import time

        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import (
            padding, rsa)

        from nomad_tpu.acl.auth_methods import login
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import ACLAuthMethod, ACLBindingRule

        key = rsa.generate_private_key(public_exponent=65537,
                                       key_size=2048)
        pem = key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo).decode()

        def enc(d):
            return b64.urlsafe_b64encode(
                j.dumps(d, separators=(",", ":")).encode()
            ).rstrip(b"=").decode()

        now = time.time()
        h = enc({"alg": "RS256", "typ": "JWT"})
        c = enc({"sub": "svc", "exp": int(now) + 60})
        sig = key.sign(f"{h}.{c}".encode(), padding.PKCS1v15(),
                       hashes.SHA256())
        jwt = f"{h}.{c}." + b64.urlsafe_b64encode(
            sig).rstrip(b"=").decode()

        st = StateStore()
        st.upsert_acl_auth_method(ACLAuthMethod(
            name="pki", type="JWT",
            config={"JWTValidationPubKeys": [pem]}))
        st.upsert_acl_binding_rule(ACLBindingRule(
            auth_method="pki", bind_type="management"))
        tok, _ = login(st, "pki", jwt, now=now)
        assert tok.is_management()

    def test_oidc_rejected_at_creation(self):
        from nomad_tpu.acl.auth_methods import validate_method
        from nomad_tpu.structs import ACLAuthMethod
        err = validate_method(ACLAuthMethod(name="sso", type="OIDC"))
        assert err and "unsupported" in err

    def test_http_login_flow_unauthenticated(self):
        """POST /v1/acl/login works WITHOUT a token on an ACL-enabled
        agent, and the minted token then authenticates."""
        import time
        import urllib.request

        from nomad_tpu.agent import Agent
        ag = Agent(num_clients=0, acl_enabled=True)
        ag.start()
        try:
            import json as j

            def req(method, path, body=None, token=""):
                r = urllib.request.Request(
                    ag.address + path,
                    data=j.dumps(body).encode() if body else None,
                    method=method)
                if body:
                    r.add_header("Content-Type", "application/json")
                if token:
                    r.add_header("X-Nomad-Token", token)
                with urllib.request.urlopen(r) as resp:
                    return j.load(resp)

            boot = req("POST", "/v1/acl/bootstrap")
            mgmt = boot["SecretID"]
            req("POST", "/v1/acl/policy/reader",
                body={"Rules":
                      'namespace "default" { policy = "read" }'},
                token=mgmt)
            req("POST", "/v1/acl/auth-method/ci", token=mgmt,
                body={"Type": "JWT",
                      "Config": {"JWTValidationSecrets": ["s3cr3t"]}})
            req("POST", "/v1/acl/binding-rule", token=mgmt,
                body={"AuthMethod": "ci", "BindType": "policy",
                      "BindName": "reader"})
            jwt = self._hs256_jwt("s3cr3t", {
                "sub": "bot", "exp": int(time.time()) + 60})
            tok = req("POST", "/v1/acl/login",
                      body={"AuthMethodName": "ci", "LoginToken": jwt})
            assert tok["Policies"] == ["reader"]
            # the minted token authenticates (reads jobs)
            jobs = req("GET", "/v1/jobs", token=tok["SecretID"])
            assert isinstance(jobs, list)
        finally:
            ag.shutdown()

    def test_minted_token_expires(self):
        """Login tokens carry the method's max TTL (never outliving the
        JWT) and resolve_token refuses them after expiry."""
        from nomad_tpu.acl.auth_methods import login
        st, now = self._setup()
        m = st.acl_auth_method_by_name("gha")
        m.max_token_ttl_s = 60.0
        st.upsert_acl_auth_method(m)
        jwt = self._hs256_jwt("top-secret", {
            "repo": "acme/app", "env": "prod", "exp": int(now) + 3600})
        tok, _ = login(st, "gha", jwt, now=now)
        assert abs(tok.expiration_time - (now + 60.0)) < 2
        assert not tok.expired(now + 30)
        assert tok.expired(now + 61)

        # end to end through resolve_token on an ACL server
        from nomad_tpu.core.server import Server
        s = Server(dev_mode=True, acl_enabled=True)
        s.establish_leadership()
        s.state.upsert_acl_token(tok)
        acl, err = s.resolve_token(tok.secret_id)
        assert acl is not None
        import time as _time
        # simulate expiry by rewinding the expiration to the past
        expired = tok
        expired.expiration_time = _time.time() - 5
        s.state.upsert_acl_token(expired)
        acl2, err2 = s.resolve_token(expired.secret_id)
        assert acl2 is None and "expired" in err2

    def test_default_method_fallback(self):
        from nomad_tpu.acl.auth_methods import login
        st, now = self._setup()
        m = st.acl_auth_method_by_name("gha")
        m.default = True
        st.upsert_acl_auth_method(m)
        jwt = self._hs256_jwt("top-secret", {
            "repo": "acme/app", "env": "ci", "exp": int(now) + 300})
        tok, policies = login(st, "", jwt, now=now)
        assert policies == ["deploy-ci"]

    def test_expired_tokens_reaped_by_gc(self):
        import time

        from nomad_tpu.core.server import Server
        from nomad_tpu.structs import ACLToken
        s = Server(dev_mode=True, acl_enabled=True)
        s.establish_leadership()
        now = time.time()
        dead = ACLToken(name="old-login", expiration_time=now - 10)
        live = ACLToken(name="fresh", expiration_time=now + 3600)
        forever = ACLToken(name="static")
        for t in (dead, live, forever):
            s.state.upsert_acl_token(t)
        s.force_gc(now=now)
        s.process_all(now=now)
        names = {t.name for t in s.state.acl_tokens()}
        assert "old-login" not in names
        assert {"fresh", "static"} <= names
