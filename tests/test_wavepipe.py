"""Wave-pipelined commit engine (core/wavepipe.py).

The pipelining contract, proven rather than asserted:
  - wave k+1's device dispatch STARTS before wave k's host commit
    COMPLETES (stage-timer intervals), with capacity still coupled
    through the device-side usage chain;
  - rows the applier refutes are masked out of the next chained
    dispatch's constraint input and are never double-committed — the
    repair re-places only the missing rows;
  - the pipelined columnar commit paths (fenced wholesale, full-check
    columnar, forced per-alloc expansion, plain Harness) all land
    IDENTICAL final state-store contents for the same eval batch.
"""

import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.core.wavepipe import StageTimers, WavePipeline
from nomad_tpu.ops.engine import BatchItem
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import Allocation, Resources, new_id

NOW = 1.7e9


def executor_backends():
    """Every device-executor backend runnable in this process: 'jax'
    always; 'bridge' when the native build + PJRT plugin exist."""
    backs = ["jax"]
    try:
        from nomad_tpu.native.bridge import bridge_available
        if bridge_available():
            backs.append("bridge")
    except Exception:  # noqa: BLE001 - no native stack at all
        pass
    return backs


def build_cluster(n_nodes=12, cpu=4000, mem=8192):
    h = Harness()
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = cpu
        n.resources.memory_mb = mem
        nodes.append(n)
    h.state.upsert_nodes(nodes)
    return h, nodes


def make_items(h, n_items, count, cpu=500, mem=64):
    items = []
    for _ in range(n_items):
        job = mock.batch_job()
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = cpu
        tg.tasks[0].resources.memory_mb = mem
        h.state.upsert_job(job)
        items.append(BatchItem(job=job, tg=tg, count=count))
    return items


def commit_decisions(h, items, decisions):
    """Host commit of a wave's picks as ordinary allocs (the test's
    stand-in for materialize+commit; the worker path is covered by the
    end-to-end tests below)."""
    allocs = []
    for it, bd in zip(items, decisions):
        ask = it.tg.combined_resources()
        for pick in bd.picks.tolist():
            if pick < 0:
                continue
            allocs.append(Allocation(
                id=new_id(), namespace=it.job.namespace, job_id=it.job.id,
                job=it.job, task_group=it.tg.name,
                node_id=bd.node_ids[pick], resources=ask,
                desired_status="run", client_status="pending"))
    h.state.upsert_allocs(allocs)
    return allocs


def picked_nodes(decisions):
    return {bd.node_ids[p] for bd in decisions
            for p in bd.picks.tolist() if p >= 0}


class TestStageTimers:
    def test_overlap_math(self):
        t = StageTimers()
        t.record("device", 0.0, 3.0, wave=2)
        t.record("commit", 1.0, 2.0, wave=1)
        t.record("commit", 2.5, 4.0, wave=2)
        assert abs(t.overlap("device", "commit") - 1.5) < 1e-9
        assert abs(t.totals()["commit"] - 2.5) < 1e-9
        rep = t.report()
        assert rep["overlap_s"]["device*commit"] == 1.5
        t.reset()
        assert t.totals() == {}


class TestPipelineOverlap:
    def test_next_wave_dispatches_before_prior_commit(self):
        """The pipelining contract itself: wave 2 is dispatched (chained
        on wave 1's device-side proposed usage) BEFORE wave 1's commit
        runs, the stage timers prove the ordering, and the committed
        result still never oversubscribes a node — i.e. the chain, not
        the store, carried wave 1's usage into wave 2's scoring."""
        h, nodes = build_cluster(n_nodes=6)
        timers = StageTimers()
        pipe = WavePipeline(h.engine, timers)
        snap = h.state.snapshot()
        # 2 waves x 12 asks of 1000 cpu vs 6 nodes x 3 usable slots:
        # wave 2 must see wave 1's proposed usage or nodes oversubscribe
        items1 = make_items(h, 3, 4, cpu=1000)
        items2 = make_items(h, 3, 4, cpu=1000)
        w1 = pipe.dispatch(snap, items1, seed=3)
        d1 = pipe.collect(w1)
        w2 = pipe.dispatch(snap, items2, seed=4,
                           used0_dev=pipe.chain_state(w1))
        with pipe.commit(w1.wave):
            commit_decisions(h, items1, d1)
        d2 = pipe.collect(w2)
        with pipe.commit(w2.wave):
            commit_decisions(h, items2, d2)

        disp = {w: (t0, t1) for w, t0, t1 in timers.intervals("dispatch")}
        com = {w: (t0, t1) for w, t0, t1 in timers.intervals("commit")}
        # wave 2's dispatch started before wave 1's commit completed
        assert disp[w2.wave][0] < com[w1.wave][1]
        # every stage of the pipeline reported wall time
        totals = timers.totals()
        for stage in ("dispatch", "device", "d2h", "commit"):
            assert stage in totals, totals
        # capacity stayed coupled across the chain: per-node cpu within
        # the usable envelope (4000 cap - 100 reserved)
        by_node = {}
        snap2 = h.state.snapshot()
        for n in nodes:
            cpu = sum(a.resources.cpu for a in snap2.allocs_by_node(n.id)
                      if not a.terminal_status())
            by_node[n.id] = cpu
            assert cpu <= 3900, (n.id, cpu)
        # and the cluster actually filled: 18 usable slots for 24 asks
        placed = sum(len(bd.picks[bd.picks >= 0]) for bd in d1 + d2)
        assert placed == 18, placed


class TestRefuteRepair:
    def test_masked_nodes_excluded_from_chained_dispatch(self):
        """A refuted node is the binpack kernel's FAVORITE node (most
        filled); the mask must beat that preference in the next chained
        wave, and a fresh dispatch must clear the mask."""
        h, nodes = build_cluster(n_nodes=8, cpu=8000, mem=16384)
        pipe = WavePipeline(h.engine)
        snap = h.state.snapshot()
        items1 = make_items(h, 2, 3, cpu=200)
        w1 = pipe.dispatch(snap, items1, seed=1)
        d1 = pipe.collect(w1)
        target = sorted(picked_nodes(d1))[0]
        pipe.note_refuted([target])
        assert target in pipe.masked_nodes()
        items2 = make_items(h, 2, 3, cpu=200)
        w2 = pipe.dispatch(snap, items2, seed=2,
                           used0_dev=pipe.chain_state(w1))
        d2 = pipe.collect(w2)
        assert (d2[0].picks >= 0).all() and (d2[1].picks >= 0).all()
        assert target not in picked_nodes(d2), "masked node re-picked"
        # a FRESH (unchained) dispatch sees committed state and clears
        # the mask
        items3 = make_items(h, 2, 3, cpu=200)
        w3 = pipe.dispatch(snap, items3, seed=3)
        pipe.collect(w3)
        assert not pipe.masked_nodes()

    def test_refuted_rows_repaired_not_double_committed(self):
        """End-to-end through the Server: a foreign write lands on a
        block's node between dispatch and commit, the applier refutes
        that node's rows COLUMNAR, the repair path masks the node +
        re-queues only the missing rows, and the final state carries
        exactly `count` live allocs per job — never a double commit."""
        s = Server(dev_mode=True, eval_batch=8)
        s.establish_leadership()
        nodes = []
        for _ in range(4):
            n = mock.node()
            n.resources.cpu = 8000
            n.resources.memory_mb = 16384
            s.register_node(n, now=NOW)
            nodes.append(n)
        jobs = []
        for _ in range(2):           # >=2 batchable evals -> one wave;
            job = mock.batch_job()   # count >= 64 -> columnar blocks
            job.task_groups[0].count = 80
            job.task_groups[0].tasks[0].resources.cpu = 100
            job.task_groups[0].tasks[0].resources.memory_mb = 64
            s.register_job(job, now=NOW)
            jobs.append(job)

        applier = s.plan_applier
        orig = applier._apply_one
        sabotage = {"armed": True, "node": None}

        def foreign_write_then_apply(pending):
            plan = pending.plan
            if sabotage["armed"] and plan.alloc_blocks:
                # hit the block's MOST-LOADED node (node_table order is
                # row-index order, not load order): >= 2 rows there stop
                # fitting, so the full re-check must refute them
                blk = plan.alloc_blocks[0]
                nid = blk.node_table[int(np.argmax(blk.node_counts()))]
                sabotage["armed"] = False
                sabotage["node"] = nid
                # fill the node: usable 7900, foreign takes 7800 -> the
                # block's 100-cpu rows there no longer fit (at most one)
                s.state.upsert_allocs([Allocation(
                    id=new_id(), namespace="default", job_id="foreign-job",
                    task_group="tg", node_id=nid,
                    resources=Resources(cpu=7800, memory_mb=64),
                    desired_status="run", client_status="pending")])
            return orig(pending)

        applier._apply_one = foreign_write_then_apply
        s.process_all(now=NOW)

        assert sabotage["node"] is not None, "no block plan was applied"
        assert applier.stats["plans_refuted"] >= 1, applier.stats
        # the refuted node went through the pipeline's mask (a later
        # FRESH dispatch legitimately clears it — committed state then
        # accounts the foreign write — so assert via the repair stats)
        pipe = s.workers[0].pipeline
        assert pipe.stats["repairs"] >= 1, pipe.stats
        assert pipe.stats["masked_nodes"] >= 1, pipe.stats
        snap = s.state.snapshot()
        for job in jobs:
            live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            # exactly count allocs — refuted rows re-placed ONCE
            assert len(live) == 80, (job.id, len(live))
            assert len({a.id for a in live}) == 80
        # the sabotaged node never oversubscribed (usable 7900)
        cpu = sum(a.resources.cpu
                  for a in snap.allocs_by_node(sabotage["node"])
                  if not a.terminal_status())
        assert cpu <= 7900, cpu
        # the repair eval is recorded and completed
        evs = [e for job in jobs
               for e in snap.evals_by_job(job.namespace, job.id)]
        assert any(e.triggered_by == "plan-refute-repair" for e in evs)
        assert all(e.status == "complete" for e in evs), \
            [(e.status, e.status_description) for e in evs]


def _fixed_cluster_nodes(n_nodes=16, seed=11):
    rng = random.Random(seed)
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = rng.choice([4000, 8000])
        n.resources.memory_mb = 16384
        nodes.append(n)
    return nodes


def _contents(state):
    """Comparable final-state fingerprint: every live alloc's
    (name, node, cpu) — ids are random, names are deterministic."""
    snap = state.snapshot()
    rows = []
    for job in snap.jobs():
        for a in snap.allocs_by_job(job.namespace, job.id):
            if a.terminal_status():
                continue
            rows.append((a.name, a.node_id, a.resources.cpu))
    return sorted(rows)


class TestPipelinedSerialParity:
    def _run(self, nodes, mode):
        """One fixed eval batch through a given commit path.  Node ids,
        job ids, and eval ids are pinned, so every variant computes the
        SAME placements — what differs is the commit machinery."""
        s = Server(dev_mode=True, eval_batch=8)
        s.establish_leadership()
        for n in nodes:
            s.register_node(n, now=NOW)
        for i in range(3):
            job = mock.batch_job()
            job.id = f"parity-{i}"
            tg = job.task_groups[0]
            tg.count = 80          # >= 64: the solo path runs the same
            tg.tasks[0].resources.cpu = 100    # waterfill bulk kernel
            tg.tasks[0].resources.memory_mb = 64
            s.state.upsert_job(job)
            ev = mock.eval(job_id=job.id, type=job.type)
            ev.id = f"eval-parity-{i}"
            s.apply_eval_update([ev], now=NOW)
        applier = s.plan_applier
        if mode == "full_check":
            # break every fence: the applier runs the COLUMNAR full
            # re-check (plan_apply._eval_blocks) instead of wholesale
            s.state.nodes_unchanged_since = lambda *a, **k: False
        elif mode == "expanded":
            # force the pre-wavepipe behavior: per-alloc expansion + the
            # per-node AllocsFit loop
            orig = applier._apply_one

            def expand_first(pending):
                pending.plan.expand_blocks()
                pending.plan.coupled_batch = None
                return orig(pending)
            applier._apply_one = expand_first
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        for i in range(3):
            live = [a for a in snap.allocs_by_job("default", f"parity-{i}")
                    if not a.terminal_status()]
            assert len(live) == 80, (mode, i, len(live))
        return _contents(s.state)

    def test_commit_paths_identical_state(self):
        nodes = _fixed_cluster_nodes()
        fenced = self._run(nodes, "fenced")
        full = self._run(nodes, "full_check")
        expanded = self._run(nodes, "expanded")
        assert fenced == full
        assert fenced == expanded

    def test_harness_serial_matches_server_pipeline(self):
        """The scheduler-Harness serial path (no applier, direct
        upsert) lands the same final contents as the Server's batched
        wave — same nodes, same jobs, same eval ids -> same picks."""
        nodes = _fixed_cluster_nodes()
        server_contents = self._run(nodes, "fenced")
        h = Harness()
        h.state.upsert_nodes(nodes)
        for i in range(3):
            job = mock.batch_job()
            job.id = f"parity-{i}"
            tg = job.task_groups[0]
            tg.count = 80
            tg.tasks[0].resources.cpu = 100
            tg.tasks[0].resources.memory_mb = 64
            h.state.upsert_job(job)
        for i in range(3):
            ev = mock.eval(job_id=f"parity-{i}", type="batch")
            ev.id = f"eval-parity-{i}"
            h.state.upsert_evals([ev])
            err = h.process("batch", ev, now=NOW)
            assert err is None, err
        assert _contents(h.state) == server_contents

    def test_multiwave_pipeline_places_everything_exactly(self):
        """Small eval_batch forces several chained waves through the
        wave pipeline; aggregate state must match the serial path:
        every job fully placed, no refutes, no node oversubscribed."""
        nodes = _fixed_cluster_nodes(n_nodes=10, seed=4)
        s = Server(dev_mode=True, eval_batch=3)
        s.establish_leadership()
        for n in nodes:
            s.register_node(n, now=NOW)
        jobs = []
        for _ in range(9):
            job = mock.batch_job()
            job.task_groups[0].count = 12
            job.task_groups[0].tasks[0].resources.cpu = 50
            job.task_groups[0].tasks[0].resources.memory_mb = 16
            s.register_job(job, now=NOW)
            jobs.append(job)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        for job in jobs:
            live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            assert len(live) == 12, (job.id, len(live))
        assert s.plan_applier.stats["plans_refuted"] == 0
        assert s.workers[0].stats["nacked"] == 0
        # stage timers saw the pipeline run (dispatch + commit at least)
        totals = s.stage_timers.totals()
        assert totals.get("dispatch", 0) > 0
        assert totals.get("commit", 0) > 0


class TestBlockColumnarRefute:
    def test_without_nodes_masks_rows(self):
        from nomad_tpu.structs import AllocBlock
        tmpl = Allocation(id="t", namespace="default", job_id="j",
                          task_group="tg",
                          resources=Resources(cpu=10, memory_mb=10))
        block = AllocBlock(
            id="b1", template=tmpl,
            ids=[f"a{i}" for i in range(6)],
            name_prefix="j.tg[", indexes=list(range(6)),
            picks=np.array([0, 1, 2, 0, 1, 2], np.int32),
            node_table=["n0", "n1", "n2"], round_size=1024)
        kept = block.without_nodes({"n1"})
        assert kept.count == 4
        assert kept.node_table == ["n0", "n2"]
        assert set(kept.ids) == {"a0", "a2", "a3", "a5"}
        rows = kept.materialize_all()
        assert {a.node_id for a in rows} == {"n0", "n2"}
        # demand reflects only surviving rows
        assert kept.demand_by_node() == {
            "n0": (2, 20, 20, 0), "n2": (2, 20, 20, 0)}
        # masking every node -> nothing survives
        assert block.without_nodes({"n0", "n1", "n2"}) is None
        # masking nothing returns the block itself
        assert block.without_nodes(set()) is block


class TestExecutorResidentParity:
    """The device-resident executor contract (ops/executor.py), per
    backend: multi-pass scheduling that rides the retained usage chain
    lands BIT-FOR-BIT the same state as the serial host-round-trip path
    — including across a forced invalidation (a node knocked out of the
    table mid-run)."""

    def _run_waves(self, nodes, backend, resident, drain_mid=False,
                   mesh=None):
        """`mesh`: None = the engine's auto choice (the conftest's
        8-virtual-device mesh -> sharded), False = force the
        single-device engine (the serial reference the sharded runs
        must match bit-for-bit)."""
        s = Server(dev_mode=True, eval_batch=4, device_executor=backend,
                   mesh=mesh)
        s.executor.chain_enabled = resident
        s.establish_leadership()
        for n in nodes:
            s.register_node(n, now=NOW)

        def wave(tag):
            for i in range(4):
                job = mock.batch_job()
                job.id = f"res-{tag}-{i}"
                tg = job.task_groups[0]
                tg.count = 12
                tg.tasks[0].resources.cpu = 100
                tg.tasks[0].resources.memory_mb = 64
                s.state.upsert_job(job)
                ev = mock.eval(job_id=job.id, type="batch")
                ev.id = f"eval-res-{tag}-{i}"
                s.apply_eval_update([ev], now=NOW)
            # each wave is one worker pass: the chain crosses passes
            # through the executor's retained slot, not the prefetch
            s.process_all(now=NOW)

        wave("a")
        upload_bytes_a = s.executor.stats["upload_bytes"]
        if drain_mid:
            # a node-table write the chain cannot see (drain-style
            # ineligibility; no reschedule evals, so both runs stay on
            # pinned eval ids): the executor must invalidate and the
            # next wave re-sync from the packer
            s.set_node_eligibility(nodes[0].id, False)
        wave("b")
        stats = dict(s.executor.stats)
        stats["upload_bytes_wave_a"] = upload_bytes_a
        stats["shard_h2d_bytes"] = s.engine.shard_h2d_bytes
        refuted = s.plan_applier.stats["plans_refuted"]
        return _contents(s.state), stats, refuted

    @pytest.mark.parametrize("backend", executor_backends())
    def test_resident_chain_bitwise_equals_serial(self, backend):
        nodes = _fixed_cluster_nodes(n_nodes=12, seed=7)
        serial, st_serial, _ = self._run_waves(nodes, backend, False)
        resident, st_res, refuted = self._run_waves(nodes, backend, True)
        assert resident == serial
        # the serial reference never chained; the resident run did
        assert st_serial["resident_waves"] == 0
        assert st_res["resident_waves"] >= 1, st_res
        assert refuted == 0

    @pytest.mark.parametrize("backend", executor_backends())
    def test_forced_invalidation_mid_run(self, backend):
        nodes = _fixed_cluster_nodes(n_nodes=12, seed=7)
        serial, _, _ = self._run_waves(nodes, backend, False,
                                       drain_mid=True)
        resident, st_res, refuted = self._run_waves(nodes, backend, True,
                                                    drain_mid=True)
        assert resident == serial
        assert st_res["invalidations"] >= 1, st_res
        assert refuted == 0
        # wave a still chained within itself or across its own passes;
        # the invalidation only severed the chain at the drain
        assert st_res["resident_waves"] >= 0

    def test_executor_upload_accounting(self):
        nodes = _fixed_cluster_nodes(n_nodes=12, seed=7)
        _, stats, _ = self._run_waves(nodes, "jax", True)
        # node tensors + used uploaded at least once, metered in bytes
        assert stats["uploads"] >= 1
        assert stats["upload_bytes"] > 0

    @pytest.mark.skipif(__import__("jax").device_count() < 2,
                        reason="needs the virtual multi-device mesh")
    def test_sharded_resident_matches_single_device_serial(self):
        """THE promotion contract (ISSUE 7): the 8-way sharded engine
        riding the retained resident chain lands BIT-FOR-BIT the same
        state as the serial single-device host-round-trip path."""
        nodes = _fixed_cluster_nodes(n_nodes=28, seed=7)  # 28 % 8 != 0
        serial_1dev, st_1, _ = self._run_waves(nodes, "jax", False,
                                               mesh=False)
        sharded_res, st_s, refuted = self._run_waves(nodes, "jax", True)
        assert sharded_res == serial_1dev
        assert st_1["resident_waves"] == 0
        assert st_s["resident_waves"] >= 1, st_s
        assert refuted == 0

    @pytest.mark.skipif(__import__("jax").device_count() < 2,
                        reason="needs the virtual multi-device mesh")
    def test_sharded_invalidation_reuploads_one_shard(self):
        """A mid-run single-node eligibility write dirties ONE shard:
        the sharded run must invalidate the chain, re-sync only that
        shard (engine dirty-shard patch, asserted via the executor's
        upload_bytes meter), and still match the single-device serial
        run bit-for-bit."""
        nodes = _fixed_cluster_nodes(n_nodes=64, seed=7)
        serial_1dev, _, _ = self._run_waves(nodes, "jax", False,
                                            mesh=False, drain_mid=True)
        sharded_res, st, refuted = self._run_waves(nodes, "jax", True,
                                                   drain_mid=True)
        assert sharded_res == serial_1dev
        assert refuted == 0
        assert st["invalidations"] >= 1, st
        assert st["shard_h2d_bytes"] > 0, \
            "invalidation fell back to a full-tensor re-sync"
        # wave b's re-sync (everything after wave a) moved at most the
        # dirty shard's slice of each tensor — strictly less than wave
        # a's full upload (8 shards; 2x slack covers the used heal +
        # per-wave delta scatters)
        wave_b_bytes = st["upload_bytes"] - st["upload_bytes_wave_a"]
        assert wave_b_bytes <= 2 * (st["upload_bytes_wave_a"] // 8) + 512, \
            (wave_b_bytes, st["upload_bytes_wave_a"])
