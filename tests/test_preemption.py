"""Preemption tests (reference scenarios: scheduler/preemption_test.go)."""

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    PreemptionConfig,
    Resources,
    SchedulerConfiguration,
)

NOW = 1_700_000_000.0


def full_node_harness(service_preemption=False):
    """One 4000MHz node filled by a low-priority batch job."""
    h = Harness()
    cfg = SchedulerConfiguration(
        preemption_config=PreemptionConfig(
            system_scheduler_enabled=True,
            batch_scheduler_enabled=False,
            service_scheduler_enabled=service_preemption))
    h.state.set_scheduler_config(cfg)
    n = mock.node()
    n.resources = type(n.resources)(cpu=4000, memory_mb=8192, disk_mb=100000)
    n.reserved = type(n.reserved)()
    h.state.upsert_node(n)
    low = mock.batch_job(priority=20)
    low.task_groups[0].count = 4
    low.task_groups[0].tasks[0].resources = Resources(cpu=900, memory_mb=512)
    h.state.upsert_job(low)
    e = mock.eval(job_id=low.id, type="batch")
    assert h.process("batch", e, now=NOW) is None
    live = [a for a in h.snapshot().allocs_by_job(low.namespace, low.id)
            if not a.terminal_status()]
    assert len(live) == 4     # node now has 3600/4000 used
    return h, n, low


class TestPreemption:
    def test_system_job_preempts_lower_priority(self):
        h, node, low = full_node_harness()
        sysjob = mock.system_job(priority=100)   # needs 500MHz; 400 free
        sysjob.task_groups[0].tasks[0].resources = Resources(
            cpu=800, memory_mb=256)
        h.state.upsert_job(sysjob)
        # system scheduler path goes through host allocs_fit; preemption is
        # driven via the generic engine only — use a service-type eval of
        # equivalent shape to exercise the engine path:
        svc = mock.job(priority=100)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        cfg = h.state.snapshot().scheduler_config()
        cfg2 = SchedulerConfiguration(
            preemption_config=PreemptionConfig(service_scheduler_enabled=True))
        h.state.set_scheduler_config(cfg2)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=100)
        assert h.process("service", e, now=NOW) is None
        plan = h.plans[-1]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 1
        preempted = [a for allocs in plan.node_preemptions.values()
                     for a in allocs]
        assert len(preempted) == 1    # one 900MHz eviction frees enough
        assert preempted[0].desired_status == "evict"
        assert preempted[0].preempted_by_allocation == placed[0].id
        assert placed[0].preempted_allocations == [preempted[0].id]
        # state reflects the eviction
        snap = h.snapshot()
        assert snap.alloc_by_id(preempted[0].id).desired_status == "evict"

    def test_no_preemption_when_disabled(self):
        h, node, low = full_node_harness(service_preemption=False)
        svc = mock.job(priority=100)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=100)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert preempted == []
        # blocked eval instead
        assert any(ev.status == "blocked" for ev in h.create_evals)

    def test_equal_priority_not_preempted(self):
        h, node, low = full_node_harness(service_preemption=True)
        svc = mock.job(priority=20)   # same as the batch job
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=20)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert preempted == []

    def test_minimal_eviction_set(self):
        # needs 1700 free; has 400 -> must evict exactly 2 x 900 allocs
        h, node, low = full_node_harness(service_preemption=True)
        svc = mock.job(priority=70)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=1700, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=70)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert len(preempted) == 2
