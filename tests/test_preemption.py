"""Preemption tests (reference scenarios: scheduler/preemption_test.go)."""

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    PreemptionConfig,
    Resources,
    SchedulerConfiguration,
)

NOW = 1_700_000_000.0


def full_node_harness(service_preemption=False):
    """One 4000MHz node filled by a low-priority batch job."""
    h = Harness()
    cfg = SchedulerConfiguration(
        preemption_config=PreemptionConfig(
            system_scheduler_enabled=True,
            batch_scheduler_enabled=False,
            service_scheduler_enabled=service_preemption))
    h.state.set_scheduler_config(cfg)
    n = mock.node()
    n.resources = type(n.resources)(cpu=4000, memory_mb=8192, disk_mb=100000)
    n.reserved = type(n.reserved)()
    h.state.upsert_node(n)
    low = mock.batch_job(priority=20)
    low.task_groups[0].count = 4
    low.task_groups[0].tasks[0].resources = Resources(cpu=900, memory_mb=512)
    h.state.upsert_job(low)
    e = mock.eval(job_id=low.id, type="batch")
    assert h.process("batch", e, now=NOW) is None
    live = [a for a in h.snapshot().allocs_by_job(low.namespace, low.id)
            if not a.terminal_status()]
    assert len(live) == 4     # node now has 3600/4000 used
    return h, n, low


class TestPreemption:
    def test_system_job_preempts_lower_priority(self):
        h, node, low = full_node_harness()
        sysjob = mock.system_job(priority=100)   # needs 500MHz; 400 free
        sysjob.task_groups[0].tasks[0].resources = Resources(
            cpu=800, memory_mb=256)
        h.state.upsert_job(sysjob)
        # system scheduler path goes through host allocs_fit; preemption is
        # driven via the generic engine only — use a service-type eval of
        # equivalent shape to exercise the engine path:
        svc = mock.job(priority=100)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        cfg2 = SchedulerConfiguration(
            preemption_config=PreemptionConfig(service_scheduler_enabled=True))
        h.state.set_scheduler_config(cfg2)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=100)
        assert h.process("service", e, now=NOW) is None
        plan = h.plans[-1]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 1
        preempted = [a for allocs in plan.node_preemptions.values()
                     for a in allocs]
        assert len(preempted) == 1    # one 900MHz eviction frees enough
        assert preempted[0].desired_status == "evict"
        assert preempted[0].preempted_by_allocation == placed[0].id
        assert placed[0].preempted_allocations == [preempted[0].id]
        # state reflects the eviction
        snap = h.snapshot()
        assert snap.alloc_by_id(preempted[0].id).desired_status == "evict"

    def test_no_preemption_when_disabled(self):
        h, node, low = full_node_harness(service_preemption=False)
        svc = mock.job(priority=100)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=100)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert preempted == []
        # blocked eval instead
        assert any(ev.status == "blocked" for ev in h.create_evals)

    def test_equal_priority_not_preempted(self):
        h, node, low = full_node_harness(service_preemption=True)
        svc = mock.job(priority=20)   # same as the batch job
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=800, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=20)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert preempted == []

    def test_minimal_eviction_set(self):
        # needs 1700 free; has 400 -> must evict exactly 2 x 900 allocs
        h, node, low = full_node_harness(service_preemption=True)
        svc = mock.job(priority=70)
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources = Resources(cpu=1700, memory_mb=256)
        h.state.upsert_job(svc)
        e = mock.eval(job_id=svc.id, priority=70)
        h.process("service", e, now=NOW)
        preempted = [a for p in h.plans for allocs in p.node_preemptions.values()
                     for a in allocs]
        assert len(preempted) == 2


class TestDevicePreemptParity:
    """The device preemption kernel (ops.preempt.preempt_bulk) vs the host
    Preemptor on identical state: identical eviction sets for homogeneous
    priority bands (the common case), valid minimal evictions always."""

    def _cluster(self, n_nodes=40, n_low_jobs=3):
        import random
        h = Harness()
        h.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                batch_scheduler_enabled=True,
                service_scheduler_enabled=True)))
        nodes = []
        for _ in range(n_nodes):
            n = mock.node()
            n.resources = type(n.resources)(cpu=4000, memory_mb=8192,
                                            disk_mb=100000)
            n.reserved = type(n.reserved)()
            nodes.append(n)
        h.state.upsert_nodes(nodes)
        for p in range(n_low_jobs):
            low = mock.batch_job(priority=10 + p * 10)
            low.task_groups[0].count = n_nodes
            low.task_groups[0].tasks[0].resources = Resources(
                cpu=1200, memory_mb=256)
            h.state.upsert_job(low)
            e = mock.eval(job_id=low.id, type="batch")
            assert h.process("batch", e, now=NOW) is None
        return h

    def test_device_matches_host_eviction_sets(self):
        """Force both implementations on the same snapshot and compare."""
        import numpy as np
        from nomad_tpu.ops import PlacementEngine

        h = self._cluster()
        snap = h.snapshot()
        hi = mock.job(priority=90)
        hi.task_groups[0].count = 20
        hi.task_groups[0].tasks[0].resources = Resources(
            cpu=2000, memory_mb=128)
        h.state.upsert_job(hi)
        snap = h.snapshot()

        def run(device: bool):
            eng = PlacementEngine(mesh=False)
            if device:
                eng.PREEMPT_DEVICE_MIN_NODES = 0     # force the kernel
            else:
                # disable the device path: force the host Preemptor
                eng.PREEMPT_DEVICE_MIN_FAILED = 10 ** 9
            ds = eng.place(snap, hi, hi.task_groups, None,
                           seed=3, block=(hi.task_groups[0].name, 20))
            picks = [d.node_id for d in ds]
            evs = sorted(v.id for d in ds for v in d.evictions)
            return picks, evs

        picks_d, evs_d = run(device=True)
        picks_h, evs_h = run(device=False)
        assert all(p is not None for p in picks_d)
        assert all(p is not None for p in picks_h)
        # same nodes chosen, same victims evicted (priority bands are
        # homogeneous: within-band order cannot differ)
        assert sorted(picks_d) == sorted(picks_h)
        assert evs_d == evs_h

    def test_device_evictions_minimal_and_lower_priority(self):
        """Heterogeneous bands: the kernel's evictions must still be
        strictly lower priority and exactly sufficient."""
        from nomad_tpu.ops import PlacementEngine

        h = Harness()
        h.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                service_scheduler_enabled=True)))
        n = mock.node()
        n.resources = type(n.resources)(cpu=4000, memory_mb=8192,
                                        disk_mb=100000)
        n.reserved = type(n.reserved)()
        h.state.upsert_node(n)
        sizes = [(500, 5), (900, 20), (700, 30), (1000, 40), (800, 45)]
        for cpu, prio in sizes:
            j = mock.batch_job(priority=prio)
            j.task_groups[0].count = 1
            j.task_groups[0].tasks[0].resources = Resources(
                cpu=cpu, memory_mb=64)
            h.state.upsert_job(j)
            e = mock.eval(job_id=j.id, type="batch")
            # batch preemption off: fill without evicting
            assert h.process("batch", e, now=NOW) is None
        snap = h.snapshot()
        hi = mock.job(priority=50)
        hi.task_groups[0].count = 4
        hi.task_groups[0].tasks[0].resources = Resources(
            cpu=900, memory_mb=64)
        h.state.upsert_job(hi)
        snap = h.snapshot()
        eng = PlacementEngine(mesh=False)
        eng.PREEMPT_DEVICE_MIN_NODES = 0             # force the kernel
        ds = eng.place(snap, hi, hi.task_groups, None,
                       seed=1, block=(hi.task_groups[0].name, 4))
        placed = sum(1 for d in ds if d.node_id is not None)
        victims = [v for d in ds for v in d.evictions]
        # every victim strictly lower priority
        assert victims
        assert all(v.job.priority < 50 for v in victims)
        # capacity math holds: used - freed + placed asks <= cap
        freed = sum(v.resources.cpu for v in victims)
        base_used = sum(c for c, _ in sizes)
        assert base_used - freed + placed * 900 <= 4000


class TestDevicePreemptionAtScale:
    def _cluster(self, n_nodes, mixed_tg=False):
        """Cluster beyond the OLD 8192-node device cap, every node filled
        by one low-priority alloc; a high-priority job must evict to
        place (the config-4 shape at scale)."""
        h = Harness()
        h.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                system_scheduler_enabled=True,
                batch_scheduler_enabled=True,
                service_scheduler_enabled=True)))
        nodes = []
        for _ in range(n_nodes):
            n = mock.node()
            n.resources = type(n.resources)(cpu=4000, memory_mb=8192,
                                            disk_mb=100000)
            nodes.append(n)
        h.state.upsert_nodes(nodes)
        low = mock.batch_job(priority=20)
        low.task_groups[0].count = n_nodes
        low.task_groups[0].tasks[0].resources = Resources(
            cpu=3000, memory_mb=64)
        h.state.upsert_job(low)
        e = mock.eval(job_id=low.id, type="batch")
        assert h.process("batch", e, now=NOW) is None
        return h, low

    def test_50k_scale_device_preemption_beyond_old_cap(self):
        """10k nodes (> the removed 8192 cap): the compact victim tables
        keep the upload O(victims), and the device path resolves the
        whole failed batch."""
        n_nodes = 10000
        h, low = self._cluster(n_nodes)
        hi = mock.job(priority=80)
        hi.task_groups[0].count = 16
        hi.task_groups[0].tasks[0].resources = Resources(
            cpu=3000, memory_mb=64)
        h.state.upsert_job(hi)
        e = mock.eval(job_id=hi.id, type="service")
        assert h.process("service", e, now=NOW) is None
        plan = h.plans[-1]
        placed = sum(len(v) for v in plan.node_allocation.values()) \
            + sum(b.count for b in plan.alloc_blocks)
        n_evict = sum(len(v) for v in plan.node_preemptions.values())
        assert placed == 16
        assert n_evict == 16
        # each victim evicted exactly ONCE (chained per-group launches
        # must not re-offer consumed victims — each frees capacity once)
        victim_ids = [a.id for v in plan.node_preemptions.values()
                      for a in v]
        assert len(set(victim_ids)) == 16, "duplicate evictions"
        # and NO committed node exceeds capacity
        snap = h.snapshot()
        touched = {a.node_id
                   for v in plan.node_allocation.values() for a in v}
        for b in plan.alloc_blocks:
            touched.update(b.node_table)
        for nid in touched:
            live = [a for a in snap.allocs_by_node(nid)
                    if not a.terminal_status()
                    and a.desired_status == "run"]
            cpu = sum(a.resources.cpu for a in live)
            node = snap.node_by_id(nid)
            assert cpu <= node.resources.cpu - node.reserved.cpu, \
                (nid, cpu)      # one victim frees exactly one slot

    def test_host_device_eviction_parity(self):
        """The device path and the host Preemptor agree on eviction sets
        for the same failure batch (VERDICT r3 #4 parity pin)."""
        from nomad_tpu.ops import engine as eng_mod

        def run(force_host):
            h, low = self._cluster(512)
            hi = mock.job(priority=80)
            hi.task_groups[0].count = 8
            hi.task_groups[0].tasks[0].resources = Resources(
                cpu=3000, memory_mb=64)
            h.state.upsert_job(hi)
            e = mock.eval(job_id=hi.id, type="service")
            if force_host:
                old = eng_mod.PlacementEngine.PREEMPT_DEVICE_MIN_FAILED
                eng_mod.PlacementEngine.PREEMPT_DEVICE_MIN_FAILED = 10 ** 9
                try:
                    assert h.process("service", e, now=NOW) is None
                finally:
                    eng_mod.PlacementEngine.PREEMPT_DEVICE_MIN_FAILED = old
            else:
                assert h.process("service", e, now=NOW) is None
            plan = h.plans[-1]
            evicted = sorted(
                a.resources.cpu for v in plan.node_preemptions.values()
                for a in v)
            n_evict = sum(len(v) for v in plan.node_preemptions.values())
            placed = sum(len(v) for v in plan.node_allocation.values()) \
                + sum(b.count for b in plan.alloc_blocks)
            return placed, n_evict, evicted

        dev = run(force_host=False)
        host = run(force_host=True)
        assert dev == host == (8, 8, [3000] * 8)

    def test_mixed_tg_failure_batch_preempts_on_device(self):
        """Two task groups failing in one eval: per-group launches chain
        through shared usage state (the old path fell back to the host
        loop for any mixed batch)."""
        from nomad_tpu.structs import Task, TaskGroup
        h, low = self._cluster(256)
        hi = mock.job(priority=80)
        hi.task_groups = [
            TaskGroup(name="a", count=8, tasks=[
                Task(name="t", driver="exec",
                     resources=Resources(cpu=3000, memory_mb=64))]),
            TaskGroup(name="b", count=8, tasks=[
                Task(name="t", driver="exec",
                     resources=Resources(cpu=2500, memory_mb=64))]),
        ]
        h.state.upsert_job(hi)
        e = mock.eval(job_id=hi.id, type="service")
        assert h.process("service", e, now=NOW) is None
        plan = h.plans[-1]
        placed = sum(len(v) for v in plan.node_allocation.values()) \
            + sum(b.count for b in plan.alloc_blocks)
        n_evict = sum(len(v) for v in plan.node_preemptions.values())
        assert placed == 16
        assert n_evict == 16
