"""Secrets plane — the Vault integration seam (reference: nomad/vault.go
+ vault_hook/template secret renders; here backed natively by nomad
variables read under the task's workload identity)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.client import Client, InProcessRPC
from nomad_tpu.core.server import Server
from nomad_tpu.structs import VariableItem

NOW_WAIT = 20


def run_job_with_template(server, client, job, timeout=NOW_WAIT):
    server.register_job(job)
    deadline = time.time() + timeout
    while time.time() < deadline:
        allocs = server.state.snapshot().allocs_by_job(
            job.namespace, job.id)
        states = [a.client_status for a in allocs]
        if states and all(s in ("complete", "failed") for s in states):
            return allocs
        time.sleep(0.1)
    raise AssertionError(f"job never finished: {states}")


@pytest.fixture()
def cluster(tmp_path):
    s = Server(dev_mode=False, num_workers=1, heartbeat_ttl=1e9)
    s.start(tick_interval=0.2)
    c = Client(InProcessRPC(s), node=mock.node(),
               data_dir=str(tmp_path / "client"))
    c.start()
    try:
        yield s, c
    finally:
        c.shutdown()
        s.shutdown()


def secret_job(template_data):
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 1
    task = tg.tasks[0]
    task.driver = "mock"
    task.config = {"run_for_s": 0}
    task.templates = [{"data": template_data, "destination": "creds.txt"}]
    return job


class TestSecretsPlane:
    def test_template_renders_workload_scoped_variable(self, cluster,
                                                       tmp_path):
        s, c = cluster
        job = secret_job(
            "user=${nomad_var.nomad/jobs/%s/db#user} "
            "pass=${nomad_var.nomad/jobs/%s/db#password}")
        job.task_groups[0].tasks[0].templates[0]["data"] = (
            f"user=${{nomad_var.nomad/jobs/{job.id}/db#user}} "
            f"pass=${{nomad_var.nomad/jobs/{job.id}/db#password}}")
        s.state.upsert_variable(VariableItem(
            path=f"nomad/jobs/{job.id}/db",
            items={"user": "app", "password": "hunter2"}))
        allocs = run_job_with_template(s, c, job)
        assert all(a.client_status == "complete" for a in allocs), [
            (a.client_status, a.task_states) for a in allocs]
        import glob
        rendered = glob.glob(str(tmp_path / "client" / "**" / "creds.txt"),
                             recursive=True)
        assert rendered
        content = open(rendered[0]).read()
        assert content == "user=app pass=hunter2"

    def test_foreign_job_subtree_denied(self, cluster):
        """The workload identity only reaches the job's OWN variable
        subtree: referencing another job's secret fails the task."""
        s, c = cluster
        s.state.upsert_variable(VariableItem(
            path="nomad/jobs/other-job/db", items={"password": "nope"}))
        job = secret_job(
            "${nomad_var.nomad/jobs/other-job/db#password}")
        allocs = run_job_with_template(s, c, job)
        assert all(a.client_status == "failed" for a in allocs)
        events = [e for a in allocs
                  for ts in a.task_states.values()
                  for e in ts.events]
        assert any("permission denied" in (e.message or "")
                   for e in events), events

    def test_missing_secret_fails_task(self, cluster):
        s, c = cluster
        job = secret_job("${nomad_var.nomad/jobs/%s/nope#key}")
        job.task_groups[0].tasks[0].templates[0]["data"] = (
            f"${{nomad_var.nomad/jobs/{job.id}/nope#key}}")
        allocs = run_job_with_template(s, c, job)
        assert all(a.client_status == "failed" for a in allocs)

    def test_provider_seam_is_pluggable(self, cluster, tmp_path):
        """An external provider (the Vault drop-in) plugs in at the
        client and serves the same template references."""
        s, c = cluster
        from nomad_tpu.integrations import SecretsProvider

        class FakeVault(SecretsProvider):
            def fetch(self, namespace, path, token):
                assert token, "provider must receive the task identity"
                return {"api_key": f"vault:{path}"}

        c.secrets_provider = FakeVault()
        job = secret_job("key=${nomad_var.secret/data/app#api_key}")
        allocs = run_job_with_template(s, c, job)
        assert all(a.client_status == "complete" for a in allocs)
        import glob
        rendered = glob.glob(str(tmp_path / "client" / "**" / "creds.txt"),
                             recursive=True)
        content = open(rendered[0]).read()
        assert content == "key=vault:secret/data/app"
