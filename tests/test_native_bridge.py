"""C++ PJRT bridge (native/pjrt_bridge/bridge.cc): the production seam a
non-Python worker uses to run the placement kernels on TPU (SURVEY §7 P6).

Export the bulk placement kernel as StableHLO, compile + execute it through
the C++ bridge against the PJRT plugin, and check the resulting packed
buffer against the in-process JAX (CPU) reference."""

import numpy as np
import pytest

from nomad_tpu.native.bridge import (
    DEFAULT_PLUGIN,
    bridge_available,
    compile_options_bytes,
    export_stablehlo,
)

pytestmark = pytest.mark.skipif(
    not bridge_available(),
    reason="PJRT plugin or native toolchain unavailable")


@pytest.fixture(scope="module")
def bridge():
    from nomad_tpu.native.bridge import PjrtBridge
    br = PjrtBridge(DEFAULT_PLUGIN)
    yield br
    br.close()


def _bulk_inputs(n=32, p=64, seed=7):
    import jax.numpy as jnp
    from nomad_tpu.ops.select import BulkInputs

    rng = np.random.default_rng(seed)
    attrs = rng.integers(0, 4, size=(n, 8)).astype(np.int32)
    cap = np.tile(np.array([[4000, 8192, 102400]], np.int32), (n, 1))
    used = np.zeros((n, 3), np.int32)
    con = np.array([[[0, 1, attrs[0, 0]]]], np.int32)
    return BulkInputs(
        attrs=jnp.asarray(attrs), cap=jnp.asarray(cap),
        used0=jnp.asarray(used),
        elig=jnp.ones(n, bool),
        dc_mask=jnp.ones(n, bool), pool_mask=jnp.ones(n, bool),
        luts=jnp.ones((1, 8), bool),
        con=jnp.asarray(con),
        aff=jnp.zeros((1, 1, 4), jnp.int32),
        req=jnp.asarray(np.array([[500, 256, 300]], np.int32)),
        desired=jnp.asarray(np.array([p], np.int32)),
        dh_limit=jnp.zeros(1, jnp.int32),
        job_count0=jnp.zeros(n, jnp.int32),
        spread_algo=jnp.asarray(False),
        g=jnp.asarray(0, jnp.int32),
        p_real=jnp.asarray(p, jnp.int32),
        seed=jnp.asarray(0, jnp.uint32),
    )


class TestBridge:
    def test_platform_and_devices(self, bridge):
        assert bridge.platform() in ("tpu", "cpu")
        assert bridge.device_count() >= 1

    def test_placement_kernel_via_bridge_matches_jax(self, bridge):
        from functools import partial
        import jax
        from nomad_tpu.ops.select import place_bulk_packed

        inp = _bulk_inputs()
        round_size, n_rounds = 64, 1
        kernel = partial(place_bulk_packed, round_size=round_size,
                         n_rounds=n_rounds, with_scores=False)

        # in-process JAX reference (CPU backend per conftest)
        ref_buf, ref_used, ref_jc = jax.jit(kernel)(inp)
        ref_buf = np.asarray(ref_buf)
        ref_used = np.asarray(ref_used)
        ref_jc = np.asarray(ref_jc)

        hlo = export_stablehlo(kernel, inp)
        ex = bridge.compile(hlo)
        assert bridge.num_outputs(ex) == 3

        flat = [np.asarray(x) for x in jax.tree_util.tree_leaves(inp)]
        out = bridge.execute(
            ex, flat,
            [(ref_buf.shape, ref_buf.dtype),
             (ref_used.shape, ref_used.dtype),
             (ref_jc.shape, ref_jc.dtype)])

        # picks/fills must match exactly (integer outputs, same program)
        assert np.array_equal(out[0][:, :round_size],
                              ref_buf[:, :round_size])
        assert np.array_equal(out[1], ref_used)
        assert np.array_equal(out[2], ref_jc)

    def test_resident_buffers_and_state_chain(self, bridge):
        """Persistent device buffers (round-5 verdict #4): upload once,
        execute on handles, fetch only chosen outputs — and chain an
        output handle (proposed usage) into the next execute without a
        host round trip."""
        from functools import partial
        import jax
        from nomad_tpu.ops.select import place_bulk_packed

        inp = _bulk_inputs(p=8)    # leave headroom: wave 2 must still
        round_size, n_rounds = 64, 1   # be able to place on the chain
        kernel = partial(place_bulk_packed, round_size=round_size,
                         n_rounds=n_rounds, with_scores=False)
        ref = [np.asarray(x) for x in jax.jit(kernel)(inp)]
        hlo = export_stablehlo(kernel, inp)
        ex = bridge.compile(hlo)
        flat = [np.asarray(x) for x in jax.tree_util.tree_leaves(inp)]
        handles = [bridge.upload(a) for a in flat]
        try:
            outs = bridge.execute_resident(ex, handles, 3)
            buf = bridge.fetch(outs[0], ref[0].shape, ref[0].dtype)
            used = bridge.fetch(outs[1], ref[1].shape, ref[1].dtype)
            assert np.array_equal(buf[:, :round_size],
                                  ref[0][:, :round_size])
            assert np.array_equal(used, ref[1])
            # chain: wave 2 starts from wave 1's used OUTPUT handle
            # (used0 is flat-input index 2 in BulkInputs field order)
            chain = list(handles)
            chain[2] = outs[1]
            outs2 = bridge.execute_resident(ex, chain, 3)
            used2 = bridge.fetch(outs2[1], ref[1].shape, ref[1].dtype)
            # usage strictly grew: the second wave consumed capacity on
            # top of the first's device-resident state
            assert used2.sum() > used.sum()
            for h in outs + outs2:
                bridge.buffer_free(h)
        finally:
            for h in handles:
                bridge.buffer_free(h)

    def test_compile_error_surfaces(self, bridge):
        from nomad_tpu.native.bridge import BridgeError
        with pytest.raises(BridgeError):
            bridge.compile(b"not an mlir module",
                           compile_options_bytes())


class TestBridgeMultiEval:
    def test_production_multi_eval_kernel_via_bridge(self, bridge):
        """The REAL production kernel (place_multi_packed, built by the
        engine's own input lowering for a multi-eval batch) compiles and
        runs through the C++ bridge, matching in-process JAX exactly
        (VERDICT r3 #3: the bridge must carry the production kernel, not
        a toy module)."""
        import random
        from functools import partial

        import jax
        from nomad_tpu import mock
        from nomad_tpu.ops import PlacementEngine
        from nomad_tpu.ops.engine import BatchItem
        from nomad_tpu.ops.select import place_multi_packed
        from nomad_tpu.scheduler import Harness

        rng = random.Random(3)
        h = Harness()
        nodes = []
        for i in range(120):
            n = mock.node()
            n.datacenter = f"dc{1 + i % 3}"
            n.resources.cpu = rng.choice([4000, 8000])
            n.resources.memory_mb = 16384
            nodes.append(n)
        h.state.upsert_nodes(nodes)
        items = []
        for i in range(6):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = 40
            tg.tasks[0].resources.cpu = 50
            tg.tasks[0].resources.memory_mb = 64
            h.state.upsert_job(job)
            items.append(BatchItem(job=job, tg=tg, count=40))
        snap = h.state.snapshot()
        eng = PlacementEngine(mesh=False)
        built = eng.build_multi_inputs(snap, items, seed=11)
        inp, rs = built["inp"], built["rs"]

        kernel = partial(place_multi_packed, round_size=rs)
        ref = jax.jit(kernel, static_argnums=())(inp)
        ref = [np.asarray(x) for x in ref]

        hlo = export_stablehlo(kernel, inp)
        ex = bridge.compile(hlo)
        flat = [np.asarray(x) for x in jax.tree_util.tree_leaves(inp)]
        out = bridge.execute(
            ex, flat, [(r.shape, r.dtype) for r in ref])
        # fills + usage integer-exact: same program, same inputs
        assert np.array_equal(out[0][:, :rs], ref[0][:, :rs])
        assert np.array_equal(out[1], ref[1])
