"""Workload identity tests (reference scenarios: workload identity +
the implicit variables policy, identity_hook, Alloc.SignIdentities)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.identity import mint, variable_prefix, verify
from nomad_tpu.core.server import Server
from nomad_tpu.structs import VariableItem

SECRET = "test-secret"


class TestTokenFormat:
    def test_mint_verify_roundtrip(self):
        tok = mint(SECRET, namespace="default", job_id="web",
                   alloc_id="a1", task="t1")
        claims = verify(SECRET, tok)
        assert claims["nomad_job_id"] == "web"
        assert claims["nomad_allocation_id"] == "a1"
        assert claims["nomad_task"] == "t1"

    def test_forged_signature_rejected(self):
        tok = mint(SECRET, namespace="default", job_id="web",
                   alloc_id="a1", task="t1")
        assert verify("other-secret", tok) is None
        # flipping claim bytes breaks the signature
        body = tok[len("nomad-wi."):]
        h, c, s = body.split(".")
        tampered = f"nomad-wi.{h}.{c[:-2] + ('AA' if c[-2:] != 'AA' else 'BB')}.{s}"
        assert verify(SECRET, tampered) is None

    def test_expiry(self):
        tok = mint(SECRET, namespace="default", job_id="web",
                   alloc_id="a1", task="t1", ttl_s=60, now=1000.0)
        assert verify(SECRET, tok, now=1030.0) is not None
        assert verify(SECRET, tok, now=1100.0) is None

    def test_garbage_rejected(self):
        assert verify(SECRET, "nope") is None
        assert verify(SECRET, "nomad-wi.x.y") is None
        assert verify(SECRET, "nomad-wi.a.b.c") is None


class TestServerIdentity:
    def _server_with_alloc(self):
        srv = Server(dev_mode=True, acl_enabled=True)
        srv.establish_leadership()
        node = mock.node()
        srv.state.upsert_node(node)
        job = mock.job()
        srv.state.upsert_job(job)
        alloc = mock.alloc(job=job, job_id=job.id, node_id=node.id)
        srv.state.upsert_allocs([alloc])
        return srv, job, alloc

    def test_secret_minted_on_leadership(self):
        srv, _, _ = self._server_with_alloc()
        assert srv.state.identity_secret()

    def test_derive_tokens_per_task(self):
        srv, job, alloc = self._server_with_alloc()
        tokens, err = srv.derive_identity_tokens(alloc.id)
        assert err == ""
        assert set(tokens) == {t.name for t in job.task_groups[0].tasks}

    def test_derive_rejects_unknown_and_terminal(self):
        srv, job, alloc = self._server_with_alloc()
        _, err = srv.derive_identity_tokens("nope")
        assert err
        dead = alloc.copy_skip_job()
        dead.client_status = "failed"
        srv.state.upsert_allocs([dead])
        _, err = srv.derive_identity_tokens(alloc.id)
        assert err

    def test_resolve_token_scopes_variables(self):
        srv, job, alloc = self._server_with_alloc()
        tokens, _ = srv.derive_identity_tokens(alloc.id)
        tok = next(iter(tokens.values()))
        acl, err = srv.resolve_token(tok)
        assert err == ""
        pre = variable_prefix(job.id)
        assert acl.allow_variable("default", f"{pre}/db", write=False)
        assert acl.allow_variable("default", pre, write=False)
        assert not acl.allow_variable("default", "nomad/jobs/other",
                                      write=False)
        assert not acl.allow_variable("default", f"{pre}/db", write=True)

    def test_resolve_rejects_identity_of_dead_alloc(self):
        srv, job, alloc = self._server_with_alloc()
        tokens, _ = srv.derive_identity_tokens(alloc.id)
        tok = next(iter(tokens.values()))
        dead = alloc.copy_skip_job()
        dead.desired_status = "stop"
        srv.state.upsert_allocs([dead])
        acl, err = srv.resolve_token(tok)
        assert acl is None and err


class TestHTTPVariableScoping:
    def test_workload_token_reads_only_its_subtree(self):
        import json
        import urllib.request
        import urllib.error
        from nomad_tpu.agent import Agent

        agent = Agent(num_clients=1, http_port=0, acl_enabled=True)
        agent.start()
        try:
            srv = agent.server
            node_ids = [c.node.id for c in agent.clients]
            job = mock.job()
            job.id = "webjob"
            srv.state.upsert_job(job)
            alloc = mock.alloc(job=job, job_id=job.id,
                               node_id=node_ids[0])
            srv.state.upsert_allocs([alloc])
            srv.state.upsert_variable(VariableItem(
                path=f"nomad/jobs/{job.id}/db", namespace="default",
                items={"password": "hunter2"}))
            srv.state.upsert_variable(VariableItem(
                path="nomad/jobs/otherjob/db", namespace="default",
                items={"password": "secret"}))
            tokens, _ = srv.derive_identity_tokens(alloc.id)
            tok = next(iter(tokens.values()))

            def req(path):
                r = urllib.request.Request(
                    agent.address + path,
                    headers={"X-Nomad-Token": tok})
                try:
                    with urllib.request.urlopen(r, timeout=10) as resp:
                        return resp.status, json.loads(resp.read())
                except urllib.error.HTTPError as e:
                    return e.code, None

            st, v = req(f"/v1/var/nomad/jobs/{job.id}/db")
            assert st == 200 and v["Items"]["password"] == "hunter2"
            st, _ = req("/v1/var/nomad/jobs/otherjob/db")
            assert st == 403
            # listing filters to the granted subtree
            st, vs = req("/v1/vars")
            assert st == 200
            assert {x["Path"] for x in vs} == {f"nomad/jobs/{job.id}/db"}
        finally:
            agent.shutdown()


class TestTaskEnvToken:
    def test_task_gets_nomad_token(self, tmp_path):
        from nomad_tpu.client.client import Client, InProcessRPC

        srv = Server(dev_mode=False, heartbeat_ttl=3600)
        srv.start()
        cl = Client(InProcessRPC(srv), node=mock.node(),
                    data_dir=str(tmp_path))
        cl.start()
        try:
            job = mock.job()
            job.id = "tokjob"
            job.task_groups[0].count = 1
            t = job.task_groups[0].tasks[0]
            t.driver = "mock"
            t.config = {"run_for_s": 60}
            srv.register_job(job)
            deadline = time.time() + 15
            tr = None
            while time.time() < deadline:
                rs = list(cl.alloc_runners.values())
                if rs and rs[0].task_runners[0].state.state == "running":
                    tr = rs[0].task_runners[0]
                    break
                time.sleep(0.2)
            assert tr is not None
            tok = tr.env.get("NOMAD_TOKEN", "")
            assert tok.startswith("nomad-wi.")
            claims = verify(srv.state.identity_secret(), tok)
            assert claims["nomad_job_id"] == "tokjob"
        finally:
            cl.shutdown()
            srv.shutdown()
