"""Invariant-analyzer coverage (scripts/analyze.py ->
scripts/analysis/).

Each pass gets positive fixtures (the exact bug class it exists to
catch, including the pre-fix shape of the round-5
`_materialize_block_locked` snapshot leak) and negative fixtures (the
blessed shapes the codebase actually uses — `with self._lock:` scopes,
`_writable_*` copies, rebound donated buffers, cond-wait under its own
lock).  Plus: suppression comments silence exactly their pass, stale
suppressions are reported, the selftest is green, and the WHOLE repo is
violation-free across all nine passes (the same gate CI runs).
"""

import importlib.util
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "analyze", ROOT / "scripts" / "analyze.py")
analyze = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(analyze)


def findings(text, passes):
    return analyze.analyze_source(text, passes=passes)


def msgs(text, passes):
    return [f[3] for f in findings(text, passes)]


# ---------------------------------------------------------- pass A: lock

LOCK_BAD = '''
class StateStore:
    def broken_entry(self, x):
        self._insert_thing_locked(x)

    def broken_helper(self, key):
        return self._writable_claim_vol(key)
'''

LOCK_GOOD = '''
class StateStore:
    def upsert(self, x):
        with self._lock:
            self._insert_thing_locked(x)

    def _merge_locked(self, x):
        self._insert_thing_locked(x)

    def _writable_tables(self):
        return self._insert_thing_locked(None)

    def via_alias(self, x):
        lk = self._lock
        with lk:
            self._insert_thing_locked(x)

    def under_condition(self, x):
        with self._cv:
            self._insert_thing_locked(x)
'''


def test_lock_flags_unlocked_callers():
    got = findings(LOCK_BAD, ("lock",))
    assert len(got) == 2, got
    assert all("outside" in m for m in msgs(LOCK_BAD, ("lock",)))


def test_lock_accepts_locked_scopes_and_aliases():
    assert findings(LOCK_GOOD, ("lock",)) == []


# ----------------------------------------------------------- pass B: cow

# the EXACT pre-fix shape of the round-5 `_materialize_block_locked`
# snapshot-isolation leak: a claim-vol fetched straight out of the
# shared table, then mutated in place (ADVICE.md round-5 medium)
COW_LEAK = '''
class StateStore:
    def _materialize_block_locked(self, block):
        key = (block.namespace, block.source)
        vol = self._csi_volumes.get(key)
        if vol is None or block.id not in vol.read_blocks:
            return
        vol.read_blocks.pop(block.id, None)
        vol.read_allocs.update({a: "" for a in block.ids})
'''

COW_SHALLOW = '''
class StateStore:
    def _release_locked(self, key, aid):
        import dataclasses
        vol = self._csi_volumes.get(key)
        v = dataclasses.replace(vol)
        v.modify_index = 7
        v.read_allocs.pop(aid, None)
'''

COW_DIRECT = '''
class StateStore:
    def delete_volume(self, key):
        self._csi_volumes.pop(key, None)

    def set_volume(self, key, vol):
        self._csi_volumes[key] = vol
'''

COW_GOOD = '''
class StateStore:
    def _claim_ok_locked(self, key, alloc):
        vol = self._writable_claim_vol(key)
        if vol is None:
            return
        vol.read_allocs[alloc.id] = alloc.node_id
        vol.read_blocks.pop(alloc.id, None)

    def snapshot_restore(self, doc):
        self._csi_volumes = {}
        for key, vol in doc.items():
            self._csi_volumes[key] = vol

    def fresh_local(self):
        acc = {}
        acc["k"] = 1
        acc.update({"j": 2})
        return acc
'''


def test_cow_catches_the_materialize_block_leak():
    got = findings(COW_LEAK, ("cow",))
    assert len(got) == 2, got
    assert all("_writable_" in m for m in msgs(COW_LEAK, ("cow",)))


def test_cow_catches_shallow_replace_inner_mutation():
    got = findings(COW_SHALLOW, ("cow",))
    # scalar attribute write on the fresh outer object is fine; the
    # inner-dict pop is the leak
    assert len(got) == 1, got
    assert "replace" in got[0][3]


def test_cow_catches_direct_table_writes():
    got = findings(COW_DIRECT, ("cow",))
    assert len(got) == 2, got


def test_cow_accepts_writable_copies_and_fresh_rebinds():
    assert findings(COW_GOOD, ("cow",)) == []


# -------------------------------------------------------- pass C: purity

PURITY_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np


def kernel(used, cap):
    free = cap - used
    total = np.asarray(free)
    return jnp.sum(free) + float(total.sum())


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    best = jnp.argmax(out)
    stale = used + 1
    return best, stale


def collect(buf):
    buf.block_until_ready()
    return buf
'''

PURITY_GOOD = '''
import jax
import jax.numpy as jnp


def kernel(used, cap):
    free = cap - used
    scale = float(1e-3)
    return jnp.where(free > 0, free, 0).sum() * scale


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    used = out
    return used


def host_branches(used, cap, chained):
    if chained:
        out = kernel_jit(used, cap)
    else:
        out = used.copy()
    return out
'''


def test_purity_flags_sync_eager_and_donated_reuse():
    got = msgs(PURITY_BAD, ("purity",))
    assert len(got) == 5, got
    assert any("np.asarray" in m for m in got)
    assert any("float()" in m for m in got)
    assert any("eager jnp.argmax" in m for m in got)
    assert any("DONATED" in m for m in got)
    assert any("block_until_ready" in m for m in got)


def test_purity_accepts_jit_jnp_rebinds_and_exclusive_branches():
    # jnp inside the traced kernel, float() on a constant, a donated
    # buffer rebound before its next read, and a read in the if-arm
    # that did NOT donate: all clean
    assert findings(PURITY_GOOD, ("purity",)) == []


# -------------------------------------------------------- pass D: thread

THREAD_BAD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        self.establish_leadership()

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
'''

THREAD_GOOD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        try:
            self.establish_leadership()
        except Exception:
            self.revoke_leadership()

    def _guarded_loop(self):
        while True:
            try:
                self.tick()
            except Exception:
                pass

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
        threading.Thread(target=self._guarded_loop).start()
'''


def test_thread_flags_unguarded_daemon_callbacks():
    got = findings(THREAD_BAD, ("thread",))
    assert len(got) == 1, got
    assert "_on_raft_leader" in got[0][3]


def test_thread_accepts_guarded_targets():
    assert findings(THREAD_GOOD, ("thread",)) == []


# ---------------------------------------------------------- suppression

def test_suppression_silences_only_its_pass():
    suppressed = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok thread")
    assert findings(suppressed, ("thread",)) == []
    # the wrong pass name does NOT silence it
    wrong = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok cow")
    assert len(findings(wrong, ("thread",))) == 1
    # the wildcard silences everything on the line
    wild = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok *")
    assert findings(wild, ("thread",)) == []


def test_suppression_is_per_line():
    two = COW_DIRECT  # two violations on two different lines
    one_off = two.replace(
        "self._csi_volumes.pop(key, None)",
        "self._csi_volumes.pop(key, None)  # analyze: ok cow")
    got = findings(one_off, ("cow",))
    assert len(got) == 1, got


# --------------------------------------------------- pass E: lockorder

LOCKORDER_CYCLE = '''
import threading


class Alpha:
    def __init__(self, beta):
        self._lock = threading.Lock()
        self.beta = beta

    def enter_alpha(self):
        with self._lock:
            return 1

    def step(self):
        with self._lock:
            self.beta.enter_beta()


class Beta:
    def __init__(self, gamma):
        self._lock = threading.Lock()
        self.gamma = gamma

    def enter_beta(self):
        with self._lock:
            self.gamma.enter_gamma()


class Gamma:
    def __init__(self, alpha):
        self._lock = threading.Lock()
        self.alpha = alpha

    def enter_gamma(self):
        with self._lock:
            self.alpha.enter_alpha()
'''

LOCKORDER_BLOCKING = '''
import threading


class Sender:
    def __init__(self, conn):
        self._lock = threading.Lock()
        self._conn = conn

    def send_under_lock(self, buf):
        with self._lock:
            self._conn.send_bytes(buf)

    def send_clean(self, buf):
        with self._lock:
            payload = self._pack(buf)
        self._conn.send_bytes(payload)
'''

LOCKORDER_GOOD = '''
import threading


class Ordered:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            self.compute()

    def compute(self):
        return 1


class Waiter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []

    def dequeue(self, timeout):
        with self._cv:
            while not self._items:
                self._cv.wait(timeout)
            return self._items.pop()

    def counters(self):
        # dict named like a queue must NOT read as Queue.get()
        with self._lock:
            return self._dequeues.get("k", 0)
'''


def test_lockorder_finds_three_lock_cycle():
    got = findings(LOCKORDER_CYCLE, ("lockorder",))
    cycles = [m for m in msgs(LOCKORDER_CYCLE, ("lockorder",))
              if "lock-order cycle" in m]
    assert len(cycles) == 1, got
    # the cycle names all three lock nodes
    assert all(n in cycles[0] for n in
               ("Alpha._lock", "Beta._lock", "Gamma._lock")), cycles


def test_lockorder_finds_transitive_self_reacquire():
    # step() holds Alpha._lock and transitively reaches enter_alpha(),
    # which re-takes the same non-reentrant Lock
    got = msgs(LOCKORDER_CYCLE, ("lockorder",))
    assert any("re-acquired" in m for m in got), got


def test_lockorder_flags_blocking_under_lock_only():
    got = findings(LOCKORDER_BLOCKING, ("lockorder",))
    assert len(got) == 1, got
    assert "send_bytes" in got[0][3]
    # the clean variant sends after the with-block closes: the finding
    # must anchor on the locked send, not the unlocked one
    assert "send_under_lock" not in got[0][3]


def test_lockorder_accepts_order_and_cond_wait():
    assert findings(LOCKORDER_GOOD, ("lockorder",)) == []


def test_lockorder_suppression():
    suppressed = LOCKORDER_BLOCKING.replace(
        "self._conn.send_bytes(buf)",
        "self._conn.send_bytes(buf)  # analyze: ok lockorder")
    assert findings(suppressed, ("lockorder",)) == []


# ------------------------------------------------- pass F: determinism

DETERMINISM_BAD = '''
import os
import random


def canonical_trace(events, tags, path):
    order = set(tags)
    for t in order:
        events.append(t)
    names = ",".join({e.name for e in events})
    jitter = random.random()
    events.sort(key=id)
    files = os.listdir(path)
    return names, jitter, files
'''

DETERMINISM_GOOD = '''
import os


def canonical_trace(events, tags, path, rng):
    for t in sorted(set(tags)):
        events.append(t)
    names = ",".join(sorted({e.name for e in events}))
    jitter = rng.random()
    events.sort(key=lambda e: e.id)
    files = sorted(os.listdir(path))
    by_kind = {}
    for kind, evs in by_kind.items():   # dict iteration is ordered
        events.extend(evs)
    return names, jitter, files
'''


def test_determinism_flags_drift_sources():
    got = msgs(DETERMINISM_BAD, ("determinism",))
    assert len(got) == 5, got
    assert any("unordered set" in m for m in got)
    assert any("random.random" in m for m in got)
    assert any("keyed on builtin id" in m for m in got)
    assert any("filesystem" in m for m in got)


def test_determinism_accepts_sorted_and_seeded_shapes():
    assert findings(DETERMINISM_GOOD, ("determinism",)) == []


# --------------------------------------------------- pass G: wireproto

WIREPROTO_DRIFT = '''
class Pool:
    def _handle(self, child, op, payload):
        if op == "deq":
            return self._handle_deq(child, payload)
        if op == "ack":
            return payload["job"]
        if op == "ghost":
            return None
        return None

    def _handle_deq(self, child, payload):
        return payload["n"]


class Proxy:
    def __init__(self, chan):
        self._chan = chan

    def deq(self):
        return self._chan.call("deq", {"n": 4})

    def ack(self):
        return self._chan.call("ack", {"id": 7})

    def drop(self):
        self._chan.notify("orphan", {})
'''

WIREPROTO_ROUNDTRIP = '''
class Pool:
    def _handle(self, child, op, payload):
        if op == "deq":
            return self._handle_deq(child, payload)
        if op in ("ready", "pull"):
            if op == "pull":
                return payload.get("since")
            return {"ok": True}
        return None

    def _handle_deq(self, child, payload):
        return payload["n"]


class Proxy:
    def __init__(self, chan):
        self._chan = chan

    def deq(self):
        return self._chan.call("deq", {"n": 4})

    def handshake(self, idx):
        self._chan.call("ready", {"idx": idx})
        return self._chan.call("pull", {"since": 0})
'''


def test_wireproto_flags_op_and_payload_drift():
    got = msgs(WIREPROTO_DRIFT, ("wireproto",))
    assert len(got) == 3, got
    assert any("'orphan' is sent but has no dispatch" in m
               for m in got)
    assert any("'ghost' has no send site" in m for m in got)
    assert any("payload['job']" in m for m in got)


def test_wireproto_accepts_consistent_table():
    # membership arms (`op in (...)`), tolerant .get() reads, and
    # helper-forwarded strict reads all round-trip clean
    assert findings(WIREPROTO_ROUNDTRIP, ("wireproto",)) == []


def test_wireproto_manifest_detects_field_drift():
    import ast as _ast
    import wireproto as wp
    src = ("from dataclasses import dataclass\n"
           "@dataclass\n"
           "class Job:\n"
           "    id: str\n"
           "    priority: int\n")
    files = {"structs.py": _ast.parse(src)}
    manifest = wp.compute_struct_manifest(files, version=1)
    assert manifest["structs"] == {"Job": ["id", "priority"]}
    # no drift, matching version: clean
    wire_tree = _ast.parse("SCHEMA_VERSION = 1\n")
    assert wp.check_manifest(files, manifest, wire_tree,
                             "wire.py", "m.json") == []
    # grow a field without regenerating: drift finding
    drifted = {"structs.py": _ast.parse(src + "    affinity: str\n")}
    got = wp.check_manifest(drifted, manifest, wire_tree,
                            "wire.py", "m.json")
    assert len(got) == 1 and "drifted" in got[0][3], got
    # regenerated manifest but stale wire constant: version finding
    manifest2 = wp.compute_struct_manifest(drifted, version=2)
    got = wp.check_manifest(drifted, manifest2, wire_tree,
                            "wire.py", "m.json")
    assert len(got) == 1 and "SCHEMA_VERSION" in got[0][3], got
    # bumped constant: clean again
    wire_tree2 = _ast.parse("SCHEMA_VERSION = 2\n")
    assert wp.check_manifest(drifted, manifest2, wire_tree2,
                             "wire.py", "m.json") == []


# --------------------------------------------------- rawtime re-import

RAWTIME_NESTED = '''
class Timers:
    def lazy_from_alias(self):
        from time import time as _t
        return _t()

    def lazy_mod_alias(self):
        import time as _clock
        return _clock.time()

    def clean(self):
        return self.clock.time()
'''


def test_rawtime_catches_nested_aliased_reimports():
    got = findings(RAWTIME_NESTED, ("rawtime",))
    assert len(got) == 2, got


# --------------------------------------------- obsbus plane registry

OBSBUS_BAD = '''
REGISTRY = object()


def configure(clock):
    REGISTRY.clock = clock
'''

OBSBUS_GOOD = '''
from nomad_tpu.core.obsbus import OBSBUS

REGISTRY = object()


def configure(clock):
    REGISTRY.clock = clock


OBSBUS.register("fixture", configure=configure)
'''


def test_obsbus_flags_unregistered_plane():
    got = findings(OBSBUS_BAD, ("obsbus",))
    assert len(got) == 1 and "OBSBUS.register" in got[0][3], got


def test_obsbus_accepts_registered_plane():
    assert findings(OBSBUS_GOOD, ("obsbus",)) == []


def test_obsbus_suppression():
    silenced = OBSBUS_BAD.replace(
        "def configure(clock):",
        "def configure(clock):  # analyze: ok obsbus")
    assert findings(silenced, ("obsbus",)) == []


# ------------------------------------------ stale-suppression account

def test_stale_suppressions_reported_repo_wide():
    findings_repo, stale = analyze.analyze_repo_full()
    assert findings_repo == []
    assert stale == [], "\n".join(
        f"{p}:{ln}: stale `# analyze: ok {tok}`" for p, ln, tok in stale)


# ----------------------------------------------------- selftest + repo

def test_selftest_green():
    assert analyze.selftest() == 0


def test_repo_is_violation_free():
    """The same gate scripts/ci.sh runs: all nine passes over their
    scoped files, zero findings.  A true positive introduced by a
    future PR fails HERE with the file:line in the assertion message."""
    got = analyze.analyze_repo()
    assert got == [], "\n".join(
        f"{p}:{ln}: [{name}] {m}" for p, ln, name, m in got)
