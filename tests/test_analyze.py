"""Invariant-analyzer coverage (scripts/analyze.py).

Each pass gets positive fixtures (the exact bug class it exists to
catch, including the pre-fix shape of the round-5
`_materialize_block_locked` snapshot leak) and negative fixtures (the
blessed shapes the codebase actually uses — `with self._lock:` scopes,
`_writable_*` copies, rebound donated buffers).  Plus: suppression
comments silence exactly their pass, the selftest is green, and the
WHOLE repo is violation-free (the same gate CI runs).
"""

import importlib.util
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "analyze", ROOT / "scripts" / "analyze.py")
analyze = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(analyze)


def findings(text, passes):
    return analyze.analyze_source(text, passes=passes)


def msgs(text, passes):
    return [f[3] for f in findings(text, passes)]


# ---------------------------------------------------------- pass A: lock

LOCK_BAD = '''
class StateStore:
    def broken_entry(self, x):
        self._insert_thing_locked(x)

    def broken_helper(self, key):
        return self._writable_claim_vol(key)
'''

LOCK_GOOD = '''
class StateStore:
    def upsert(self, x):
        with self._lock:
            self._insert_thing_locked(x)

    def _merge_locked(self, x):
        self._insert_thing_locked(x)

    def _writable_tables(self):
        return self._insert_thing_locked(None)

    def via_alias(self, x):
        lk = self._lock
        with lk:
            self._insert_thing_locked(x)

    def under_condition(self, x):
        with self._cv:
            self._insert_thing_locked(x)
'''


def test_lock_flags_unlocked_callers():
    got = findings(LOCK_BAD, ("lock",))
    assert len(got) == 2, got
    assert all("outside" in m for m in msgs(LOCK_BAD, ("lock",)))


def test_lock_accepts_locked_scopes_and_aliases():
    assert findings(LOCK_GOOD, ("lock",)) == []


# ----------------------------------------------------------- pass B: cow

# the EXACT pre-fix shape of the round-5 `_materialize_block_locked`
# snapshot-isolation leak: a claim-vol fetched straight out of the
# shared table, then mutated in place (ADVICE.md round-5 medium)
COW_LEAK = '''
class StateStore:
    def _materialize_block_locked(self, block):
        key = (block.namespace, block.source)
        vol = self._csi_volumes.get(key)
        if vol is None or block.id not in vol.read_blocks:
            return
        vol.read_blocks.pop(block.id, None)
        vol.read_allocs.update({a: "" for a in block.ids})
'''

COW_SHALLOW = '''
class StateStore:
    def _release_locked(self, key, aid):
        import dataclasses
        vol = self._csi_volumes.get(key)
        v = dataclasses.replace(vol)
        v.modify_index = 7
        v.read_allocs.pop(aid, None)
'''

COW_DIRECT = '''
class StateStore:
    def delete_volume(self, key):
        self._csi_volumes.pop(key, None)

    def set_volume(self, key, vol):
        self._csi_volumes[key] = vol
'''

COW_GOOD = '''
class StateStore:
    def _claim_ok_locked(self, key, alloc):
        vol = self._writable_claim_vol(key)
        if vol is None:
            return
        vol.read_allocs[alloc.id] = alloc.node_id
        vol.read_blocks.pop(alloc.id, None)

    def snapshot_restore(self, doc):
        self._csi_volumes = {}
        for key, vol in doc.items():
            self._csi_volumes[key] = vol

    def fresh_local(self):
        acc = {}
        acc["k"] = 1
        acc.update({"j": 2})
        return acc
'''


def test_cow_catches_the_materialize_block_leak():
    got = findings(COW_LEAK, ("cow",))
    assert len(got) == 2, got
    assert all("_writable_" in m for m in msgs(COW_LEAK, ("cow",)))


def test_cow_catches_shallow_replace_inner_mutation():
    got = findings(COW_SHALLOW, ("cow",))
    # scalar attribute write on the fresh outer object is fine; the
    # inner-dict pop is the leak
    assert len(got) == 1, got
    assert "replace" in got[0][3]


def test_cow_catches_direct_table_writes():
    got = findings(COW_DIRECT, ("cow",))
    assert len(got) == 2, got


def test_cow_accepts_writable_copies_and_fresh_rebinds():
    assert findings(COW_GOOD, ("cow",)) == []


# -------------------------------------------------------- pass C: purity

PURITY_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np


def kernel(used, cap):
    free = cap - used
    total = np.asarray(free)
    return jnp.sum(free) + float(total.sum())


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    best = jnp.argmax(out)
    stale = used + 1
    return best, stale


def collect(buf):
    buf.block_until_ready()
    return buf
'''

PURITY_GOOD = '''
import jax
import jax.numpy as jnp


def kernel(used, cap):
    free = cap - used
    scale = float(1e-3)
    return jnp.where(free > 0, free, 0).sum() * scale


kernel_jit = jax.jit(kernel, donate_argnums=(0,))


def host_loop(used, cap):
    out = kernel_jit(used, cap)
    used = out
    return used


def host_branches(used, cap, chained):
    if chained:
        out = kernel_jit(used, cap)
    else:
        out = used.copy()
    return out
'''


def test_purity_flags_sync_eager_and_donated_reuse():
    got = msgs(PURITY_BAD, ("purity",))
    assert len(got) == 5, got
    assert any("np.asarray" in m for m in got)
    assert any("float()" in m for m in got)
    assert any("eager jnp.argmax" in m for m in got)
    assert any("DONATED" in m for m in got)
    assert any("block_until_ready" in m for m in got)


def test_purity_accepts_jit_jnp_rebinds_and_exclusive_branches():
    # jnp inside the traced kernel, float() on a constant, a donated
    # buffer rebound before its next read, and a read in the if-arm
    # that did NOT donate: all clean
    assert findings(PURITY_GOOD, ("purity",)) == []


# -------------------------------------------------------- pass D: thread

THREAD_BAD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        self.establish_leadership()

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
'''

THREAD_GOOD = '''
import threading


class ClusterServer:
    def _on_raft_leader(self):
        try:
            self.establish_leadership()
        except Exception:
            self.revoke_leadership()

    def _guarded_loop(self):
        while True:
            try:
                self.tick()
            except Exception:
                pass

    def start(self):
        RaftNode(on_leader=self._on_raft_leader)
        threading.Thread(target=self._guarded_loop).start()
'''


def test_thread_flags_unguarded_daemon_callbacks():
    got = findings(THREAD_BAD, ("thread",))
    assert len(got) == 1, got
    assert "_on_raft_leader" in got[0][3]


def test_thread_accepts_guarded_targets():
    assert findings(THREAD_GOOD, ("thread",)) == []


# ---------------------------------------------------------- suppression

def test_suppression_silences_only_its_pass():
    suppressed = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok thread")
    assert findings(suppressed, ("thread",)) == []
    # the wrong pass name does NOT silence it
    wrong = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok cow")
    assert len(findings(wrong, ("thread",))) == 1
    # the wildcard silences everything on the line
    wild = THREAD_BAD.replace(
        "def _on_raft_leader(self):",
        "def _on_raft_leader(self):  # analyze: ok *")
    assert findings(wild, ("thread",)) == []


def test_suppression_is_per_line():
    two = COW_DIRECT  # two violations on two different lines
    one_off = two.replace(
        "self._csi_volumes.pop(key, None)",
        "self._csi_volumes.pop(key, None)  # analyze: ok cow")
    got = findings(one_off, ("cow",))
    assert len(got) == 1, got


# ----------------------------------------------------- selftest + repo

def test_selftest_green():
    assert analyze.selftest() == 0


def test_repo_is_violation_free():
    """The same gate scripts/ci.sh runs: every pass over its scoped
    files, zero findings.  A true positive introduced by a future PR
    fails HERE with the file:line in the assertion message."""
    got = analyze.analyze_repo()
    assert got == [], "\n".join(
        f"{p}:{ln}: [{name}] {m}" for p, ln, name, m in got)
