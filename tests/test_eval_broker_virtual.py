"""Eval broker under virtual time: nack-requeue penalty, delayed-eval
promotion, and delivery-limit failure driven by a VirtualClock — each
scripted sequence is run twice and its canonical trace compared byte
for byte (the broker's observable schedule is a pure function of the
script; reference: eval_broker.go nack delay + delayed eval heap)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.chaos.clock import VirtualClock
from nomad_tpu.chaos.trace import Trace
from nomad_tpu.core.eval_broker import EvalBroker


def _broker(**kw):
    b = EvalBroker(**kw)
    b.set_enabled(True)
    return b


class TestNackPenalty:
    def test_first_nack_redelivers_immediately(self):
        b = _broker(subsequent_nack_delay=20.0)
        b.enqueue(mock.eval(id="e1", job_id="j1"), now=0.0)
        ev, tok = b.dequeue(["service"], now=0.0, timeout=0.0)
        assert b.nack(ev.id, tok, now=0.0) is None
        ev2, _ = b.dequeue(["service"], now=0.0, timeout=0.0)
        assert ev2 is not None and ev2.id == "e1"
        assert b.stats["nack_delayed"] == 0

    def test_subsequent_nack_parks_in_delayed_heap(self):
        clock = VirtualClock()
        b = _broker(subsequent_nack_delay=20.0)
        b.enqueue(mock.eval(id="e1", job_id="j1"),
                  now=clock.monotonic())
        for _ in range(2):          # attempt 1 nack: immediate requeue
            ev, tok = b.dequeue(["service"], now=clock.monotonic(),
                                timeout=0.0)
            assert ev is not None
            b.nack(ev.id, tok, now=clock.monotonic())
        assert b.stats["nack_delayed"] == 1
        # penalty window: nothing ready until 20 virtual seconds pass
        none, _ = b.dequeue(["service"], now=clock.monotonic(),
                            timeout=0.0)
        assert none is None
        clock.advance(19.5)
        b.tick(clock.monotonic())
        none, _ = b.dequeue(["service"], now=clock.monotonic(),
                            timeout=0.0)
        assert none is None
        clock.advance(0.5)
        b.tick(clock.monotonic())
        ev, tok = b.dequeue(["service"], now=clock.monotonic(),
                            timeout=0.0)
        assert ev is not None and ev.id == "e1"
        assert b.ack(ev.id, tok) is None

    def test_penalized_eval_counts_as_pending(self):
        b = _broker(subsequent_nack_delay=20.0)
        b.enqueue(mock.eval(id="e1", job_id="j1"), now=0.0)
        for _ in range(2):
            ev, tok = b.dequeue(["service"], now=0.0, timeout=0.0)
            b.nack(ev.id, tok, now=0.0)
        assert b.pending_evals() == 1   # parked, not lost


class TestDeterministicReplay:
    """The same scripted churn twice -> byte-identical canonical
    traces.  The script exercises every broker path the soak leans on:
    penalty redeliveries, wait_until promotion, nack-timeout expiry,
    and delivery-limit failure."""

    def _run_script(self) -> bytes:
        clock = VirtualClock()
        trace = Trace()
        b = _broker(nack_timeout=30.0, delivery_limit=3,
                    subsequent_nack_delay=10.0)
        try:
            # j-flaky nacks until the delivery limit; j-late waits on
            # wait_until; j-slow's worker dies (nack-timeout expiry);
            # j-good acks first time
            b.enqueue(mock.eval(id="e-flaky", job_id="j-flaky"),
                      now=clock.monotonic())
            b.enqueue(mock.eval(id="e-late", job_id="j-late",
                                wait_until=clock.monotonic() + 25.0),
                      now=clock.monotonic())
            b.enqueue(mock.eval(id="e-slow", job_id="j-slow"),
                      now=clock.monotonic())
            b.enqueue(mock.eval(id="e-good", job_id="j-good"),
                      now=clock.monotonic())
            held = {}
            for _ in range(200):
                now = clock.monotonic()
                b.tick(now)
                while True:
                    ev, tok = b.dequeue(["service"], now=now,
                                        timeout=0.0)
                    if ev is None:
                        break
                    attempt = b._dequeues.get(ev.id, 0)
                    trace.record(now, "dequeue", eval=ev.id,
                                 attempt=attempt)
                    if ev.id == "e-flaky":
                        b.nack(ev.id, tok, now=now)
                        trace.record(now, "nack", eval=ev.id,
                                     attempt=attempt)
                    elif ev.id == "e-slow" and not held:
                        held[ev.id] = tok   # worker wedges: no ack
                    else:
                        b.ack(ev.id, tok)
                        trace.record(now, "ack", eval=ev.id,
                                     attempt=attempt)
                for ev in b.drain_failed():
                    trace.record(clock.monotonic(), "failed",
                                 eval=ev.id)
                clock.advance(1.0)
            trace.record(clock.monotonic(), "verdict",
                         stats={k: b.stats[k] for k in
                                ("enqueued", "dequeued", "acked",
                                 "nacked", "nack_delayed", "failed")},
                         pending=b.pending_evals())
            return trace.canonical_bytes()
        finally:
            clock.close()

    def test_double_run_byte_identical(self):
        first = self._run_script()
        second = self._run_script()
        assert first == second

    def test_script_hits_every_path(self):
        text = self._run_script().decode()
        # flaky reached the delivery limit and failed out
        assert 'failed {"at"' in text and '"e-flaky"' in text
        # the delayed eval was promoted and acked after its wait_until
        assert '"eval":"e-late"' in text
        # the wedged delivery expired and the redelivery was acked
        acks = [ln for ln in text.splitlines()
                if ln.startswith("ack ") and "e-slow" in ln]
        assert len(acks) == 1 and '"attempt":2' in acks[0]


class TestDelayedPromotion:
    def test_wait_until_promotes_on_tick(self):
        clock = VirtualClock()
        b = _broker()
        b.enqueue(mock.eval(id="e1", job_id="j1",
                            wait_until=clock.monotonic() + 5.0),
                  now=clock.monotonic())
        none, _ = b.dequeue(["service"], now=clock.monotonic(),
                            timeout=0.0)
        assert none is None
        clock.advance(5.0)
        b.tick(clock.monotonic())
        ev, _ = b.dequeue(["service"], now=clock.monotonic(),
                          timeout=0.0)
        assert ev is not None and ev.id == "e1"


class TestDeliveryLimitChurn:
    def test_limit_reached_through_penalty_cycles(self):
        """A persistently nacking eval still fails out at the delivery
        limit even though later attempts route through the penalty
        heap (the soak's guarantee that poison evals drain)."""
        clock = VirtualClock()
        b = _broker(delivery_limit=3, subsequent_nack_delay=5.0)
        b.enqueue(mock.eval(id="e1", job_id="j1"),
                  now=clock.monotonic())
        nacks = 0
        for _ in range(100):
            now = clock.monotonic()
            b.tick(now)
            ev, tok = b.dequeue(["service"], now=now, timeout=0.0)
            if ev is not None:
                b.nack(ev.id, tok, now=now)
                nacks += 1
            if b.failed_evals():
                break
            clock.advance(1.0)
        assert nacks == 3
        assert [e.id for e in b.drain_failed()] == ["e1"]
        assert b.stats["nack_delayed"] == 1   # only attempt 2 delayed
        assert b.pending_evals() == 0


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
