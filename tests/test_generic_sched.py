"""Generic + system scheduler tests through the Harness
(reference scenarios: scheduler/generic_sched_test.go, system_sched_test.go)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import BUILTIN_SCHEDULERS, Harness
from nomad_tpu.structs import (
    DrainStrategy,
    Resources,
)


NOW = 1_700_000_000.0


def make_harness(n_nodes=10):
    h = Harness()
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(n)
    return h, nodes


def register_and_eval(h, job):
    h.state.upsert_job(job)
    e = mock.eval(job_id=job.id, type=job.type)
    h.state.upsert_evals([e])
    return e


class TestServiceScheduler:
    def test_factories_registered(self):
        for name in ("service", "batch", "system", "sysbatch",
                     "service-tpu", "batch-tpu"):
            assert name in BUILTIN_SCHEDULERS

    def test_register_places_all(self):
        h, nodes = make_harness(10)
        job = mock.job()   # count=10, 500MHz/256MB
        e = register_and_eval(h, job)
        err = h.process("service", e, now=NOW)
        assert err is None
        assert len(h.plans) == 1
        plan = h.plans[0]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 10
        # names indexed 0..9, metrics attached
        idxs = sorted(a.index() for a in placed)
        assert idxs == list(range(10))
        assert all(a.metrics.nodes_evaluated == 10 for a in placed)
        h.assert_eval_status("complete")
        # state shows them
        out = h.snapshot().allocs_by_job(job.namespace, job.id)
        assert len(out) == 10

    def test_exhausted_creates_blocked_eval(self):
        h, _ = make_harness(1)   # one node: 3900MHz usable
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].tasks[0].resources = Resources(cpu=1500, memory_mb=64)
        e = register_and_eval(h, job)
        assert h.process("service", e, now=NOW) is None
        plan = h.plans[0]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 2          # 2x1500 fits in 3900
        assert len(h.create_evals) == 1
        blocked = h.create_evals[0]
        assert blocked.status == "blocked"
        assert blocked.previous_eval == e.id
        assert "web" in blocked.failed_tg_allocs
        assert h.evals[-1].queued_allocations["web"] == 3
        m = blocked.failed_tg_allocs["web"]
        assert m.dimension_exhausted.get("cpu", 0) > 0
        assert m.coalesced_failures == 2

    def test_stop_job_stops_all(self):
        h, nodes = make_harness(3)
        job = mock.job()
        job.task_groups[0].count = 3
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        stopped = h.snapshot().job_by_id(job.namespace, job.id).copy()
        stopped.stop = True
        h.state.upsert_job(stopped)
        e2 = mock.eval(job_id=job.id, triggered_by="job-deregister")
        h.process("service", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert len(stops) == 3
        assert all(a.desired_status == "stop" for a in stops)

    def test_count_decrease_stops_highest_indexes(self):
        h, _ = make_harness(5)
        job = mock.job()
        job.task_groups[0].count = 5
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        j2 = h.snapshot().job_by_id(job.namespace, job.id).copy()
        j2.task_groups[0].count = 3
        h.state.upsert_job(j2)
        e2 = mock.eval(job_id=job.id)
        h.process("service", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert sorted(a.index() for a in stops) == [3, 4]

    def test_node_down_replaces_lost(self):
        h, nodes = make_harness(3)
        job = mock.job()
        job.task_groups[0].count = 2
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        # find a node hosting an alloc, take it down
        snap = h.snapshot()
        victim = next(a.node_id for a in snap.allocs_by_job(job.namespace, job.id))
        h.state.update_node_status(victim, "down")
        e2 = mock.eval(job_id=job.id, triggered_by="node-update")
        h.process("service", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert len(stops) == 1 and stops[0].client_status == "lost"
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 1
        assert placed[0].node_id != victim
        assert placed[0].previous_allocation == stops[0].id

    def test_drain_migrates(self):
        from nomad_tpu.structs import DesiredTransition
        h, nodes = make_harness(3)
        job = mock.job()
        job.task_groups[0].count = 2
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        snap = h.snapshot()
        victim_alloc = next(a for a in snap.allocs_by_job(job.namespace, job.id))
        victim = victim_alloc.node_id
        h.state.update_node_drain(victim, DrainStrategy(deadline_s=3600))

        # an unflagged alloc on a draining node keeps running (the drainer
        # releases batches by setting DesiredTransition.migrate) — the
        # eval is a no-op, no plan is submitted
        n_plans = len(h.plans)
        e2 = mock.eval(job_id=job.id, triggered_by="node-drain")
        h.process("service", e2, now=NOW)
        assert len(h.plans) == n_plans

        h.state.update_alloc_desired_transition(
            [victim_alloc.id], DesiredTransition(migrate=True))
        e3 = mock.eval(job_id=job.id, triggered_by="node-drain")
        h.process("service", e3, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert len(stops) == 1
        assert stops[0].desired_description == "alloc is being migrated"
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 1 and placed[0].node_id != victim

    def test_failed_alloc_reschedules_later_with_followup(self):
        h, _ = make_harness(2)
        job = mock.job()
        job.task_groups[0].count = 1
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        a = h.snapshot().allocs_by_job(job.namespace, job.id)[0]
        fail = a.copy_skip_job()
        fail.client_status = "failed"
        fail.modify_time = NOW
        h.state.upsert_allocs([fail])
        e2 = mock.eval(job_id=job.id, triggered_by="alloc-failure")
        h.process("service", e2, now=NOW + 1)
        # policy delay is 30s exponential -> later
        followups = [ev for ev in h.create_evals
                     if ev.triggered_by == "failed-follow-up"]
        assert len(followups) == 1
        assert followups[0].wait_until == pytest.approx(NOW + 30)
        # the failed alloc is annotated with the follow-up eval id
        ann = h.snapshot().alloc_by_id(a.id)
        assert ann.followup_eval_id == followups[0].id

    def test_failed_alloc_reschedules_now_after_delay(self):
        h, _ = make_harness(2)
        job = mock.job()
        job.task_groups[0].count = 1
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        a = h.snapshot().allocs_by_job(job.namespace, job.id)[0]
        prev_node = a.node_id
        fail = a.copy_skip_job()
        fail.client_status = "failed"
        fail.modify_time = NOW
        h.state.upsert_allocs([fail])
        e2 = mock.eval(job_id=job.id, triggered_by="alloc-failure")
        h.process("service", e2, now=NOW + 60)   # past the 30s delay
        plan = h.plans[-1]
        placed = [x for allocs in plan.node_allocation.values() for x in allocs
                  if x.id != a.id]
        assert len(placed) == 1
        new = placed[0]
        assert new.previous_allocation == a.id
        assert new.reschedule_tracker is not None
        assert len(new.reschedule_tracker.events) == 1
        # reschedule penalty: should avoid the previous node
        assert new.node_id != prev_node

    def test_destructive_update_respects_max_parallel(self):
        h, _ = make_harness(6)
        job = mock.job()
        job.task_groups[0].count = 4
        job.update.max_parallel = 2
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        j2 = h.snapshot().job_by_id(job.namespace, job.id).copy()
        j2.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
        h.state.upsert_job(j2)
        e2 = mock.eval(job_id=job.id)
        h.process("service", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert len(stops) == 2            # max_parallel
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 2
        assert all(a.job_version == j2.version + 0 or True for a in placed)
        assert plan.deployment is not None
        assert plan.deployment.task_groups["web"].desired_total == 4

    def test_inplace_update_when_tasks_unchanged(self):
        h, _ = make_harness(4)
        job = mock.job()
        job.task_groups[0].count = 2
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        j2 = h.snapshot().job_by_id(job.namespace, job.id).copy()
        j2.priority = 70   # non-destructive change
        h.state.upsert_job(j2)
        e2 = mock.eval(job_id=job.id)
        h.process("service", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert stops == []
        updated = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(updated) == 2
        cur = h.snapshot().job_by_id(job.namespace, job.id)
        stored = h.snapshot().allocs_by_job(job.namespace, job.id)
        assert all(a.job_version == cur.version for a in stored)


class TestBatchScheduler:
    def test_completed_batch_not_replaced(self):
        h, _ = make_harness(2)
        job = mock.batch_job()
        job.task_groups[0].count = 2
        e = register_and_eval(h, job)
        h.process("batch", e, now=NOW)
        allocs = h.snapshot().allocs_by_job(job.namespace, job.id)
        assert len(allocs) == 2
        done = allocs[0].copy_skip_job()
        done.client_status = "complete"
        h.state.upsert_allocs([done])
        e2 = mock.eval(job_id=job.id, type="batch")
        h.process("batch", e2, now=NOW)
        plan = h.plans[-1] if len(h.plans) > 1 else None
        # no new placements (the completed alloc is not replaced)
        if plan is not None:
            placed = [a for allocs in plan.node_allocation.values()
                      for a in allocs]
            assert placed == []


class TestSystemScheduler:
    def test_one_alloc_per_eligible_node(self):
        h, nodes = make_harness(4)
        h.state.upsert_node(mock.node(datacenter="dc2"))  # ineligible dc
        job = mock.system_job()
        e = register_and_eval(h, job)
        err = h.process("system", e, now=NOW)
        assert err is None
        plan = h.plans[0]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 4
        assert len({a.node_id for a in placed}) == 4

    def test_new_node_gets_alloc(self):
        h, nodes = make_harness(2)
        job = mock.system_job()
        e = register_and_eval(h, job)
        h.process("system", e, now=NOW)
        newbie = mock.node()
        h.state.upsert_node(newbie)
        e2 = mock.eval(job_id=job.id, type="system",
                       triggered_by="node-update", node_id=newbie.id)
        h.process("system", e2, now=NOW)
        plan = h.plans[-1]
        placed = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(placed) == 1 and placed[0].node_id == newbie.id

    def test_node_down_stops_system_alloc(self):
        h, nodes = make_harness(2)
        job = mock.system_job()
        e = register_and_eval(h, job)
        h.process("system", e, now=NOW)
        victim = nodes[0].id
        h.state.update_node_status(victim, "down")
        e2 = mock.eval(job_id=job.id, type="system", triggered_by="node-update")
        h.process("system", e2, now=NOW)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        assert len(stops) == 1 and stops[0].node_id == victim
        assert stops[0].client_status == "lost"


class TestReviewRegressions:
    def test_reschedule_later_does_not_double_place(self):
        # A failed alloc with a pending follow-up eval holds its slot: the
        # same eval must NOT also place a replacement now.
        h, _ = make_harness(2)
        job = mock.job()
        job.task_groups[0].count = 1
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        a = h.snapshot().allocs_by_job(job.namespace, job.id)[0]
        fail = a.copy_skip_job()
        fail.client_status = "failed"
        fail.modify_time = NOW
        h.state.upsert_allocs([fail])
        h.process("service", mock.eval(job_id=job.id), now=NOW + 1)
        live = [x for x in h.snapshot().allocs_by_job(job.namespace, job.id)
                if not x.terminal_status() and x.client_status != "failed"]
        assert live == []          # nothing new placed yet

    def test_reschedule_exhausted_never_replaced(self):
        h, _ = make_harness(2)
        job = mock.job()
        job.task_groups[0].count = 1
        job.task_groups[0].reschedule_policy.attempts = 0
        job.task_groups[0].reschedule_policy.unlimited = False
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        a = h.snapshot().allocs_by_job(job.namespace, job.id)[0]
        fail = a.copy_skip_job()
        fail.client_status = "failed"
        fail.modify_time = NOW
        h.state.upsert_allocs([fail])
        for i in range(3):
            h.process("service", mock.eval(job_id=job.id), now=NOW + 100 * i)
        allocs = h.snapshot().allocs_by_job(job.namespace, job.id)
        assert len(allocs) == 1    # only the failed one, never replaced

    def test_destructive_update_on_full_node_can_replace(self):
        # One node; the old alloc nearly fills it. The destructive update
        # must be able to place the replacement into the capacity freed by
        # the stop in the same plan.
        h = Harness()
        n = mock.node()
        n.resources.cpu = 4000
        n.reserved.cpu = 0
        h.state.upsert_node(n)
        job = mock.job()
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources = Resources(cpu=3000, memory_mb=64)
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        assert len(h.snapshot().allocs_by_job(job.namespace, job.id)) == 1
        j2 = h.snapshot().job_by_id(job.namespace, job.id).copy()
        j2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
        h.state.upsert_job(j2)
        h.process("service", mock.eval(job_id=job.id), now=NOW + 1)
        live = [a for a in h.snapshot().allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 1
        cur = h.snapshot().job_by_id(job.namespace, job.id)
        assert live[0].job_version == cur.version
        # lineage: replacement links to the replaced alloc
        assert live[0].previous_allocation

    def test_multi_group_deployment_tracks_all_groups(self):
        from nomad_tpu.structs import Task, TaskGroup, UpdateStrategy
        h, _ = make_harness(4)
        job = mock.job()
        tg2 = TaskGroup(name="api", count=2,
                        tasks=[Task(name="api", driver="exec",
                                    resources=Resources(cpu=100, memory_mb=64))])
        job.task_groups.append(tg2)
        job.update = UpdateStrategy(max_parallel=1)
        e = register_and_eval(h, job)
        h.process("service", e, now=NOW)
        plan = h.plans[0]
        assert plan.deployment is not None
        assert set(plan.deployment.task_groups) == {"web", "api"}


class TestPortExhaustionFallback:
    def test_exhausted_ports_fall_back_to_runner_up(self):
        """Static port taken on the kernel's preferred node: the
        placement must land on the metric's runner-up, not fail
        (VERDICT r4 #5; reference: rank.go iterator pulls the next
        candidate)."""
        from nomad_tpu import mock
        from nomad_tpu.scheduler import Harness
        from nomad_tpu.structs import NetworkResource, Port, Resources

        h = Harness()
        # node A fuller than B -> binpack prefers A
        na, nb = mock.node(), mock.node()
        for n in (na, nb):
            n.resources.cpu = 8000
            n.resources.memory_mb = 16384
        h.state.upsert_nodes([na, nb])
        filler = mock.job()
        h.state.upsert_job(filler)
        base = mock.alloc(job=filler, node_id=na.id)
        base.resources = Resources(cpu=3000, memory_mb=1024)
        h.state.upsert_allocs([base])
        # an alloc on A already owns port 8080
        holder = mock.alloc(job=filler, node_id=na.id)
        holder.resources = Resources(cpu=100, memory_mb=64)
        holder.allocated_ports = {"http": 8080}
        h.state.upsert_allocs([holder])

        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0].resources.networks = [NetworkResource(
            reserved_ports=[Port(label="http", value=8080)])]
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id, type=job.type)
        h.state.upsert_evals([e])
        err = h.process("service", e, now=1.7e9)
        assert err is None
        plan = h.plans[-1]
        placed = [a for allocs in plan.node_allocation.values()
                  for a in allocs]
        assert len(placed) == 1, h.evals[-1].failed_tg_allocs
        # the kernel preferred A (fuller), but 8080 is taken there: the
        # runner-up B must carry the placement
        assert placed[0].node_id == nb.id
        assert placed[0].allocated_ports == {"http": 8080}
        # host redirection dropped the fence: the applier full-checks
        assert plan.coupled_batch is None and plan.host_redirected
