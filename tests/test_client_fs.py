"""Alloc filesystem / logs / stats endpoints
(reference scenarios: client/fs_endpoint.go tests, command/alloc_logs)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent


@pytest.fixture(scope="module")
def agent_with_job(tmp_path_factory):
    agent = Agent(num_clients=1, http_port=0)
    # clients need a writable data_dir for task sandboxes + logs
    for i, c in enumerate(agent.clients):
        c.data_dir = str(tmp_path_factory.mktemp(f"alloc{i}"))
    agent.start()
    job = mock.job()
    job.id = "logjob"
    job.task_groups[0].count = 1
    t = job.task_groups[0].tasks[0]
    t.name = "speaker"
    t.driver = "raw_exec"
    t.config = {"command": "/bin/sh",
                "args": ["-c",
                         "echo hello-stdout; echo hello-stderr 1>&2; "
                         "echo data > artifact.txt; sleep 300"]}
    agent.server.register_job(job)
    deadline = time.time() + 20
    alloc_id = None
    while time.time() < deadline:
        runners = list(agent.clients[0].alloc_runners.values())
        if runners and runners[0].task_runners \
                and runners[0].task_runners[0].state.state == "running":
            alloc_id = runners[0].alloc.id
            break
        time.sleep(0.2)
    assert alloc_id, "task never started"
    time.sleep(0.5)              # let the echos land on disk
    yield agent, alloc_id
    agent.shutdown()


def get(agent, path):
    with urllib.request.urlopen(agent.address + path, timeout=10) as r:
        return json.loads(r.read())


class TestLogs:
    def test_stdout(self, agent_with_job):
        agent, aid = agent_with_job
        r = get(agent, f"/v1/client/fs/logs/{aid}?task=speaker")
        assert "hello-stdout" in r["Data"]
        assert r["Offset"] > 0

    def test_stderr(self, agent_with_job):
        agent, aid = agent_with_job
        r = get(agent, f"/v1/client/fs/logs/{aid}?task=speaker&type=stderr")
        assert "hello-stderr" in r["Data"]

    def test_offset_pagination(self, agent_with_job):
        agent, aid = agent_with_job
        r1 = get(agent, f"/v1/client/fs/logs/{aid}?task=speaker&limit=5")
        assert len(r1["Data"]) == 5
        r2 = get(agent, f"/v1/client/fs/logs/{aid}?task=speaker"
                        f"&offset={r1['Offset']}")
        assert (r1["Data"] + r2["Data"]).startswith("hello-stdout")

    def test_negative_offset_tails(self, agent_with_job):
        agent, aid = agent_with_job
        r = get(agent, f"/v1/client/fs/logs/{aid}?task=speaker&offset=-3")
        assert len(r["Data"]) == 3

    def test_default_task(self, agent_with_job):
        agent, aid = agent_with_job
        r = get(agent, f"/v1/client/fs/logs/{aid}")
        assert "hello-stdout" in r["Data"]


class TestFS:
    def test_ls_and_cat(self, agent_with_job):
        agent, aid = agent_with_job
        top = get(agent, f"/v1/client/fs/ls/{aid}")
        assert any(e["Name"] == "speaker" and e["IsDir"] for e in top)
        files = get(agent, f"/v1/client/fs/ls/{aid}?path=speaker")
        names = {e["Name"] for e in files}
        assert {"speaker.stdout", "speaker.stderr",
                "artifact.txt"} <= names
        body = get(agent,
                   f"/v1/client/fs/cat/{aid}?path=speaker/artifact.txt")
        assert body.strip() == "data"

    def test_path_traversal_blocked(self, agent_with_job):
        agent, aid = agent_with_job
        for bad in ("../../etc/passwd", "..%2F..%2Fetc%2Fpasswd"):
            try:
                get(agent, f"/v1/client/fs/cat/{aid}?path={bad}")
            except urllib.error.HTTPError as e:
                assert e.code in (403, 404)
            else:
                raise AssertionError("traversal not blocked")

    def test_unknown_alloc_404(self, agent_with_job):
        agent, _ = agent_with_job
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(agent, "/v1/client/fs/ls/deadbeef")
        assert ei.value.code == 404


class TestStats:
    def test_alloc_stats(self, agent_with_job):
        agent, aid = agent_with_job
        r = get(agent, f"/v1/client/allocation/{aid}/stats")
        t = r["Tasks"]["speaker"]
        assert t["Pid"] > 0
        assert t["State"] == "running"
        assert t["MemoryRSSKB"] > 0


class TestCLI:
    def test_alloc_logs_command(self, agent_with_job, capsys):
        agent, aid = agent_with_job
        from nomad_tpu.cli import main
        rc = main(["-address", agent.address, "alloc", "logs", aid,
                   "speaker"])
        assert rc == 0
        assert "hello-stdout" in capsys.readouterr().out

    def test_alloc_fs_command(self, agent_with_job, capsys):
        agent, aid = agent_with_job
        from nomad_tpu.cli import main
        rc = main(["-address", agent.address, "alloc", "fs", aid,
                   "speaker"])
        assert rc == 0
        assert "artifact.txt" in capsys.readouterr().out


class TestAllocExec:
    def test_exec_runs_in_task_sandbox(self, agent_with_job):
        """Non-interactive `alloc exec` (reference: DriverPlugin.ExecTask):
        the command runs in the live task's working directory."""
        import base64
        agent, alloc_id = agent_with_job
        req = urllib.request.Request(
            agent.address + f"/v1/client/allocation/{alloc_id}/exec",
            data=json.dumps({"Cmd": ["cat", "artifact.txt"]}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["ExitCode"] == 0
        assert base64.b64decode(out["Output"]).decode().strip() == "data"

    def test_exec_nonzero_exit(self, agent_with_job):
        import base64
        agent, alloc_id = agent_with_job
        req = urllib.request.Request(
            agent.address + f"/v1/client/allocation/{alloc_id}/exec",
            data=json.dumps({"Cmd": ["/bin/sh", "-c",
                                     "echo boom >&2; exit 3"]}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["ExitCode"] == 3
        assert "boom" in base64.b64decode(out["Output"]).decode()
