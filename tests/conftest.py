"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(nomad_tpu.parallel) is exercised without TPU hardware — must be set before
jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
