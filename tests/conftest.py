"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(nomad_tpu.parallel) is exercised without TPU hardware.  The machine's
sitecustomize imports jax and registers the axon TPU plugin before this
conftest runs, so plain env vars are too late — force the platform through
jax.config (no backend is initialized yet at conftest time).  Real-TPU
behavior is covered by bench.py and the verify flows.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second wall-clock test; excluded from tier-1 "
        "(pytest -m 'not slow') and run by the dedicated CI stages "
        "(scripts/ci.sh chaos stage, or -m slow)")
