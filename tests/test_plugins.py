"""External plugin framework tests
(reference scenarios: plugins/drivers/testutils + drivermanager tests —
real subprocess plugins over the handshake protocol)."""

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.plugins import PluginError, PluginManager, launch_plugin

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLUGDIR = os.path.join(REPO, "examples", "plugins")


@pytest.fixture(scope="module")
def manager(tmp_path_factory):
    m = PluginManager(PLUGDIR,
                      socket_dir=str(tmp_path_factory.mktemp("socks")))
    m.scan()
    yield m
    m.shutdown()


class TestProtocol:
    def test_handshake_and_info(self, manager):
        assert "hello" in manager.drivers
        assert "fake-gpu" in manager.devices

    def test_refuses_direct_execution(self):
        import subprocess
        import sys
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("NOMAD_TPU_PLUGIN")}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, os.path.join(PLUGDIR, "hello_driver.py")],
            capture_output=True, timeout=120, env=env)
        assert p.returncode == 1
        assert b"plugin manager" in p.stderr

    def test_bad_plugin_rejected(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        import sys
        with pytest.raises(PluginError):
            launch_plugin([sys.executable, str(bad)],
                          str(tmp_path / "socks"), timeout=60.0)


class TestExternalDriver:
    def test_task_lifecycle(self, manager):
        drv = manager.drivers["hello"]
        fp = drv.fingerprint()
        assert fp["driver.hello"] == "1"
        task = mock.job().task_groups[0].tasks[0]
        task.driver = "hello"
        task.config = {"message": "hi", "run_for_s": 0.2}
        h = drv.start_task("t1", task, {"NOMAD_TASK_NAME": "web"}, "")
        assert h.pid > 0
        res = drv.wait_task(h, timeout=60.0)
        assert res is not None and res.successful()

    def test_stop_task(self, manager):
        drv = manager.drivers["hello"]
        task = mock.job().task_groups[0].tasks[0]
        task.config = {"run_for_s": 300}
        h = drv.start_task("t2", task, {}, "")
        assert drv.recover_task(h)
        drv.stop_task(h, kill_timeout=2.0)
        res = drv.wait_task(h, timeout=60.0)
        assert res is not None

    def test_concurrent_wait_does_not_block_other_calls(self, manager):
        """Request-id multiplexing: a blocked wait_task must not stall
        fingerprints (the reason the reference multiplexes streams)."""
        drv = manager.drivers["hello"]
        task = mock.job().task_groups[0].tasks[0]
        task.config = {"run_for_s": 3}
        h = drv.start_task("t3", task, {}, "")
        import threading
        done = []
        t = threading.Thread(
            target=lambda: done.append(drv.wait_task(h, timeout=30)))
        t.start()
        t0 = time.time()
        fp = drv.fingerprint()
        assert fp and time.time() - t0 < 2.0
        drv.stop_task(h, 1.0)
        t.join(timeout=10)
        assert done


class TestSupervision:
    def test_crashed_plugin_relaunched(self, tmp_path):
        m = PluginManager(PLUGDIR, socket_dir=str(tmp_path / "socks"))
        m.scan()
        if "hello" not in m.drivers:
            # cold interpreter starts on a loaded host can outlast even
            # the manager's internal retries; one more scan, and carry
            # the log ring into the assertion so a real failure explains
            # itself
            m.scan()
        from nomad_tpu.core.logging import RING
        assert "hello" in m.drivers, RING.tail(6)
        try:
            drv = m.drivers["hello"]
            assert drv.fingerprint()
            # kill the plugin process behind the shim
            drv.client.proc.kill()
            drv.client.proc.wait(timeout=5)
            time.sleep(0.2)
            assert drv.fingerprint() == {}      # dead connection
            m.start_supervisor(interval=0.5)
            # relaunch spawns a fresh interpreter; allow for a loaded host
            deadline = time.time() + 90
            while time.time() < deadline:
                if drv.fingerprint().get("driver.hello") == "1":
                    break
                time.sleep(0.3)
            # the SAME shim object works again after relaunch
            assert drv.fingerprint()["driver.hello"] == "1"
        finally:
            m.shutdown()


class TestExternalDevicePlugin:
    def test_fingerprint_groups(self, manager):
        groups = manager.fingerprint_devices()
        ids = {g.id() for g in groups}
        assert "acme/gpu/fake100" in ids

    def test_reserve(self, manager):
        plug = manager.devices["fake-gpu"]
        r = plug.reserve(["fake100-1"])
        assert r["envs"]["ACME_VISIBLE_DEVICES"] == "fake100-1"


class TestClientIntegration:
    def test_client_uses_plugin_driver_and_devices(self, tmp_path):
        """Full slice: client with plugin_dir schedules a job onto the
        external driver; node advertises the plugin's devices."""
        from nomad_tpu.core.server import Server
        from nomad_tpu.client.client import Client, InProcessRPC

        srv = Server(dev_mode=False, heartbeat_ttl=3600)
        srv.start()
        node = mock.node()
        cl = Client(InProcessRPC(srv), node=node,
                    data_dir=str(tmp_path / "c1"), plugin_dir=PLUGDIR)
        cl.start()
        try:
            nd = srv.state.node_by_id(node.id)
            assert nd.attributes.get("driver.hello") == "1"
            assert nd.drivers.get("hello") is True
            assert any(d.id() == "acme/gpu/fake100"
                       for d in nd.resources.devices)

            job = mock.job()
            job.id = "hello-job"
            job.task_groups[0].count = 1
            t = job.task_groups[0].tasks[0]
            t.driver = "hello"
            t.config = {"message": "external", "run_for_s": 60}
            from nomad_tpu.structs import RequestedDevice
            t.resources.devices = [RequestedDevice(name="gpu", count=1)]
            srv.register_job(job)
            deadline = time.time() + 20
            runner = None
            while time.time() < deadline:
                runners = list(cl.alloc_runners.values())
                if runners and runners[0].task_runners[0].state.state \
                        == "running":
                    runner = runners[0]
                    break
                time.sleep(0.2)
            assert runner is not None, "task never started on plugin driver"
            tr = runner.task_runners[0]
            assert tr.handle.driver == "hello"
            assert tr.handle.pid > 0
            # device plugin reserve() mapped the assigned instance into
            # the task env (plus the generic NOMAD_DEVICE_* exposure)
            alloc = runner.alloc
            assert alloc.allocated_devices
            iid = alloc.allocated_devices[0].device_ids[0]
            assert tr.env["ACME_VISIBLE_DEVICES"] == iid
            assert tr.env["NOMAD_DEVICE_ACME_GPU_FAKE100"] == iid
        finally:
            cl.shutdown()
            srv.shutdown()
