"""Memory & footprint observability plane (core/memledger.py): the
per-plane byte ledger, journal compaction equivalence, floor-fallback
accounting, idle-shape GC, and the rss_mb SLO rule (ISSUE 19)."""

import threading

import pytest

from nomad_tpu import mock
from nomad_tpu.chaos.clock import SystemClock, VirtualClock
from nomad_tpu.chaos.trace import state_fingerprint
from nomad_tpu.core import flightrec
from nomad_tpu.core.fanout import WatchHub, _Shape
from nomad_tpu.core.memledger import (
    MEMLEDGER,
    MemLedger,
    approx_sizeof,
    read_rss,
)
from nomad_tpu.core.telemetry import REGISTRY
from nomad_tpu.state.state_store import StateStore


# ---------------------------------------------------------------------------
# estimator + RSS reader
# ---------------------------------------------------------------------------


def test_approx_sizeof_counts_shared_objects_once():
    shared = "x" * 10_000
    doubled = approx_sizeof([shared, "y" * 10_000])
    deduped = approx_sizeof([shared, shared])
    # the second reference to the SAME object must be ~free
    assert deduped < doubled * 0.75
    assert approx_sizeof({}) > 0
    assert approx_sizeof(None) > 0


def test_approx_sizeof_extrapolates_from_samples():
    small = approx_sizeof(list(range(100)), sample=8)
    big = approx_sizeof(list(range(10_000)), sample=8)
    # sampling must still scale the estimate with container length
    assert big > small * 20


def test_read_rss_reports_process_residency():
    doc = read_rss()
    assert doc["rss_bytes"] > 0
    assert doc["rss_peak_bytes"] >= doc["rss_bytes"]


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def test_scrape_publishes_gauges_and_isolates_sizer_errors():
    ml = MemLedger(min_wall_s=0.0)
    ml.register("alpha", lambda: {"bytes": 1000, "entries": 3,
                                  "cap": 10, "evictions": 2,
                                  "gauges": {"nomad.test.extra": 7.0}})
    ml.register("broken", lambda: 1 / 0)
    doc = ml.scrape()
    assert doc["Schema"] == "nomad-tpu.memory.v1"
    assert doc["Planes"]["alpha"]["bytes"] == 1000
    # the gauges sub-dict is published verbatim, not kept in the doc
    assert "gauges" not in doc["Planes"]["alpha"]
    assert REGISTRY.gauge("nomad.test.extra") == 7.0
    assert REGISTRY.gauge("nomad.mem.plane_bytes", plane="alpha") == 1000
    assert REGISTRY.gauge("nomad.mem.rss_bytes") > 0
    # a raising sizer is an errored plane, never a failed scrape
    assert "error" in doc["Planes"]["broken"]
    assert doc["TrackedBytes"] == 1000
    assert ml.evictions() == {"alpha": 2, "broken": 0}
    assert ml.rss_mb() > 0


def test_sample_throttles_on_injected_clock():
    ml = MemLedger(interval_s=5.0, min_wall_s=0.0)
    ml.register("p", lambda: {"bytes": 1})
    assert ml.sample(100.0) is True
    assert ml.sample(101.0) is False      # inside interval_s
    assert ml.sample(104.9) is False
    assert ml.sample(105.0) is True
    assert ml.stats()["scrapes"] == 2


def test_sample_wall_guard_caps_scrape_rate():
    # a VirtualClock soak advances hundreds of virtual seconds per wall
    # second; the wall guard must keep that from becoming dozens of
    # scrapes (values are volatile wall facts — skipping loses nothing)
    ml = MemLedger(interval_s=5.0, min_wall_s=3600.0)
    ml.register("p", lambda: {"bytes": 1})
    assert ml.sample(0.0) is True
    assert ml.sample(1000.0) is False     # wall guard, not interval
    assert ml.stats()["scrapes"] == 1


def test_register_is_last_write_wins_and_unregister_drops():
    ml = MemLedger(min_wall_s=0.0)
    ml.register("p", lambda: {"bytes": 1})
    ml.register("p", lambda: {"bytes": 2})
    assert ml.scrape()["Planes"]["p"]["bytes"] == 2
    ml.unregister("p")
    assert ml.planes() == []


# ---------------------------------------------------------------------------
# journal compaction
# ---------------------------------------------------------------------------


def _churn(store, n_rounds, n_jobs, delete_every=0):
    """Duplicate-heavy write load: the same keys dirtied repeatedly,
    with optional interleaved deletes (tombstone coverage)."""
    jobs = []
    for i in range(n_jobs):
        j = mock.job()
        j.id = f"job-{i}"
        jobs.append(j)
    node = mock.node()
    store.upsert_node(node)
    for r in range(n_rounds):
        for i, j in enumerate(jobs):
            jj = j.copy() if hasattr(j, "copy") else j
            store.upsert_job(jj, preserve_version=True)
            ev = mock.eval(job_id=jj.id)
            ev.id = f"eval-{i}"          # same key every round
            store.upsert_evals([ev])
            if delete_every and r % delete_every == delete_every - 1:
                store.delete_job(jj.namespace, jj.id)
                store.upsert_job(jj, preserve_version=True)


def test_compaction_keeps_floor_at_zero_under_duplicate_churn():
    store = StateStore()
    store._journal_cap = 64
    _churn(store, n_rounds=60, n_jobs=8)
    st = store.journal_stats()
    # merge-by-key coalescing absorbs the duplicate-heavy overflow:
    # nothing evicted, the floor never moves, fallbacks impossible
    assert st["floor"] == 0
    assert st["evictions"] == 0
    assert st["compactions"] > 0
    assert st["bytes_reclaimed"] > 0
    assert st["entries"] <= 64
    assert st["bytes"] > 0
    assert st["gauges"]["nomad.journal.floor_fallbacks"] == 0


def test_compaction_equivalence_full_replay():
    """Newest-wins dedupe must preserve export semantics: a replica
    built purely from the compacted journal's delta (since=0, floor
    still 0) converges to the parent's exact state — including
    tombstoned jobs and re-upserts."""
    store = StateStore()
    store._journal_cap = 64
    _churn(store, n_rounds=40, n_jobs=6, delete_every=4)
    # also leave one job tombstoned for the delete path
    store.delete_job("default", "job-0")
    assert store.journal_stats()["floor"] == 0
    export = store.export_since(0)
    assert export["kind"] == "delta"
    replica = StateStore()
    replica.apply_export(export)
    assert replica.latest_index() == store.latest_index()
    assert (state_fingerprint(replica.snapshot())
            == state_fingerprint(store.snapshot()))
    snap = replica.snapshot()
    assert snap.job_by_id("default", "job-0") is None
    assert snap.job_by_id("default", "job-1") is not None


def test_compaction_equivalence_incremental_cursors():
    """A replica tailing the journal by cursor while compaction runs
    underneath stays bit-identical to the parent at every pull."""
    store = StateStore()
    store._journal_cap = 64
    replica = StateStore()
    for r in range(30):
        _churn(store, n_rounds=2, n_jobs=5,
               delete_every=3 if r % 2 else 0)
        export = store.export_since(replica.latest_index())
        assert export["kind"] in ("delta", "empty")   # never "full"
        replica.apply_export(export)
        assert (state_fingerprint(replica.snapshot())
                == state_fingerprint(store.snapshot()))
    assert store.journal_stats()["floor_fallbacks"] == 0
    assert store.journal_stats()["compactions"] > 0


def test_floor_fallback_counted_under_unique_key_churn():
    """Unique-key churn cannot be coalesced: the journal trims, the
    floor rises, and a cursor below the floor gets a counted full
    resync — the regression the perfcheck gate (== 0 in soaks) pins."""
    store = StateStore()
    store._journal_cap = 64
    for i in range(300):
        ev = mock.eval()
        ev.id = f"uniq-{i}"                  # every write a new key
        store.upsert_evals([ev])
    st = store.journal_stats()
    assert st["floor"] > 0
    assert st["evictions"] > 0
    export = store.export_since(1)           # cursor below the floor
    assert export["kind"] == "full"
    assert store.journal_stats()["floor_fallbacks"] == 1
    replica = StateStore()
    replica.apply_export(export)
    assert (state_fingerprint(replica.snapshot())
            == state_fingerprint(store.snapshot()))


def test_compact_journal_is_idempotent():
    store = StateStore()
    store._journal_cap = 64
    _churn(store, n_rounds=10, n_jobs=4)
    first = store.compact_journal()
    assert store.compact_journal() == 0      # nothing left to reclaim
    assert first >= 0


# ---------------------------------------------------------------------------
# WatchHub idle-shape GC
# ---------------------------------------------------------------------------


def test_watchhub_reap_idle_drops_only_stale_shapes():
    clock = SystemClock()
    hub = WatchHub(StateStore(), clock)
    base = REGISTRY.counter("nomad.fanout.shapes_reaped")
    with hub._lock:
        stale = hub._shapes["stale"] = _Shape(hub._lock)
        stale.touched = 100.0
        active = hub._shapes["active"] = _Shape(hub._lock)
        active.touched = 100.0
        active.waiters = 1                   # a parked client: immune
        fresh = hub._shapes["fresh"] = _Shape(hub._lock)
        fresh.touched = 395.0
    assert hub.reap_idle(now=400.0, idle_s=250.0) == 1
    st = hub.stats()
    assert st["shapes"] == 2
    assert st["shapes_reaped"] == 1
    assert REGISTRY.counter("nomad.fanout.shapes_reaped") == base + 1
    assert hub.reap_idle(now=400.0, idle_s=250.0) == 0   # idempotent
    assert hub.mem_stats()["entries"] == 2


# ---------------------------------------------------------------------------
# rss_mb SLO rule + dump bundles
# ---------------------------------------------------------------------------


def test_rss_mb_rule_disabled_by_default():
    assert flightrec.DEFAULT_SLO["rss_mb"] == -1.0
    w = flightrec.HealthWatchdog(clock=SystemClock())
    doc = w.check()
    row = [r for r in doc["Rules"] if r["Rule"] == "rss_mb"][0]
    assert row["Ok"] is True


def test_rss_mb_rule_breaches_and_dump_carries_memory():
    MEMLEDGER.scrape()
    w = flightrec.HealthWatchdog(slo={"rss_mb": 0.001},
                                 clock=SystemClock())
    doc = w.check()
    row = [r for r in doc["Rules"] if r["Rule"] == "rss_mb"][0]
    assert row["Ok"] is False
    assert row["Observed"] > 0.001
    dumps = w.dumps()
    assert dumps, "breach must snapshot a dump bundle"
    assert dumps[-1]["Memory"]["Schema"] == "nomad-tpu.memory.v1"
    assert dumps[-1]["Memory"]["RSSBytes"] > 0


def test_unknown_slo_key_still_rejected():
    with pytest.raises(ValueError):
        flightrec.HealthWatchdog(slo={"rss_megabytes": 1.0})


# ---------------------------------------------------------------------------
# Server integration: tick sampling + plane registration
# ---------------------------------------------------------------------------


def test_server_registers_planes_and_tick_scrapes():
    from nomad_tpu.core.server import Server
    clock = VirtualClock(epoch=1_700_000_000.0)
    s = Server(num_workers=0, clock=clock)
    try:
        expected = {"state", "journal", "watch_hub", "events",
                    "flight", "timeline", "tracer", "metrics",
                    "logring", "profiler"}
        assert expected <= set(MEMLEDGER.planes())
        s.state.upsert_node(mock.node())
        MEMLEDGER.min_wall_s = 0.0
        before = MEMLEDGER.stats()["scrapes"]
        s.tick()
        clock.advance(MEMLEDGER.interval_s + 1.0)
        s.tick()
        assert MEMLEDGER.stats()["scrapes"] > before
        doc = MEMLEDGER.doc()
        assert doc["Planes"]["state"]["bytes"] > 0
        assert doc["Planes"]["journal"]["entries"] > 0
    finally:
        MEMLEDGER.min_wall_s = 0.5
        s.shutdown()
        clock.close()


def test_operator_memory_surface():
    from nomad_tpu.agent import Agent
    from nomad_tpu.api.client import APIClient
    a = Agent(client_enabled=False, num_workers=0).start()
    try:
        c = APIClient(address=a.address)
        doc = c.operator.memory()
        assert doc["Schema"] == "nomad-tpu.memory.v1"
        assert doc["RSSBytes"] > 0
        assert {"state", "journal", "flight"} <= set(doc["Planes"])
        cached = c.operator.memory(cached=True)
        assert cached["Scrapes"] >= doc["Scrapes"]
        dbg = c.operator.debug()
        assert dbg["Memory"]["RSSBytes"] > 0
        assert "journal" in dbg["Evictions"]
    finally:
        a.shutdown()
