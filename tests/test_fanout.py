"""Read-path fanout plane (core/fanout.py): coalesced blocking-query
watches, the cursor-based event ring, and follower-served reads
(reference: blockingRPC + nomad/stream/event_buffer.go + stale reads)."""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.chaos.clock import SystemClock
from nomad_tpu.core.fanout import EventRing, WatchHub
from nomad_tpu.core.stream import EventBroker
from nomad_tpu.core.telemetry import REGISTRY
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Node, codec


def _wait(fn, timeout=30, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


def _wire_batch_job(count=1, run_for=300):
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].config = {"run_for_s": run_for}
    return codec.encode(job), job


# ---------------------------------------------------------------------------
# WatchHub
# ---------------------------------------------------------------------------


class TestWatchHub:
    def test_coalesced_wake_delivers_to_all_waiters_once(self):
        """K same-shape waiters, one write: every waiter wakes exactly
        once, and the shape's result index is evaluated once per commit
        batch — not once per waiter (the whole point of the hub)."""
        state = StateStore()
        hub = WatchHub(state, SystemClock())
        idx = state.latest_index()
        k = 8
        results = []
        lock = threading.Lock()

        def block():
            got = hub.block(("nodes",), state.latest_index, idx, wait=10)
            with lock:
                results.append(got)

        threads = [threading.Thread(target=block, daemon=True)
                   for _ in range(k)]
        for t in threads:
            t.start()
        _wait(lambda: hub.stats()["waiters"] == k, timeout=5)
        state.upsert_node(Node())
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        assert results == [True] * k
        st = hub.stats()
        assert st["wakes"] == k
        # one evaluation per commit batch, shared by all K waiters (a
        # couple of batches can race the thread starts; never one-per-K)
        assert st["evals"] <= 4
        assert st["coalesced"] > 0
        # shapes drain with their waiters (no leak of parked conditions)
        assert st["shapes"] == 0 and st["waiters"] == 0

    def test_unrelated_result_index_rides_timeout(self):
        """A store write that does NOT raise the watched result index
        (a deletion, or an unrelated table) must not wake the watcher —
        it rides the wait timeout (reference blockingRPC semantics)."""
        state = StateStore()
        hub = WatchHub(state, SystemClock())
        idx = 7
        done = []

        def block():
            # result index pinned at the caller's index: nothing the
            # store commits can raise it (the deletion-only shape)
            done.append(hub.block(("pinned",), lambda: idx, idx, wait=1.0))

        t = threading.Thread(target=block, daemon=True)
        t.start()
        _wait(lambda: hub.stats()["waiters"] == 1, timeout=5)
        state.upsert_node(Node())       # advances latest_index only
        t.join(timeout=10)
        assert not t.is_alive()
        assert done == [False]
        assert hub.stats()["timeouts"] == 1

    def test_immediate_return_when_already_past(self):
        state = StateStore()
        state.upsert_node(Node())
        hub = WatchHub(state, SystemClock())
        assert hub.block(("nodes",), state.latest_index, 0, wait=5) is True
        assert hub.stats()["evals"] == 1


# ---------------------------------------------------------------------------
# EventRing + cursor subscriptions
# ---------------------------------------------------------------------------


class TestEventRing:
    def test_cursor_replay_from_index(self):
        """A late subscriber seeks by index and replays ring history."""
        broker = EventBroker()
        store = StateStore()
        broker.attach(store)
        n1 = store.upsert_node(Node())
        store.upsert_node(Node())
        sub = broker.subscribe({"Node": ["*"]}, from_index=0)
        got = [sub.next(timeout=1), sub.next(timeout=1)]
        assert all(e is not None for e in got)
        assert [e.index for e in got] == sorted(e.index for e in got)
        assert got[0].index == n1
        # replay from the middle skips the first commit
        sub2 = broker.subscribe({"Node": ["*"]}, from_index=n1)
        ev = sub2.next(timeout=1)
        assert ev is not None and ev.index > n1
        broker.close()

    def test_slow_cursor_drop_accounting(self):
        """A cursor that falls off a small ring counts every lost event
        into its ledger and nomad.stream.dropped — and never blocks the
        publisher (the appends below happen with the sub parked)."""
        before = REGISTRY.counter("nomad.stream.dropped")
        broker = EventBroker(buffer_size=4)
        store = StateStore()
        broker.attach(store)
        sub = broker.subscribe({"Node": ["*"]}, from_index=0)
        n = 12
        for _ in range(n):
            store.upsert_node(Node())
        # ring holds 4 entries; the cursor at seq 0 lost the rest
        evs = []
        while True:
            ev = sub.next(timeout=0.2)
            if ev is None:
                break
            evs.append(ev)
        assert len(evs) == 4
        assert sub.dropped == n - 4
        assert broker.stats()["DroppedTotal"] == n - 4
        assert REGISTRY.counter("nomad.stream.dropped") - before == n - 4
        assert sub.stats()["Dropped"] == n - 4
        broker.close()

    def test_trim_accounts_unexpanded_entries(self):
        """Drop accounting is exact even for entries trimmed before any
        reader expanded them (the O(1) append-time count ledger)."""
        ring = EventRing(capacity=2)
        for i in range(6):
            ring.append("Node", i + 1, object(), count=3)
        st = ring.stats()
        assert st["entries"] == 2
        # 4 trimmed entries x 3 events each sit below the cum base
        probe = ring.fetch(0)
        assert probe[0] == "behind"
        assert probe[2] == 12        # cum_base

    def test_close_wakes_parked_consumer(self):
        broker = EventBroker()
        out = []

        sub = broker.subscribe({"Node": ["*"]})

        def consume():
            out.append(sub.next(timeout=30))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.2)
        broker.close()
        t.join(timeout=5)
        assert not t.is_alive()
        assert out == [None]


# ---------------------------------------------------------------------------
# HTTP plane: hub-backed blocking + columnar lists
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent():
    ag = Agent(num_clients=2, num_workers=1, heartbeat_ttl=3600)
    ag.start()
    yield ag
    ag.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(address=agent.address)


class TestHTTPFanout:
    def test_http_waiters_coalesce_on_one_shape(self, api, agent):
        hub = agent.server.watch_hub
        before = hub.stats()
        idx = agent.server.state.latest_index()
        k = 6
        results = []
        lock = threading.Lock()

        def blocked():
            out = api.request("GET", "/v1/jobs",
                              params={"index": idx, "wait": 10})
            with lock:
                results.append(out)

        threads = [threading.Thread(target=blocked, daemon=True)
                   for _ in range(k)]
        for t in threads:
            t.start()
        _wait(lambda: hub.stats()["waiters"] - before["waiters"] >= k,
              timeout=5)
        wire, job = _wire_batch_job()
        api.jobs.register(wire)
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        assert len(results) == k
        assert all(any(s["ID"] == job.id for s in out) for out in results)
        after = hub.stats()
        assert after["wakes"] - before["wakes"] >= k
        # K HTTP clients shared O(1) evaluations, not one each
        assert after["evals"] - before["evals"] < k
        api.jobs.deregister(job.id, purge=True)

    def test_deletion_only_change_rides_timeout(self, api, agent):
        wire, job = _wire_batch_job()
        api.jobs.register(wire)
        jobs = api.request("GET", "/v1/jobs")
        result_idx = max(s["ModifyIndex"] for s in jobs)
        api.jobs.deregister(job.id, purge=True)
        _wait(lambda: all(s["ID"] != job.id
                          for s in api.request("GET", "/v1/jobs")))
        # the purge advanced the STORE index but lowered the jobs result
        # index — a blocked watcher must ride the timeout, not wake
        t0 = time.perf_counter()
        api.request("GET", "/v1/jobs",
                    params={"index": result_idx, "wait": 1})
        assert time.perf_counter() - t0 >= 0.9

    def test_columnar_allocations_list(self, api, agent):
        wire, job = _wire_batch_job(count=4)
        api.jobs.register(wire)
        rows = _wait(lambda: [a for a in api.request(
            "GET", "/v1/allocations") if a["JobID"] == job.id])
        assert len(rows) >= 4
        out = api.request("GET", "/v1/allocations",
                          params={"columnar": "true"})
        assert out["Columnar"] is True
        cols = out["Columns"]
        assert out["Count"] == len(cols["ID"]) == len(cols["Name"])
        assert set(cols) == {"ID", "Name", "JobID", "NodeID",
                             "ClientStatus", "ModifyIndex"}
        flat = api.request("GET", "/v1/allocations")
        assert sorted(cols["ID"]) == sorted(a["ID"] for a in flat)
        by_id = {a["ID"]: a for a in flat}
        for i, aid in enumerate(cols["ID"]):
            assert cols["Name"][i] == by_id[aid]["Name"]
            assert cols["JobID"][i] == by_id[aid]["JobID"]

    def test_debug_bundle_has_fanout_sections(self, api):
        dbg = api.request("GET", "/v1/operator/debug")
        assert "WatchHub" in dbg and "EventBroker" in dbg
        assert "Follower" in dbg
        assert dbg["EventBroker"]["Ring"]["next_seq"] >= 0


# ---------------------------------------------------------------------------
# ReadFollower: replicated reads, headers, proxying, failover
# ---------------------------------------------------------------------------


class TestReadFollower:
    def test_follower_serves_reads_headers_and_proxies_writes(self):
        leader = Agent(num_clients=1, num_workers=1,
                       heartbeat_ttl=3600).start()
        fol = Agent(num_clients=0, num_workers=1, heartbeat_ttl=3600,
                    follow=leader.address).start()
        try:
            api = APIClient(address=leader.address)
            fapi = APIClient(address=fol.address)
            wire, job = _wire_batch_job()
            api.jobs.register(wire)
            # replicated read served locally by the follower
            assert _wait(lambda: any(
                s["ID"] == job.id for s in fapi.jobs.list()), timeout=15)
            # consistency headers on follower responses
            import urllib.request
            with urllib.request.urlopen(fol.address + "/v1/jobs",
                                        timeout=5) as r:
                assert r.headers["X-Nomad-KnownLeader"] == "true"
                assert int(r.headers["X-Nomad-LastContact"]) >= 0
            # a write through the follower proxies to the upstream
            wire2, job2 = _wire_batch_job()
            resp = fapi.jobs.register(wire2)
            assert resp["EvalID"]
            assert _wait(lambda: any(
                s["ID"] == job2.id for s in api.jobs.list()))
            # ?stale=false forces the leader round-trip too
            out = fapi.request("GET", "/v1/jobs",
                               params={"stale": "false"})
            assert any(s["ID"] == job2.id for s in out)
            st = fol.follower.stats()
            assert st["known_leader"] and st["failures"] == 0
        finally:
            fol.shutdown()
            leader.shutdown()

    def test_follow_excludes_cluster_mode(self):
        with pytest.raises(ValueError):
            Agent(follow="http://127.0.0.1:1", bootstrap_expect=3)

    def test_no_stale_reads_across_failover(self):
        """Chaos scenario: the follower's upstream dies and the next
        candidate is BEHIND the index the follower already served.  The
        follower must skip the lagging upstream (reads never regress)
        and only resume applying once the candidate catches up past its
        head — monotonic stale-bounded reads across failover."""
        a = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600).start()
        b = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600).start()
        fol = Agent(num_clients=0, num_workers=1, heartbeat_ttl=3600,
                    follow=f"{a.address},{b.address}").start()
        observed = []
        stop = threading.Event()

        def watch_index():
            while not stop.is_set():
                observed.append(fol.server.state.latest_index())
                time.sleep(0.02)

        t = threading.Thread(target=watch_index, daemon=True)
        t.start()
        try:
            api_a = APIClient(address=a.address)
            for _ in range(3):
                wire, _ = _wire_batch_job()
                api_a.jobs.register(wire)
            head = a.server.state.latest_index()
            assert _wait(
                lambda: fol.server.state.latest_index() >= head, timeout=15)
            # kill the leader; candidate B is far behind the follower
            a.shutdown()
            assert b.server.state.latest_index() < head
            assert _wait(lambda: fol.follower.skipped_regressions > 0,
                         timeout=15), "lagging upstream was not skipped"
            assert fol.server.state.latest_index() >= head
            # B catches up past the follower's head -> tail resumes
            api_b = APIClient(address=b.address)
            while b.server.state.latest_index() <= head:
                wire, _ = _wire_batch_job()
                api_b.jobs.register(wire)
            new_head = b.server.state.latest_index()
            assert _wait(
                lambda: fol.server.state.latest_index() >= new_head,
                timeout=15), "follower never resumed from the new leader"
            # flag is set just after the apply inside the same pull —
            # poll rather than racing that window
            assert _wait(lambda: fol.follower.stats()["known_leader"],
                         timeout=10)
        finally:
            stop.set()
            t.join(timeout=5)
            fol.shutdown()
            b.shutdown()
        # the local index NEVER regressed at any sampled instant
        assert observed == sorted(observed), \
            "follower served a regressed index during failover"
