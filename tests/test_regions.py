"""Multi-region federation (reference: nomad/regions.go, WAN serf,
rpcHandler.forward region hop, the `multiregion` jobspec stanza)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient, APIException
from nomad_tpu.structs import Multiregion, codec


@pytest.fixture()
def federated():
    east = Agent(client_enabled=False, num_workers=1, region="east").start()
    west = Agent(client_enabled=False, num_workers=1, region="west",
                 join_wan=[east.address]).start()
    for a in (east, west):
        a.server.establish_leadership()
        for _ in range(3):
            a.server.register_node(mock.node())
    try:
        yield east, west
    finally:
        east.shutdown()
        west.shutdown()


class TestFederation:
    def test_push_pull_join_teaches_both_sides(self, federated):
        east, west = federated
        assert west.federation.regions() == ["east", "west"]
        # the join POSTed west's table into east as well
        assert east.federation.regions() == ["east", "west"]

    def test_regions_endpoint(self, federated):
        east, west = federated
        api = APIClient(address=west.address)
        assert api.get("/v1/regions") == ["east", "west"]

    def test_cross_region_forwarding(self, federated):
        east, west = federated
        # submit against WEST with ?region=east: lands in east's state
        api = APIClient(address=west.address, region="east")
        job = mock.job()
        out = api.jobs.register(codec.encode(job))
        assert out["EvalID"]
        deadline = time.time() + 15
        while time.time() < deadline:
            live = [a for a in east.server.state.snapshot()
                    .allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            if live:
                break
            time.sleep(0.1)
        assert live, "job never placed in east"
        assert west.server.state.snapshot().job_by_id(
            job.namespace, job.id) is None
        # reads forward too
        stub = api.get(f"/v1/job/{job.id}")
        assert stub["ID"] == job.id
        # node region stamped by the owning server
        node = east.server.state.snapshot().nodes()[0]
        assert node.region == "east"

    def test_unknown_region_404(self, federated):
        _, west = federated
        api = APIClient(address=west.address, region="mars")
        with pytest.raises(APIException) as e:
            api.get("/v1/jobs")
        assert e.value.status == 404

    def test_multiregion_job_fans_out(self, federated):
        east, west = federated
        api = APIClient(address=west.address)
        job = mock.batch_job()
        job.task_groups[0].count = 5
        job.multiregion = Multiregion(regions=[
            {"Name": "west", "Count": 2},
            {"Name": "east", "Count": 3},
        ])
        out = api.jobs.register(codec.encode(job))
        assert set(out["Regions"]) == {"east", "west"}
        assert all("Error" not in r for r in out["Regions"].values()), out
        deadline = time.time() + 15
        counts = {}
        while time.time() < deadline:
            counts = {
                name: len([a for a in ag.server.state.snapshot()
                           .allocs_by_job(job.namespace, job.id)
                           if not a.terminal_status()])
                for name, ag in (("east", east), ("west", west))}
            if counts == {"east": 3, "west": 2}:
                break
            time.sleep(0.1)
        assert counts == {"east": 3, "west": 2}, counts
        # each region's stored copy carries its own region + count
        for name, ag in (("east", east), ("west", west)):
            stored = ag.server.state.snapshot().job_by_id(
                job.namespace, job.id)
            assert stored.region == name
            assert stored.multiregion is None
