"""Client layer tests (reference: client/*_test.go patterns — in-process
client + server, mock driver lifecycles, no containers)."""

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, InProcessRPC, new_driver_registry
from nomad_tpu.client.drivers import MockDriver, RawExecDriver
from nomad_tpu.client.restarts import KILL, RESTART, RestartTracker
from nomad_tpu.client.state import StateDB
from nomad_tpu.client.task_runner import TaskRunner
from nomad_tpu.client.taskenv import build_task_env, interpolate
from nomad_tpu.core import Server
from nomad_tpu.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_RUNNING,
    RestartPolicy,
    Task,
    TASK_STATE_DEAD,
)


def make_alloc(job, node, tg_name=None):
    tg = job.task_groups[0]
    a = mock.alloc(job=job, node_id=node.id,
                   task_group=tg_name or tg.name)
    a.job = job
    return a


# ---------------------------------------------------------------- drivers

def test_mock_driver_lifecycle():
    d = MockDriver()
    task = Task(name="t", driver="mock", config={"run_for_s": 0.05})
    h = d.start_task("t1", task, {}, "")
    res = d.wait_task(h, timeout=2)
    assert res is not None and res.successful()


def test_mock_driver_failure_and_kill():
    d = MockDriver()
    task = Task(name="t", driver="mock",
                config={"run_for_s": 0.05, "exit_code": 3})
    h = d.start_task("t1", task, {}, "")
    res = d.wait_task(h, timeout=2)
    assert res.exit_code == 3
    task2 = Task(name="t2", driver="mock", config={"run_for_s": 30})
    h2 = d.start_task("t2", task2, {}, "")
    d.stop_task(h2)
    res2 = d.wait_task(h2, timeout=2)
    assert res2.exit_code == 137


def test_raw_exec_driver(tmp_path):
    d = RawExecDriver()
    task = Task(name="echo", driver="raw_exec",
                config={"command": "sh", "args": ["-c", "echo hi; exit 0"]})
    h = d.start_task("t1", task, {}, str(tmp_path))
    res = d.wait_task(h, timeout=5)
    assert res.successful()
    out = (tmp_path / "echo.stdout").read_bytes()
    assert b"hi" in out


def test_raw_exec_nonzero_exit(tmp_path):
    d = RawExecDriver()
    task = Task(name="f", driver="raw_exec",
                config={"command": "sh", "args": ["-c", "exit 7"]})
    h = d.start_task("t1", task, {}, str(tmp_path))
    res = d.wait_task(h, timeout=5)
    assert res.exit_code == 7 and not res.successful()


def test_unavailable_drivers_fingerprint_unhealthy():
    """docker/java/qemu register but fingerprint unhealthy when their
    binary/daemon is absent, so placement skips such nodes."""
    from nomad_tpu.client.fingerprint import FingerprintManager
    from nomad_tpu.structs import Node
    reg = new_driver_registry()
    assert {"docker", "java", "qemu"} <= set(reg)
    node = Node()
    FingerprintManager(reg).run(node)
    for name in ("docker", "java", "qemu"):
        drv = reg[name]
        assert node.drivers[name] == drv.available()
        if not drv.available():
            assert f"driver.{name}" not in node.attributes
    # the always-available drivers stay healthy
    assert node.drivers["raw_exec"] and node.drivers["mock"]


@pytest.mark.skipif(
    __import__("shutil").which("docker") is None,
    reason="docker not installed")
def test_docker_driver_lifecycle(tmp_path):
    from nomad_tpu.client.drivers import DockerDriver
    d = DockerDriver()
    if not d.available():
        pytest.skip("docker daemon unreachable")
    task = Task(name="t", driver="docker",
                config={"image": "busybox",
                        "command": "sh", "args": ["-c", "exit 4"]})
    h = d.start_task("t1", task, {}, str(tmp_path))
    try:
        res = d.wait_task(h, timeout=60)
        assert res is not None and res.exit_code == 4
    finally:
        d.destroy_task(h)


@pytest.mark.skipif(
    __import__("shutil").which("java") is None,
    reason="java not installed")
def test_java_driver_starts_jvm(tmp_path):
    from nomad_tpu.client.drivers import JavaDriver
    d = JavaDriver()
    task = Task(name="t", driver="java", config={"class": "NoSuchMain"})
    h = d.start_task("t1", task, {}, str(tmp_path))
    res = d.wait_task(h, timeout=30)
    assert res is not None and res.exit_code != 0   # JVM ran, class missing


# ---------------------------------------------------------------- restarts

def test_restart_tracker_batch_success_no_restart():
    rt = RestartTracker(RestartPolicy(attempts=3), is_batch=True)
    decision, _ = rt.next(0, False, now=100.0)
    assert decision == KILL


def test_restart_tracker_fail_mode_exhaustion():
    rt = RestartTracker(RestartPolicy(attempts=2, interval_s=300,
                                      delay_s=0.01, mode="fail"))
    assert rt.next(1, True, now=10.0)[0] == RESTART
    assert rt.next(1, True, now=11.0)[0] == RESTART
    assert rt.next(1, True, now=12.0)[0] == KILL


def test_restart_tracker_interval_reset():
    rt = RestartTracker(RestartPolicy(attempts=1, interval_s=10,
                                      delay_s=0.01, mode="fail"))
    assert rt.next(1, True, now=0.0)[0] == RESTART
    # new interval after 10s: counter resets
    assert rt.next(1, True, now=20.0)[0] == RESTART


# ---------------------------------------------------------------- task env

def test_task_env_and_interpolation():
    job = mock.job()
    node = mock.node()
    alloc = make_alloc(job, node)
    task = job.task_groups[0].tasks[0]
    task.env = {"DC": "${node.datacenter}", "K": "${attr.kernel.name}"}
    env = build_task_env(alloc, task, node)
    assert env["NOMAD_ALLOC_ID"] == alloc.id
    assert env["DC"] == "dc1"
    assert env["K"] == "linux"
    assert interpolate("${meta.missing}", {}, node) == ""


# -------------------------------------------------------------- task runner

def test_task_runner_batch_completes():
    job = mock.batch_job()
    job.task_groups[0].tasks[0].config = {"run_for_s": 0.05}
    node = mock.node()
    alloc = make_alloc(job, node)
    tr = TaskRunner(alloc, job.task_groups[0].tasks[0], MockDriver(), node,
                    is_batch=True)
    tr.run()
    assert tr.state.state == TASK_STATE_DEAD
    assert not tr.state.failed
    types = [e.type for e in tr.state.events]
    assert "Started" in types and "Terminated" in types


def test_task_runner_restarts_then_fails():
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.restart_policy = RestartPolicy(attempts=1, interval_s=300,
                                      delay_s=0.01, mode="fail")
    tg.tasks[0].config = {"run_for_s": 0.02, "exit_code": 1}
    node = mock.node()
    alloc = make_alloc(job, node)
    tr = TaskRunner(alloc, tg.tasks[0], MockDriver(), node, is_batch=True)
    tr.run()
    assert tr.state.state == TASK_STATE_DEAD
    assert tr.state.failed
    assert tr.state.restarts == 1


# ------------------------------------------------------------ client state

def test_state_db_roundtrip(tmp_path):
    db = StateDB(str(tmp_path))
    job = mock.batch_job()
    node = mock.node()
    alloc = make_alloc(job, node)
    db.put_allocation(alloc)
    from nomad_tpu.client.drivers.base import TaskHandle
    db.put_task_handle(alloc.id, "worker",
                       TaskHandle(task_id="x", driver="mock", pid=42))
    db.close()
    db2 = StateDB(str(tmp_path))
    assert db2.get_allocations()[0]["id"] == alloc.id
    assert db2.get_task_handles(alloc.id)["worker"].pid == 42
    db2.close()


# ------------------------------------------------- end-to-end with server

@pytest.fixture
def dev_cluster():
    server = Server(dev_mode=True)
    server.establish_leadership()
    client = Client(InProcessRPC(server), heartbeat_interval=0.2,
                    sync_interval=0.05)
    yield server, client
    client.shutdown()


def test_client_runs_batch_job_to_completion(dev_cluster):
    server, client = dev_cluster
    client.rpc.register_node(client.node)

    job = mock.batch_job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].driver = "mock"
    job.task_groups[0].tasks[0].config = {"run_for_s": 0.05}
    server.register_job(job)
    assert server.process_all() >= 1

    allocs, idx = server.get_client_allocs(client.node.id, 0, timeout=1.0)
    assert len(allocs) == 2
    client.run_allocs(allocs)
    assert client.wait_until_idle(timeout=5)
    client.sync_once()

    stored = server.state.allocs_by_job(job.namespace, job.id)
    assert all(a.client_status == ALLOC_CLIENT_COMPLETE for a in stored)
    assert all(a.task_states["worker"].state == TASK_STATE_DEAD
               for a in stored)


def test_failed_alloc_triggers_reschedule_eval(dev_cluster):
    server, client = dev_cluster
    client.rpc.register_node(client.node)

    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.restart_policy = RestartPolicy(attempts=0, mode="fail")
    tg.tasks[0].config = {"run_for_s": 0.02, "exit_code": 1}
    server.register_job(job)
    server.process_all()

    allocs, _ = server.get_client_allocs(client.node.id, 0, timeout=1.0)
    assert len(allocs) == 1
    client.run_allocs(allocs)
    assert client.wait_until_idle(timeout=5)
    client.sync_once()

    stored = server.state.alloc_by_id(allocs[0].id)
    assert stored.client_status == ALLOC_CLIENT_FAILED
    evs = [e for e in server.state.snapshot().evals()
           if e.triggered_by == "alloc-failure"]
    assert evs, "terminal failed alloc must create an eval"


def test_client_restart_adopts_live_tasks(tmp_path):
    """reference: client restore — a restarted agent re-adopts live tasks
    from its state db instead of killing/restarting them."""
    import subprocess

    server = Server(dev_mode=True)
    server.establish_leadership()
    data_dir = str(tmp_path)
    node = mock.node()
    client = Client(InProcessRPC(server), node=node, data_dir=data_dir)
    client.rpc.register_node(client.node)

    job = mock.job()
    job.task_groups[0].count = 1
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "sleep", "args": ["120"]}
    server.register_job(job)
    assert server.process_all() >= 1

    allocs, _ = server.get_client_allocs(client.node.id, 0, timeout=1.0)
    client.run_allocs(allocs)
    deadline = time.time() + 10
    pid = 0
    while time.time() < deadline and not pid:
        ar = client.alloc_runners.get(allocs[0].id)
        if ar and ar.task_runners and ar.task_runners[0].handle:
            pid = ar.task_runners[0].handle.pid
        time.sleep(0.1)
    assert pid, "task never started"
    # simulate agent death: abandon runners WITHOUT killing tasks (their
    # threads must exit too, or the old client restarts the task later)
    for ar in client.alloc_runners.values():
        ar.abandon()
    client.state_db.close()
    client.alloc_runners.clear()

    # a fresh client over the same data dir re-adopts the live pid
    client2 = Client(InProcessRPC(server), node=node, data_dir=data_dir)
    allocs2, _ = server.get_client_allocs(node.id, 0, timeout=1.0)
    client2.run_allocs(allocs2)
    deadline = time.time() + 10
    adopted = None
    while time.time() < deadline:
        ar = client2.alloc_runners.get(allocs[0].id)
        if ar and ar.task_runners and ar.task_runners[0].handle:
            adopted = ar.task_runners[0].handle
            if ar.task_runners[0].state.state == "running":
                break
        time.sleep(0.1)
    assert adopted is not None
    assert adopted.pid == pid, "adopted a different process"
    # the original process is still alive (never restarted)
    os.kill(pid, 0)
    # cleanup
    for ar in list(client2.alloc_runners.values()):
        ar.destroy()
    client2.wait_until_idle(timeout=10)
    time.sleep(0.3)
    # in this test both "agents" share our process, so the killed task
    # lingers as an unreaped zombie child: dead means state Z/X/gone
    from nomad_tpu.client.drivers.rawexec import _proc_stat
    state, _ = _proc_stat(pid)
    assert state in (None, "Z", "X"), f"task still running: {state}"
    client2.state_db.close()


def test_client_threaded_end_to_end():
    server = Server(dev_mode=False, num_workers=1)
    server.start(tick_interval=0.1)
    client = Client(InProcessRPC(server), heartbeat_interval=0.2,
                    sync_interval=0.05)
    try:
        client.start()
        job = mock.batch_job()
        job.task_groups[0].tasks[0].config = {"run_for_s": 0.05}
        server.register_job(job)
        deadline = time.time() + 15
        while time.time() < deadline:
            stored = server.state.allocs_by_job(job.namespace, job.id)
            if stored and all(a.client_status == ALLOC_CLIENT_COMPLETE
                              for a in stored):
                break
            time.sleep(0.1)
        stored = server.state.allocs_by_job(job.namespace, job.id)
        assert stored
        assert all(a.client_status == ALLOC_CLIENT_COMPLETE
                   for a in stored)
    finally:
        client.shutdown()
        server.shutdown()


# --------------------------------------------- failure-path regressions

def test_missing_driver_fails_alloc():
    """An alloc whose task driver is absent must fail, not hang pending."""
    from nomad_tpu.client.alloc_runner import AllocRunner
    job = mock.batch_job()
    job.task_groups[0].tasks[0].driver = "docker"   # not in registry
    node = mock.node()
    alloc = make_alloc(job, node)
    updates = []
    ar = AllocRunner(alloc, {}, node, on_update=updates.append)
    ar.run()
    assert ar.wait(1.0)
    assert alloc.client_status == ALLOC_CLIENT_FAILED
    ts = alloc.task_states[job.task_groups[0].tasks[0].name]
    assert ts.state == TASK_STATE_DEAD and ts.failed
    assert "driver" in ts.events[0].message
    assert updates, "terminal status must be shipped to the client"


def test_driver_leaking_exception_fails_task():
    """Non-DriverError exceptions from start_task must still land the
    task in a terminal failed state."""
    class ExplodingDriver(MockDriver):
        def start_task(self, *a, **kw):
            raise ValueError("bad config")

    job = mock.batch_job()
    node = mock.node()
    alloc = make_alloc(job, node)
    tr = TaskRunner(alloc, job.task_groups[0].tasks[0], ExplodingDriver(),
                    node, is_batch=True)
    tr.run()
    assert tr.state.state == TASK_STATE_DEAD
    assert tr.state.failed
    assert any("bad config" in (e.message or "") for e in tr.state.events)


def test_restart_drops_running_state():
    """Between exit and restart the task leaves `running` so health
    watchers can see crash loops."""
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.restart_policy = RestartPolicy(attempts=2, interval_s=300,
                                      delay_s=0.05, mode="fail")
    tg.tasks[0].config = {"run_for_s": 0.02, "exit_code": 1}
    node = mock.node()
    alloc = make_alloc(job, node)
    seen = set()
    tr = TaskRunner(alloc, tg.tasks[0], MockDriver(), node, is_batch=True,
                    on_state_change=lambda r: seen.add(r.state.state))
    tr.run()
    assert "pending" in seen     # dropped out of running during restart
    assert tr.state.state == TASK_STATE_DEAD


def test_removed_alloc_not_resurrected_in_state_db():
    """A server-dropped alloc must not be re-put into the state DB by a
    late task-thread update."""
    server = Server(num_workers=0)
    server.start()
    try:
        client = Client(InProcessRPC(server), node=mock.node(),
                        sync_interval=0.05)
        job = mock.batch_job()
        job.task_groups[0].tasks[0].config = {"run_for_s": 10}
        alloc = make_alloc(job, client.node)
        client.run_allocs([alloc])
        deadline = time.time() + 2
        while time.time() < deadline and \
                client.alloc_runners[alloc.id].alloc.client_status \
                != ALLOC_CLIENT_RUNNING:
            time.sleep(0.01)
        # server drops the alloc from the node's set
        client.run_allocs([])
        assert alloc.id not in client.alloc_runners
        # let the killed task threads fire their late updates
        time.sleep(0.3)
        ids = [a["id"] for a in client.state_db.get_allocations()]
        assert alloc.id not in ids
        client.shutdown()
        assert client.state_db.get_allocations() == []   # closed: empty
    finally:
        server.shutdown()
