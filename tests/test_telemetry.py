"""End-to-end eval-lifecycle tracing + metrics registry (ISSUE 4):
registry/histogram units, prometheus exposition grammar, the
broker→applier trace join, virtual-clock timing determinism, the
streaming endpoints (`/v1/agent/monitor`, `/v1/event/stream`
disconnect cleanup), and LogRing drop accounting."""

import json
import re
import threading
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.chaos.clock import VirtualClock
from nomad_tpu.core.logging import LogRing, RING, log
from nomad_tpu.core.server import Server
from nomad_tpu.core.telemetry import (
    Histogram,
    MetricsRegistry,
    REGISTRY,
    StatCounters,
    TRACER,
    span_id,
)
from nomad_tpu.structs import codec, new_id


def _wait(fn, timeout=60, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


# ------------------------------------------------------------- registry


class TestRegistry:
    def test_counters_gauges_labels(self):
        reg = MetricsRegistry()
        reg.inc("t.hits")
        reg.inc("t.hits", 4)
        reg.inc("t.hits", 2, code="500")
        reg.set_gauge("t.depth", 7)
        assert reg.counter("t.hits") == 5
        assert reg.counter("t.hits", code="500") == 2
        assert reg.gauge("t.depth") == 7
        snap = reg.snapshot()
        assert snap["counters"]["t.hits"] == 5
        assert snap["counters"]['t.hits{code=500}'] == 2
        # snapshot must be JSON-safe
        json.dumps(snap)

    def test_histogram_percentiles(self):
        h = Histogram(buckets=(0.001, 0.01, 0.1, 1.0))
        for _ in range(90):
            h.observe(0.005)           # lands in the (0.001, 0.01] bucket
        for _ in range(10):
            h.observe(0.5)             # lands in the (0.1, 1.0] bucket
        assert h.count == 100
        assert h.sum == pytest.approx(90 * 0.005 + 10 * 0.5)
        s = h.summary()
        # p50 interpolates inside the 0.001..0.01 bucket; p99 inside
        # 0.1..1.0; estimates must be ordered and bucket-bounded
        assert 0.001 < s["p50"] <= 0.01
        assert 0.1 < s["p99"] <= 1.0
        assert s["p50"] <= s["p95"] <= s["p99"]

    def test_histogram_timed_block_reads_injected_clock(self):
        clock = VirtualClock()
        reg = MetricsRegistry(clock=clock)
        with reg.time("t.block_s"):
            clock.advance(2.5)
        s = reg.histogram("t.block_s")
        assert s["count"] == 1
        assert s["sum"] == pytest.approx(2.5)

    def test_stat_counters_concurrent_increments_lose_nothing(self):
        # the satellite's point: bare-dict `stats["x"] += 1` from many
        # threads loses updates; StatCounters must not
        name = f"t.atomic.{new_id()[:8]}"
        sc = StatCounters(name, ("n",))
        threads = [threading.Thread(
            target=lambda: [sc.inc("n") for _ in range(1000)])
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sc["n"] == 8000
        assert REGISTRY.counter(f"{name}.n") == 8000
        # dict-protocol compatibility with the old stats blocks
        assert dict(sc) == {"n": 8000}
        sc["n"] = 0
        assert sc["n"] == 0


# ----------------------------------------------------------- exposition

_TYPE_RE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$")
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r' -?[0-9]+(\.[0-9]+)?([eE][-+][0-9]+)?$')


def assert_valid_exposition(text):
    """Every line is a `# TYPE` comment or a sample; histogram bucket
    series are cumulative with le=+Inf equal to _count."""
    assert text.endswith("\n")
    families = {}
    samples = []
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert _TYPE_RE.match(line), f"bad TYPE line: {line!r}"
            _, _, fam, kind = line.split()
            families[fam] = kind
            continue
        assert _SAMPLE_RE.match(line.replace('le="+Inf"', 'le="Inf"')), \
            f"bad sample line: {line!r}"
        samples.append(line)
    assert families and samples
    # cumulative bucket check per histogram family
    for fam, kind in families.items():
        if kind != "histogram":
            continue
        buckets = [ln for ln in samples
                   if ln.startswith(f"{fam}_bucket")]
        assert buckets, f"histogram {fam} has no buckets"
        by_labels = {}
        for ln in buckets:
            labels = re.sub(r',?le="[^"]*"', "", ln.split(" ")[0])
            by_labels.setdefault(labels, []).append(
                float(ln.rsplit(" ", 1)[1]))
        for series in by_labels.values():
            assert series == sorted(series), "buckets not cumulative"
        count_lines = [ln for ln in samples
                       if ln.startswith(f"{fam}_count")]
        assert count_lines, f"histogram {fam} lacks _count"
        assert any(ln.startswith(f"{fam}_sum") for ln in samples)
    return families


class TestPrometheusExposition:
    def test_grammar_and_cumulative_buckets(self):
        reg = MetricsRegistry()
        reg.inc("t.requests", 3)
        reg.inc("t.requests", 1, code="500")
        reg.set_gauge("t.depth", 2)
        for v in (0.002, 0.02, 0.2, 2.0):
            reg.observe("t.latency_s", v)
        families = assert_valid_exposition(reg.prometheus())
        assert families["t_requests"] == "counter"
        assert families["t_depth"] == "gauge"
        assert families["t_latency_seconds"] == "histogram"
        # the _s suffix renders as _seconds, with quantile gauges
        for q in ("p50", "p95", "p99"):
            assert families[f"t_latency_seconds_{q}"] == "gauge"


# ------------------------------------------------------------ trace join


class TestTraceJoin:
    def test_broker_to_applier_trace_join(self):
        TRACER.reset()
        s = Server(num_workers=1)
        s.establish_leadership()
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        ev = s.register_job(job)
        assert ev.trace_id == ev.id     # stamped at the FSM boundary
        s.process_all()
        spans = TRACER.trace(ev.trace_id)
        names = {sp["Name"] for sp in spans}
        assert {"eval", "broker.wait", "worker.schedule",
                "plan.queue_wait", "plan.apply"} <= names, names
        # consistent parent/child links: every parent id resolves
        ids = {sp["SpanID"] for sp in spans}
        for sp in spans:
            assert sp["ParentID"] == "" or sp["ParentID"] in ids, sp
        root = next(sp for sp in spans if sp["Name"] == "eval")
        assert root["ParentID"] == ""
        assert root["SpanID"] == span_id(ev.trace_id, "eval")
        # the wait histogram observed the dequeue
        assert REGISTRY.histogram("nomad.broker.wait_s")["count"] >= 1
        assert REGISTRY.histogram("nomad.worker.schedule_s",
                                  type=job.type)["count"] >= 1

    def test_follow_up_evals_inherit_trace(self):
        ev = mock.eval()
        ev.trace_id = "tid-123"
        fu = ev.create_failed_follow_up_eval(wait_until=99.0)
        assert fu.trace_id == "tid-123"
        blocked = ev.create_blocked_eval({}, escaped=False)
        assert blocked.trace_id == "tid-123"


class TestVirtualClockDeterminism:
    def _run_once(self):
        """One synchronous dev-server pass on a VirtualClock with a
        scripted advance schedule — the deterministic shape chaos
        scenarios drive (same clock seam, no thread races)."""
        TRACER.reset()
        REGISTRY.reset()
        clock = VirtualClock(epoch=1.7e9)
        s = Server(num_workers=1, clock=clock)
        s.establish_leadership()
        s.register_node(mock.node())
        clock.advance(1.0)
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job)
        clock.advance(0.5)
        s.process_all()
        clock.advance(0.25)
        spans = sorted(TRACER.spans(), key=lambda sp: sp["Seq"])
        return json.dumps(
            [(sp["Name"], sp["Start"], sp["End"], sp["Duration"])
             for sp in spans]).encode()

    def test_same_run_twice_is_byte_identical(self):
        a = self._run_once()
        b = self._run_once()
        assert a == b
        assert b"worker.schedule" in a


# ---------------------------------------------------------- end to end


@pytest.fixture(scope="module")
def agent():
    TRACER.reset()
    ag = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600)
    ag.start()
    yield ag
    ag.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(address=agent.address)


class TestEndToEnd:
    def _register(self, api, count=1, run_for=300):
        job = mock.batch_job()
        job.task_groups[0].count = count
        job.task_groups[0].tasks[0].config = {"run_for_s": run_for}
        resp = api.jobs.register(codec.encode(job))
        assert resp["EvalID"]
        return job, resp["EvalID"]

    def test_one_run_yields_one_joined_trace(self, api):
        _, eval_id = self._register(api)

        def full_trace():
            try:
                t = api.agent.trace(eval_id)
            except Exception:  # noqa: BLE001 - not recorded yet
                return None
            names = {sp["Name"] for sp in t["Spans"]}
            want = {"eval", "broker.wait", "worker.schedule",
                    "plan.queue_wait", "plan.apply", "client.alloc_start"}
            return t if want <= names else None

        t = _wait(full_trace, timeout=30)
        assert t, "trace never covered the full lifecycle: " + str(
            api.agent.traces())
        spans = t["Spans"]
        ids = {sp["SpanID"] for sp in spans}
        for sp in spans:
            assert sp["ParentID"] == "" or sp["ParentID"] in ids, sp
        # tree shape: broker/schedule under the root eval span, plan
        # spans under schedule, alloc start under plan.apply
        by_name = {sp["Name"]: sp for sp in spans}
        root_id = by_name["eval"]["SpanID"]
        assert by_name["broker.wait"]["ParentID"] == root_id
        assert by_name["worker.schedule"]["ParentID"] == root_id
        sched_id = by_name["worker.schedule"]["SpanID"]
        assert by_name["plan.queue_wait"]["ParentID"] == sched_id
        assert by_name["plan.apply"]["ParentID"] == sched_id
        assert by_name["client.alloc_start"]["ParentID"] == \
            by_name["plan.apply"]["SpanID"]
        # summaries list the trace too
        assert any(row["TraceID"] == eval_id
                   for row in api.agent.traces())

    def test_prometheus_endpoint(self, api):
        self._register(api)
        _wait(lambda: REGISTRY.histogram("nomad.plan.apply_s"))
        text = api.agent.metrics(format="prometheus")
        families = assert_valid_exposition(text)
        # acceptance: histogram families with percentile summaries for
        # broker wait, schedule, and plan-apply latency
        for fam in ("nomad_broker_wait_seconds",
                    "nomad_worker_schedule_seconds",
                    "nomad_plan_apply_seconds"):
            assert families.get(fam) == "histogram", families
            for q in ("p50", "p95", "p99"):
                assert families.get(f"{fam}_{q}") == "gauge"
        assert families.get("nomad_broker_acked") == "counter"
        assert families.get("nomad_state_nodes") == "gauge"

    def test_metrics_json_includes_percentile_summaries(self, api):
        m = api.agent.metrics()
        assert "nomad.broker.total_ready" in m     # legacy keys survive
        assert "nomad.state.nodes" in m
        assert "nomad.broker.wait_s.p99" in m
        assert "nomad.broker.wait_s.count" in m

    def test_operator_debug_bundle(self, api):
        bundle = api.operator.debug()
        for key in ("Stats", "Metrics", "Prometheus", "Traces", "Spans",
                    "Logs", "Threads"):
            assert key in bundle, sorted(bundle)
        assert isinstance(bundle["Prometheus"], str)
        assert bundle["Traces"], "debug bundle has no traces"

    # --------------------------------------------- streaming endpoints

    def test_monitor_stream_backlog_then_live(self, agent):
        marker_backlog = f"backlog-{new_id()[:8]}"
        log("telemetry-test", "warn", marker_backlog)
        url = f"{agent.address}/v1/agent/monitor?log_level=trace"
        subs_before = len(RING._subs)
        resp = urllib.request.urlopen(url, timeout=10)
        try:
            assert _wait(lambda: len(RING._subs) == subs_before + 1,
                         timeout=5)
            # backlog: the pre-subscribe record arrives first
            seen = []
            while True:
                line = resp.readline()
                seen.append(line)
                if marker_backlog.encode() in line:
                    break
                assert line, f"stream ended early: {seen}"
            # live: a record logged after subscribe streams through
            marker_live = f"live-{new_id()[:8]}"
            log("telemetry-test", "warn", marker_live)
            while True:
                line = resp.readline()
                assert line, "stream ended before live record"
                if marker_live.encode() in line:
                    break
            rec = json.loads(line)
            assert rec["component"] == "telemetry-test"
        finally:
            resp.close()
        # disconnect cleanup: once the client is gone, the next write
        # attempts fail and the subscription is unsubscribed
        def drained():
            log("telemetry-test", "warn", "poke")
            return len(RING._subs) == subs_before
        assert _wait(drained, timeout=10), "monitor sub never cleaned up"

    def test_event_stream_cleanup_on_disconnect(self, agent, api):
        events = agent.server.events
        subs_before = len(events._subs)
        url = f"{agent.address}/v1/event/stream?topic=Job"
        resp = urllib.request.urlopen(url, timeout=10)
        try:
            assert _wait(lambda: len(events._subs) == subs_before + 1,
                         timeout=5)
            # a matching event streams through while connected
            self._register(api)
            line = resp.readline()
            assert line
            batch = json.loads(line)
            assert batch["Events"][0]["Topic"] == "Job"
        finally:
            resp.close()

        def drained():
            self._register(api)      # generate events -> write fails
            return len(events._subs) == subs_before
        assert _wait(drained, timeout=10), "event sub never cleaned up"


# --------------------------------------------------------------- logring


class TestLogRing:
    def test_wrap_trim_and_subscriber_drops_are_counted(self):
        ring = LogRing(size=8)
        trim0 = REGISTRY.counter("nomad.logring.dropped", reason="trim")
        for i in range(9):
            ring.log("t", "info", f"m{i}")
        assert REGISTRY.counter("nomad.logring.dropped",
                                reason="trim") == trim0 + 2  # size // 4
        q = ring.subscribe(maxsize=1)
        sub0 = REGISTRY.counter("nomad.logring.dropped",
                                reason="subscriber")
        for i in range(3):
            ring.log("t", "info", f"s{i}")
        assert REGISTRY.counter(
            "nomad.logring.dropped", reason="subscriber") == sub0 + 2
        ring.unsubscribe(q)

    def test_min_level_gates_producer_side(self):
        ring = LogRing(size=16)
        ring.min_level = "warn"
        ring.log("t", "debug", "invisible")
        ring.log("t", "error", "visible")
        msgs = [r["msg"] for r in ring.tail(10)]
        assert "visible" in msgs and "invisible" not in msgs


# ---------------------------------------------------------- cheap scrape


class TestCheapScrape:
    def test_state_counts_match_tables(self):
        s = Server(num_workers=1)
        s.establish_leadership()
        assert s.state.counts()["nodes"] == 0
        s.register_node(mock.node())
        s.register_job(mock.job())
        counts = s.state.counts()
        assert counts["nodes"] == 1
        assert counts["jobs"] == 1
        assert counts["evals"] >= 1
