"""Cluster-scope observability (core/obsbus.py + core/federation.py):
the ObsBus plane-registration seam, metric federation (leader pulls
compact peer snapshots, publishes `nomad.cluster.*`), cross-node trace
stitching, and the HTTP/SDK/CLI surfaces on top of them.

Determinism doctrine: federation cadence rides the injected clock, the
fake peer transport is a pure function of (origin, scrape count), and
two identical runs must publish byte-identical cluster gauge/counter
sequences (wall-derived self-metering — scrape_us — is excluded, like
every other volatile wall fact)."""

import json
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.chaos.clock import VirtualClock
from nomad_tpu.core import wire
from nomad_tpu.core.federation import (
    FederationPuller,
    agent_snapshot,
    stitch_trace,
)
from nomad_tpu.core.flightrec import HealthWatchdog
from nomad_tpu.core.obsbus import OBSBUS, ObsBus
from nomad_tpu.core.telemetry import REGISTRY
from nomad_tpu.structs import codec


def _wait(fn, timeout=30, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


def _wire_batch_job(count=1, run_for=300):
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].config = {"run_for_s": run_for}
    return codec.encode(job), job


# ---------------------------------------------------------------------------
# ObsBus
# ---------------------------------------------------------------------------


class TestObsBus:
    def test_every_plane_registers_on_server_import(self):
        """Importing core.server pulls in every plane module, and each
        registers itself at module bottom — the acceptance list: all
        eight planes visible on the process-global bus."""
        import nomad_tpu.core.server  # noqa: F401 - registration side effect
        assert {"telemetry", "tracer", "flightrec", "logging",
                "identity", "timeline", "memledger",
                "profiler"} <= set(OBSBUS.planes())

    def test_configure_fans_out_and_isolates_errors(self):
        bus = ObsBus()
        seen = []
        bus.register("good", configure=seen.append)
        bus.register("bad", configure=lambda c: 1 / 0)
        clock = VirtualClock()
        bus.configure(clock)
        assert seen == [clock]
        assert bus.stats()["hook_errors"] == 1

    def test_snapshot_routes_and_isolates(self):
        bus = ObsBus()
        bus.register("a", snapshot=lambda: {"x": 1})
        bus.register("b", snapshot=lambda: 1 / 0)
        bus.register("c")                    # no snapshot hook: absent
        snap = bus.snapshot()
        assert snap["a"] == {"x": 1}
        assert "error" in snap["b"]
        assert "c" not in snap

    def test_reset_returns_reset_plane_names(self):
        bus = ObsBus()
        hit = []
        bus.register("a", reset=lambda: hit.append("a"))
        bus.register("b")
        assert bus.reset() == ["a"]
        assert hit == ["a"]

    def test_registration_is_last_write_wins(self):
        bus = ObsBus()
        bus.register("p", snapshot=lambda: {"v": 1})
        bus.register("p", snapshot=lambda: {"v": 2})
        assert bus.planes() == ["p"]
        assert bus.snapshot()["p"] == {"v": 2}


# ---------------------------------------------------------------------------
# agent_snapshot (the federation scrape body)
# ---------------------------------------------------------------------------


class TestAgentSnapshot:
    def test_shape_and_wire_round_trip(self):
        doc = agent_snapshot("s1")
        assert doc["Schema"] == "nomad-tpu.federation.v1"
        assert doc["Origin"] == "s1"
        assert set(doc["Counters"]) >= {"nomad.heartbeat.missed",
                                        "nomad.health.breaches"}
        assert "Timeline" in doc and "Memory" in doc
        again = wire.unpackb(wire.packb(doc))
        assert again["Origin"] == "s1"
        assert again["Counters"] == doc["Counters"]

    def test_since_seq_bounds_the_timeline_delta(self):
        full = agent_snapshot("s1", since_seq=0)["Timeline"]
        tail = agent_snapshot("s1",
                              since_seq=full["Seq"])["Timeline"]
        assert tail["Seq"] >= full["Seq"]
        assert len(tail["Samples"]) <= len(full["Samples"])


# ---------------------------------------------------------------------------
# stitch_trace
# ---------------------------------------------------------------------------


def _span(name, trace="t1", parent="", start=0.0, seq=0, dur=0.001):
    sid = f"{trace[:8]}-{name}"
    return {"TraceID": trace, "SpanID": sid, "ParentID": parent,
            "Name": name, "Start": start, "End": start + dur,
            "Duration": dur, "Seq": seq}


class TestStitchTrace:
    def test_cross_origin_parent_edge(self):
        """The whole point: a follower's forwarded-RPC span parents
        the leader's commit span even though they were recorded on
        different nodes (ParentID resolves cross-origin when no
        same-origin parent exists)."""
        fwd = _span("rpc.forward", start=0.0, seq=0)
        commit = _span("plan.apply", parent=fwd["SpanID"],
                       start=0.001, seq=1)
        doc = stitch_trace("t1", {"follower": [fwd],
                                  "leader": [commit]})
        assert doc["Origins"] == ["follower", "leader"]
        assert doc["SpanCount"] == 2
        assert len(doc["Tree"]) == 1
        root = doc["Tree"][0]
        assert root["Span"]["Name"] == "rpc.forward"
        assert root["Span"]["Origin"] == "follower"
        kids = [k["Span"] for k in root["Children"]]
        assert [(k["Name"], k["Origin"]) for k in kids] == [
            ("plan.apply", "leader")]

    def test_same_origin_parent_preferred(self):
        """Replicated span names collide by SpanID (deterministic ids);
        each copy must attach to ITS OWN origin's parent, not the first
        origin's."""
        docs = {}
        for o in ("a", "b"):
            root = _span("eval", start=0.0, seq=0)
            kid = _span("worker.schedule", parent=root["SpanID"],
                        start=0.001, seq=1)
            docs[o] = [root, kid]
        doc = stitch_trace("t1", docs)
        assert doc["SpanCount"] == 4
        for tree in doc["Tree"]:
            origin = tree["Span"]["Origin"]
            for kid in tree["Children"]:
                assert kid["Span"]["Origin"] == origin

    def test_dedupe_and_empty_origins_excluded(self):
        s = _span("eval")
        doc = stitch_trace("t1", {"a": [s, dict(s)],   # same (origin, id)
                                  "b": [],             # polled, empty
                                  "c": [dict(s)]})     # same id, new origin
        assert doc["SpanCount"] == 2
        assert doc["Origins"] == ["a", "c"]            # b contributed 0


# ---------------------------------------------------------------------------
# FederationPuller: determinism, peer isolation, throttle, SLO edge
# ---------------------------------------------------------------------------


def _fake_transport(fail=()):
    """Pure function of (origin, call count) — deterministic scrape
    bodies; origins in `fail` raise like a dark peer."""
    calls = {}

    def fetch(origin, url, since_seq):
        n = calls[origin] = calls.get(origin, 0) + 1
        if origin in fail:
            raise ConnectionError(f"{origin} down")
        return {"Schema": "nomad-tpu.federation.v1", "Origin": origin,
                "At": float(n), "AppliedIndex": 100 * n,
                "Counters": {"nomad.heartbeat.missed": float(n)},
                "Gauges": {"nomad.health.healthy": 1.0,
                           "nomad.health.breached_rules": 0.0,
                           "nomad.mem.rss_bytes": 1024.0 * n},
                "Flight": {"entries": 10 * n},
                "Memory": {"rss_bytes": 1024 * n},
                "Follower": None,
                "Timeline": {"Seq": since_seq, "StepS": 1.0,
                             "Samples": {}, "Annotations": []}}
    return fetch


class _FakeState:
    def __init__(self, index=500):
        self.index = index

    def latest_index(self):
        return self.index


def _cluster_metrics():
    """The deterministic `nomad.cluster.*` slice of the registry —
    wall-derived self-metering (scrape_us, scrape_s windows) excluded,
    like every volatile wall fact."""
    snap = REGISTRY.snapshot()
    out = {}
    for kind in ("counters", "gauges"):
        for k, v in snap[kind].items():
            if k.startswith("nomad.cluster.") and "scrape_us" not in k:
                out[f"{kind}:{k}"] = v
    return out


def _run_scrapes(n=4):
    REGISTRY.clear_series("nomad.cluster.")
    clock = VirtualClock()
    puller = FederationPuller(
        "s1", targets=lambda: [("s2", "http://s2"), ("s3", "http://s3")],
        transport=_fake_transport(), clock=clock, state=_FakeState(),
        interval_s=5.0, min_wall_s=0.0)
    seq = []
    for i in range(n):
        assert puller.sample(5.0 * i)
        seq.append(json.dumps(_cluster_metrics(), sort_keys=True))
    return "\n".join(seq).encode()


class TestFederationPuller:
    def test_double_run_is_byte_identical(self):
        assert _run_scrapes() == _run_scrapes()

    def test_gauges_are_origin_labeled(self):
        _run_scrapes(n=1)
        g = REGISTRY.snapshot()["gauges"]
        assert g["nomad.cluster.applied_index{origin=s2}"] == 100.0
        assert g["nomad.cluster.applied_index{origin=s3}"] == 100.0
        assert g["nomad.cluster.peers"] == 2.0
        assert g["nomad.cluster.peers_ok"] == 2.0

    def test_throttle_follows_the_memledger_discipline(self):
        puller = FederationPuller(
            "s1", targets=lambda: [], transport=_fake_transport(),
            clock=VirtualClock(), interval_s=5.0, min_wall_s=0.0)
        assert puller.sample(0.0)
        assert not puller.sample(2.0)      # within interval: suppressed
        assert puller.sample(5.0)          # due
        assert puller.sample(-10.0)        # rebound timebase: due

    def test_peer_down_is_counted_never_raised(self):
        REGISTRY.clear_series("nomad.cluster.")
        puller = FederationPuller(
            "s1", targets=lambda: [("s2", "http://s2"),
                                   ("s3", "http://s3")],
            transport=_fake_transport(fail=("s3",)),
            clock=VirtualClock(), state=_FakeState(),
            interval_s=5.0, min_wall_s=0.0)
        assert puller.sample(0.0)          # the dark peer must not raise
        assert REGISTRY.counter("nomad.cluster.scrape_failures",
                                origin="s3") == 1.0
        g = REGISTRY.snapshot()["gauges"]
        assert g["nomad.cluster.peers"] == 2.0
        assert g["nomad.cluster.peers_ok"] == 1.0
        row = puller.doc()["Origins"]["s3"]
        assert not row["Ok"] and "down" in row["Error"]

    def test_follower_registration_merges_and_unregisters(self):
        puller = FederationPuller(
            "s1", targets=lambda: [("s2", "http://s2")],
            transport=_fake_transport(), clock=VirtualClock())
        puller.register_target("follower-1", "http://f1")
        assert puller.targets() == [("follower-1", "http://f1"),
                                    ("s2", "http://s2")]
        puller.unregister_target("follower-1")
        assert puller.targets() == [("s2", "http://s2")]

    def test_scrape_failure_trips_the_cluster_slo_once(self):
        """The cluster_scrape_failures rule is edge-triggered: a peer
        that STAYS dark breaches on the first check after the failures
        appear and is not re-counted while the breach persists."""
        REGISTRY.clear_series("nomad.cluster.")
        clock = VirtualClock()
        wd = HealthWatchdog(clock=clock)
        wd.check(now=0.0)                  # baseline
        puller = FederationPuller(
            "s1", targets=lambda: [("s2", "http://s2")],
            transport=_fake_transport(fail=("s2",)),
            clock=clock, interval_s=5.0, min_wall_s=0.0)
        puller.sample(0.0)
        doc = wd.check(now=60.0)
        rule = next(r for r in doc["Rules"]
                    if r["Rule"] == "cluster_scrape_failures")
        assert not rule["Ok"]
        breaches = wd.stats["breaches"]
        puller.sample(65.0)                # still dark: more failures
        wd.check(now=120.0)
        assert wd.stats["breaches"] == breaches   # edge-triggered once

    def test_cluster_rules_observe_none_without_federation(self):
        """Followers and standalone servers never run the puller, so
        every cluster_* rule observes None (can't breach) until the
        `nomad.cluster.scrapes` counter moves."""
        REGISTRY.clear_series("nomad.cluster.")
        wd = HealthWatchdog(clock=VirtualClock())
        wd.check(now=0.0)
        doc = wd.check(now=60.0)
        for r in doc["Rules"]:
            if r["Rule"].startswith("cluster_"):
                assert r["Observed"] is None and r["Ok"], r


# ---------------------------------------------------------------------------
# HTTP surfaces on a standalone agent (fast)
# ---------------------------------------------------------------------------


class TestStandaloneSurfaces:
    def test_compact_self_cluster_health_and_bundle(self):
        ag = Agent(num_clients=1, num_workers=1,
                   heartbeat_ttl=3600).start()
        try:
            api = APIClient(address=ag.address)
            w, job = _wire_batch_job()
            api.jobs.register(w)
            _wait(lambda: api.jobs.allocations(job.id))

            # compact scrape body: msgpack, not JSON
            with urllib.request.urlopen(
                    ag.address + "/v1/agent/self?compact=1",
                    timeout=5) as r:
                assert r.headers["Content-Type"] == "application/msgpack"
                doc = wire.unpackb(r.read())
            assert doc["Schema"] == "nomad-tpu.federation.v1"
            assert doc["AppliedIndex"] >= 1

            # cluster-health: no federation plane in standalone mode,
            # cluster rules observe None -> healthy
            ch = api.operator.cluster_health()
            assert ch["Healthy"] and ch["Federation"] is None
            assert {r["Rule"] for r in ch["Rules"]} == {
                "cluster_scrape_failures", "cluster_follower_lag",
                "cluster_heartbeat_misses"}

            # the debug bundle carries the (absent) cluster section
            assert ag.http and "Cluster" in api.operator.debug()
            assert api.operator.debug()["Cluster"] is None

            # ?cluster=true works standalone: one origin, local spans
            ev = api.jobs.evaluations(job.id)[0]["ID"]
            stitched = api.agent.trace(ev, cluster=True)
            assert stitched["Origins"] == ["local"]
            assert stitched["SpanCount"] >= 1
        finally:
            ag.shutdown()

    def test_follower_gauges_and_announce_latch(self):
        """Satellite: the read follower publishes `nomad.follower.*`
        registry gauges (not just HTTP headers), and announces itself
        to its upstream exactly once per upstream."""
        leader = Agent(num_clients=1, num_workers=1,
                       heartbeat_ttl=3600).start()
        fol = Agent(num_clients=0, num_workers=1, heartbeat_ttl=3600,
                    follow=leader.address).start()
        try:
            api = APIClient(address=leader.address)
            w, job = _wire_batch_job()
            api.jobs.register(w)
            fapi = APIClient(address=fol.address)
            assert _wait(lambda: any(s["ID"] == job.id
                                     for s in fapi.jobs.list()),
                         timeout=15)
            g = REGISTRY.snapshot()["gauges"]
            assert g["nomad.follower.applied_index"] >= 1
            assert g["nomad.follower.last_contact_s"] >= 0.0
            # announce latched to the current upstream (the standalone
            # leader has no puller, but the PUT round-trip succeeded).
            # The latch happens on the same pull that applied the job,
            # just after the state apply — poll, don't race it.
            assert _wait(lambda:
                         fol.follower._announced_to == leader.address,
                         timeout=15)
        finally:
            fol.shutdown()
            leader.shutdown()


# ---------------------------------------------------------------------------
# 3-server cluster: stitched traces + cluster health across failover
# ---------------------------------------------------------------------------


def _cluster_trio():
    a1 = Agent(server_name="fed-s1", bootstrap_expect=3, num_clients=1,
               num_workers=1, heartbeat_ttl=3600).start()
    seed = "{}:{}".format(*a1.server.gossip.addr)
    a2 = Agent(server_name="fed-s2", bootstrap_expect=3, num_clients=0,
               num_workers=1, heartbeat_ttl=3600, join=[seed]).start()
    a3 = Agent(server_name="fed-s3", bootstrap_expect=3, num_clients=0,
               num_workers=1, heartbeat_ttl=3600, join=[seed]).start()
    agents = [a1, a2, a3]
    for ag in agents:
        # shrink the federation cadence so the test doesn't idle
        # through the production 5 s interval / 0.5 s wall floor
        ag.server.federation.interval_s = 0.2
        ag.server.federation.min_wall_s = 0.0
    return agents


@pytest.mark.slow
class TestClusterFederation:
    def test_stitch_health_and_failover_reconvergence(self):
        agents = _cluster_trio()
        try:
            leader = _wait(lambda: next(
                (a for a in agents if a.server.is_leader()), None),
                timeout=30)
            assert leader is not None
            others = [a for a in agents if a is not leader]
            lapi = APIClient(address=leader.address)

            # register through a NON-leader: the forwarded write is the
            # cross-origin hop the stitched trace exists to show
            fapi = APIClient(address=others[0].address)
            w, job = _wire_batch_job()
            fapi.jobs.register(w)
            assert _wait(lambda: lapi.jobs.allocations(job.id),
                         timeout=30)

            # federation converges: the leader scraped both peers
            def scraped():
                doc = lapi.operator.cluster_health()
                fed = doc.get("Federation") or {}
                return (len(fed.get("Origins") or {}) >= 2
                        and fed.get("Scrapes", 0) > 0 and doc)
            doc = _wait(scraped, timeout=30)
            assert doc and doc["Healthy"], doc
            assert all(r["Ok"] for r in doc["Rules"])
            rows = doc["Federation"]["Origins"]
            assert all(rows[o]["Ok"] for o in rows), rows

            # the exposition carries the cluster families
            prom = lapi.agent.metrics(format="prometheus")
            assert "nomad_cluster_peers" in prom
            assert "nomad_cluster_applied_index" in prom

            # stitched trace: one joined tree, >= 2 origins
            ev = fapi.jobs.evaluations(job.id)[0]["ID"]
            stitched = lapi.agent.trace(ev, cluster=True)
            assert len(stitched["Origins"]) >= 2, stitched["Origins"]
            assert stitched["Tree"], "stitched trace has no roots"

            # kill the leader: a new leader's puller takes over and
            # cluster health re-converges green
            leader.shutdown()
            new_leader = _wait(lambda: next(
                (a for a in others if a.server.is_leader()), None),
                timeout=30)
            assert new_leader is not None
            napi = APIClient(address=new_leader.address)

            def reconverged():
                doc = napi.operator.cluster_health()
                fed = doc.get("Federation") or {}
                rows = fed.get("Origins") or {}
                live = [o for o, r in rows.items() if r.get("Ok")]
                return (fed.get("Scrapes", 0) > 0 and live and doc)
            doc = _wait(reconverged, timeout=30)
            assert doc, "new leader never scraped"
            assert doc["Healthy"] or any(
                not r["Ok"] for r in doc["Rules"]) is False
        finally:
            for ag in agents:
                try:
                    ag.shutdown()
                except Exception:
                    pass
