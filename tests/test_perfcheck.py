"""Performance-trajectory gate coverage (scripts/perfcheck.py).

The comparator itself is load-bearing CI wiring: these tests prove the
bands fail when they should (step regressions, flipped fingerprints,
scale mismatches) and pass when they should (identity, noise inside
the tolerance), plus the --self-check posture against the checked-in
trajectory files."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "perfcheck", ROOT / "scripts" / "perfcheck.py")
perfcheck = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perfcheck)

BENCH = {
    "n_evals": 16, "placements_per_eval": 2000, "workers": 2,
    "value": 100.0, "sustained_evals_per_sec": 100.0,
    "p99_plan_queue_ms": 2.0, "plan_refute_rate": 0.0,
    "h2d_bytes_per_wave": 2560.0, "slo_breaches": 0,
    "sampler_overhead_fraction": 0.004,
    "profile_attributed_fraction": 1.0,
}

SOAK = {
    "soak_virtual_hours": 2.0, "soak_evals": 500, "soak_breaches": 0,
    "schedule_events": 900, "p99_plan_queue_ms": 1.5,
    "converged_fingerprint": "a" * 64, "trace_digest": "b" * 64,
    "violations": [], "wall_s": 40.0,
}


def test_bench_identity_passes():
    v = perfcheck.compare_bench(BENCH, dict(BENCH),
                                perfcheck.BENCH_BANDS)
    assert v["verdict"] == "pass", v
    assert v["failed"] == []


def test_bench_noise_inside_band_passes():
    fresh = dict(BENCH, value=75.0, p99_plan_queue_ms=3.5)
    v = perfcheck.compare_bench(BENCH, fresh, perfcheck.BENCH_BANDS)
    assert v["verdict"] == "pass", v


def test_bench_step_regression_fails_named():
    fresh = dict(BENCH, value=40.0, p99_plan_queue_ms=30.0)
    v = perfcheck.compare_bench(BENCH, fresh, perfcheck.BENCH_BANDS)
    assert v["verdict"] == "fail"
    assert "value" in v["failed"]
    assert "p99_plan_queue_ms" in v["failed"]


def test_bench_abs_gates_are_baseline_free():
    # the fresh doc alone must satisfy the profiling-plane acceptance
    fresh = dict(BENCH, sampler_overhead_fraction=0.05,
                 profile_attributed_fraction=0.5, slo_breaches=2)
    v = perfcheck.compare_bench(BENCH, fresh, perfcheck.BENCH_BANDS)
    assert v["verdict"] == "fail"
    for m in ("sampler_overhead_fraction",
              "profile_attributed_fraction", "slo_breaches"):
        assert m in v["failed"], v["failed"]


def test_bench_scale_mismatch_is_incomparable():
    v = perfcheck.compare_bench(BENCH, dict(BENCH, workers=1),
                                perfcheck.BENCH_BANDS)
    assert v["verdict"] == "incomparable"
    assert "workers" in v["scale_mismatch"]
    v = perfcheck.compare_bench(BENCH, dict(BENCH, workers=1),
                                perfcheck.BENCH_BANDS,
                                allow_scale_mismatch=True)
    assert v["verdict"] == "pass"


def test_bench_missing_fields_skip_not_fail():
    # pre-profiling-plane baselines lack the sampler fields entirely
    base = {k: v for k, v in BENCH.items()
            if not k.startswith(("sampler", "profile"))}
    v = perfcheck.compare_bench(base, dict(base),
                                perfcheck.BENCH_BANDS)
    assert v["verdict"] == "pass"
    assert "sampler_overhead_fraction" in v["skipped"]


def test_soak_identity_passes():
    v = perfcheck.compare_soak(SOAK, dict(SOAK))
    assert v["verdict"] == "pass", v
    assert v["wall_s"] == {"baseline": 40.0, "fresh": 40.0}


def test_soak_fingerprint_flip_fails_exact():
    # exact bands compare strings too — a changed fingerprint is a
    # determinism break, not noise
    v = perfcheck.compare_soak(SOAK, dict(SOAK,
                                          converged_fingerprint="0" * 64))
    assert v["verdict"] == "fail"
    assert v["failed"] == ["converged_fingerprint"]


def test_soak_wall_clock_is_informational():
    v = perfcheck.compare_soak(SOAK, dict(SOAK, wall_s=400.0))
    assert v["verdict"] == "pass"


def test_soak_violations_and_breaches_fail():
    fresh = dict(SOAK, violations=["broker: stuck eval"],
                 soak_breaches=3)
    v = perfcheck.compare_soak(SOAK, fresh)
    assert v["verdict"] == "fail"
    assert "violations" in v["failed"]
    assert "soak_breaches" in v["failed"]


def test_band_override_parsing():
    bands = perfcheck._parse_band_overrides(
        ["value=0.10"], perfcheck.BENCH_BANDS)
    assert bands["value"] == ("min", 0.10, 0.0)
    v = perfcheck.compare_bench(BENCH, dict(BENCH, value=75.0), bands)
    assert v["verdict"] == "fail"   # 25% drop vs the tightened 10% band


def test_load_unwraps_bench_round_wrapper(tmp_path):
    p = tmp_path / "BENCH_wrapped.json"
    p.write_text(json.dumps({"round": 7, "parsed": BENCH}))
    assert perfcheck._load(str(p)) == BENCH


def test_cli_verdict_json_and_exit_codes(tmp_path):
    base = tmp_path / "base.json"
    base.write_text(json.dumps(BENCH))
    good = tmp_path / "good.json"
    good.write_text(json.dumps(BENCH))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(dict(BENCH, value=1.0)))
    out = tmp_path / "verdict.json"
    script = str(ROOT / "scripts" / "perfcheck.py")
    r = subprocess.run(
        [sys.executable, script, "--kind", "bench",
         "--fresh", str(good), "--baseline", str(base),
         "--json", str(out)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["verdict"] == "pass"
    assert doc["baseline_path"]
    r = subprocess.run(
        [sys.executable, script, "--fresh", str(bad),
         "--baseline", str(base)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "value" in json.loads(r.stdout)["failed"]
    r = subprocess.run(
        [sys.executable, script, "--fresh", str(tmp_path / "nope.json"),
         "--baseline", str(tmp_path / "missing.json")],
        capture_output=True, text=True)
    assert r.returncode == 2


def test_self_check_green_against_checked_in_trajectory():
    """The exact gate scripts/ci.sh runs: comparator passes against
    itself and catches injected regressions on the real baselines."""
    assert perfcheck.self_check() == 0
