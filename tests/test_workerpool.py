"""Multi-process worker plane tests (core/workerpool.py).

Fast tier-1 tests cover the pieces in isolation: state export/delta
round-trips, the device submission front-end's serialization, the
sharded dynamic-port scan, and the replica-vs-thread visibility knobs.
The spawn-based integration tests (real worker processes against a
live Server) are marked `slow` and ride the ci.sh multiproc stage —
each spawn pays a full interpreter + jax import.
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.state import StateStore
from nomad_tpu.structs import funcs as structs_funcs
from nomad_tpu.structs.funcs import NetworkIndex, set_dynamic_port_scan_base
from nomad_tpu.structs.structs import (
    MAX_DYNAMIC_PORT,
    MIN_DYNAMIC_PORT,
    NetworkResource,
    Port,
    VolumeRequest,
)


@pytest.fixture(autouse=True)
def _reset_port_scan():
    """Every test leaves the process scan base at its historical
    default — the thread plane's byte-identical seeded soaks depend on
    ascending-from-20000 picks."""
    yield
    set_dynamic_port_scan_base(MIN_DYNAMIC_PORT, rotate=False)


# =====================================================================
# state export / delta round-trip
# =====================================================================


class TestStateExport:
    def _seeded_store(self):
        s = StateStore()
        nodes = [mock.node(name=f"n{i}") for i in range(4)]
        s.upsert_nodes(nodes)
        job = mock.job()
        s.upsert_job(job)
        allocs = [mock.alloc(node_id=nodes[i % 4].id, job=job,
                             job_id=job.id)
                  for i in range(6)]
        s.upsert_allocs(allocs)
        return s, nodes, job, allocs

    def test_full_export_bootstraps_replica(self):
        s, nodes, job, allocs = self._seeded_store()
        # a replica older than the journal floor gets a full snapshot
        s._journal_floor = s.latest_index()
        export = s.export_since(0)
        assert export["kind"] == "full"
        r = StateStore()
        r.apply_export(export)
        assert r.latest_index() == s.latest_index()
        rs, ss = r.snapshot(), s.snapshot()
        assert {n.id for n in rs.nodes()} == {n.id for n in nodes}
        assert len(rs.allocs_by_node(nodes[0].id)) == \
            len(ss.allocs_by_node(nodes[0].id))

    def test_delta_ships_only_dirtied_keys(self):
        s, nodes, job, allocs = self._seeded_store()
        r = StateStore()
        r.apply_export(s.export_since(0))
        since = r.latest_index()
        # dirty one node and one alloc
        n0 = nodes[0].copy()
        n0.status = "down"
        s.upsert_node(n0)
        a0 = allocs[0].copy_skip_job()
        a0.job = job
        a0.client_status = "running"
        s.upsert_allocs([a0])
        export = s.export_since(since)
        assert export["kind"] == "delta"
        assert {n.id for n in export["upserts"]["nodes"]} == {n0.id}
        assert {a.id for a in export["upserts"]["allocs"]} == {a0.id}
        r.apply_export(export)
        assert r.node_by_id(n0.id).status == "down"
        got = {a.id: a for a in r.snapshot().allocs_by_node(nodes[0].id)}
        assert got[a0.id].client_status == "running"
        # the replica re-attaches the embedded job pointer (slimmed on
        # the wire) so schedulers can resolve task groups
        assert got[a0.id].job is not None
        assert r.latest_index() == s.latest_index()

    def test_delta_carries_deletions_as_tombstones(self):
        s, nodes, job, allocs = self._seeded_store()
        r = StateStore()
        r.apply_export(s.export_since(0))
        since = r.latest_index()
        s.delete_node(nodes[3].id)
        export = s.export_since(since)
        assert export["kind"] == "delta"
        assert ("nodes", nodes[3].id) in export["deletes"]
        r.apply_export(export)
        assert r.node_by_id(nodes[3].id) is None

    def test_fresh_replica_bootstraps_via_delta(self):
        # journal floor starts at 0, so since=0 rides the delta path:
        # every key dirtied since genesis ships as an upsert
        s, nodes, job, allocs = self._seeded_store()
        export = s.export_since(0)
        assert export["kind"] == "delta"
        r = StateStore()
        r.apply_export(export)
        assert {n.id for n in r.snapshot().nodes()} == \
            {n.id for n in nodes}
        assert r.latest_index() == s.latest_index()

    def test_empty_export_when_caught_up(self):
        s, _, _, _ = self._seeded_store()
        export = s.export_since(s.latest_index())
        assert export["kind"] == "empty"

    def test_export_survives_wire_roundtrip(self):
        from nomad_tpu.core import wire
        from nomad_tpu.core.workerpool import _ensure_wire_types
        _ensure_wire_types()
        s, nodes, job, allocs = self._seeded_store()
        export = wire.unpackb(wire.packb(s.export_since(0)))
        r = StateStore()
        r.apply_export(export)
        assert {n.id for n in r.snapshot().nodes()} == \
            {n.id for n in nodes}
        assert r.latest_index() == s.latest_index()


# =====================================================================
# device submission front-end
# =====================================================================


class _SlowExecutor:
    """Records overlap: dispatches must never interleave."""

    def __init__(self):
        self.inside = 0
        self.max_inside = 0
        self.calls = 0
        self._guard = threading.Lock()

    def dispatch_batch(self, snapshot, items, seed=0, used0_dev=None,
                       masked_node_ids=None):
        with self._guard:
            self.inside += 1
            self.max_inside = max(self.max_inside, self.inside)
        time.sleep(0.01)
        with self._guard:
            self.inside -= 1
            self.calls += 1
        return {"ok": True}


class TestSubmissionFrontEnd:
    def test_serializes_and_meters_queue_wait(self):
        from nomad_tpu.ops.executor import SubmissionFrontEnd
        front = SubmissionFrontEnd(_SlowExecutor())
        threads = [threading.Thread(
            target=lambda: front.dispatch_batch(None, []))
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert front.executor.calls == 4
        assert front.executor.max_inside == 1     # never interleaved
        assert front.stats["submits"] == 4
        # with 4 threads racing a 10ms dispatch, someone waited
        assert front.stats["queue_waits"] >= 1
        assert front.stats["queue_wait_s"] > 0.0


# =====================================================================
# sharded dynamic-port scan
# =====================================================================


class TestPortScanSharding:
    def test_default_base_is_bit_identical_ascending(self):
        ni = NetworkIndex()
        got = ni.claim_dynamic_block(3)
        assert got == [MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT + 1,
                       MIN_DYNAMIC_PORT + 2]

    def test_offset_base_starts_mid_range_and_wraps(self):
        base = MAX_DYNAMIC_PORT - 1
        set_dynamic_port_scan_base(base)
        ni = NetworkIndex()
        got = ni.claim_dynamic_block(4)
        assert got == [base, MAX_DYNAMIC_PORT,
                       MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT + 1]

    def test_assign_ports_respects_base(self):
        set_dynamic_port_scan_base(25000)
        ni = NetworkIndex()
        ask = [NetworkResource(dynamic_ports=[Port(label="http")])]
        ports, dim = ni.assign_ports(ask)
        assert dim == ""
        assert ports["http"] == 25000

    def test_disjoint_shards_never_collide(self):
        """Two 'processes' (simulated by switching the base) placing on
        the same empty node pick disjoint ports."""
        set_dynamic_port_scan_base(20000)
        a = NetworkIndex().claim_dynamic_block(16)
        set_dynamic_port_scan_base(26000)
        b = NetworkIndex().claim_dynamic_block(16)
        assert not set(a) & set(b)

    def test_rotating_mode_advances_past_commits(self):
        set_dynamic_port_scan_base(24000, rotate=True)
        first = NetworkIndex().claim_dynamic_block(4)
        assert first[0] == 24000
        # a FRESH index (stale-snapshot analogue: it has no idea the
        # first claim happened) still starts past the committed picks
        second = NetworkIndex().claim_dynamic_block(4)
        assert not set(first) & set(second)
        assert second[0] == 24004

    def test_non_rotating_mode_base_is_stable(self):
        set_dynamic_port_scan_base(24000, rotate=False)
        NetworkIndex().claim_dynamic_block(4)
        assert NetworkIndex().claim_dynamic_block(1) == [24000]

    def test_commit_advances_in_rotating_mode(self):
        set_dynamic_port_scan_base(24000, rotate=True)
        ni = NetworkIndex()
        ask = [NetworkResource(dynamic_ports=[Port(label="http")])]
        ports, _ = ni.assign_ports(ask)
        ni.commit(ports)
        assert NetworkIndex().claim_dynamic_block(1) == [24001]

    def test_dyn_free_count_unaffected_by_base(self):
        set_dynamic_port_scan_base(29000)
        ni = NetworkIndex()
        free0 = ni.dyn_free_count()
        assert free0 == MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
        ni.claim_dynamic_block(5)
        assert ni.dyn_free_count() == free0 - 5


# =====================================================================
# replica-staleness knobs
# =====================================================================


class TestReplicaKnobs:
    def test_thread_worker_keeps_reference_attempt_limits(self):
        from nomad_tpu.core.server import Server
        from nomad_tpu.scheduler.generic import (
            MAX_BATCH_ATTEMPTS, GenericScheduler)
        s = Server(dev_mode=True, num_workers=1)
        s.establish_leadership()
        try:
            worker = s.workers[0] if getattr(s, "workers", None) else None
            if worker is None:
                pytest.skip("dev-mode server exposes no worker list")
            assert getattr(worker, "schedule_attempt_boost", 0) == 0
            sched = GenericScheduler(s.state.snapshot(), worker,
                                     is_batch=True, engine=s.engine)
            assert sched.max_attempts == MAX_BATCH_ATTEMPTS
        finally:
            s.shutdown()

    def test_child_server_shim_boosts_attempts(self):
        from nomad_tpu.core.workerpool import _ChildServer
        assert _ChildServer.schedule_attempt_boost > 0


# =====================================================================
# packed-fill cap (pack/packer.py)
# =====================================================================


class TestPackedFillCap:
    def test_cap_is_the_20_bit_row_limit(self):
        from nomad_tpu.pack import packer as packer_mod
        assert packer_mod.PACKED_FILL_CAP == 1 << 20

    def test_oversized_cluster_raises_named_error(self, monkeypatch):
        from nomad_tpu.pack import packer as packer_mod
        monkeypatch.setattr(packer_mod, "PACKED_FILL_CAP", 4)
        store = StateStore()
        store.upsert_nodes([mock.node(name=f"n{i}") for i in range(4)])
        p = packer_mod.ClusterPacker()
        with pytest.raises(ValueError) as exc:
            p.build(store.snapshot())
        assert "PACKED_FILL_CAP" in str(exc.value)


# =====================================================================
# traffic knobs (chaos/traffic.py)
# =====================================================================


class TestTrafficKnobs:
    def test_networked_fraction_and_classes_are_deterministic(self):
        from nomad_tpu.chaos.traffic import (TrafficProfile, fleet,
                                             generate_schedule)
        prof = TrafficProfile(hours=0.5, networked_fraction=0.7,
                              node_classes=("edge", "core"))
        a = generate_schedule(1234, prof)
        b = generate_schedule(1234, prof)
        assert a == b
        ported = [e for e in a if e.get("ports")]
        assert ported, "0.7 networked_fraction produced no port asks"
        assert all(e.get("node_class") in ("edge", "core")
                   for e in ported)
        nodes = fleet(1234, prof)
        assert {n["node_class"] for n in nodes} == {"edge", "core"}

    def test_zero_knobs_do_not_consume_rng(self):
        from nomad_tpu.chaos.traffic import TrafficProfile, generate_schedule
        base = TrafficProfile(hours=0.5)
        off = TrafficProfile(hours=0.5, networked_fraction=0.0,
                             node_classes=())
        assert generate_schedule(77, base) == generate_schedule(77, off)


# =====================================================================
# spawn-based integration (slow: real worker processes)
# =====================================================================


def _build_cluster(n):
    nodes = []
    for i in range(n):
        nd = mock.node(name=f"pool-n{i}")
        nd.datacenter = f"dc{i % 3 + 1}"
        nodes.append(nd)
    return nodes


def _make_batch_job(count, net=False, zone_vol=None):
    job = mock.batch_job()
    job.datacenters = ["dc1", "dc2", "dc3"]
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = 10
    tg.tasks[0].resources.memory_mb = 10
    if zone_vol is not None:
        tg.volumes = {"data": VolumeRequest(
            name="data", type="csi", source=zone_vol, read_only=True)}
    if net:
        tg.tasks[0].resources.networks = [
            NetworkResource(dynamic_ports=[Port(label="http")])]
    return job


def _drain(server, evs, deadline_s=90.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        sts = [getattr(server.state.eval_by_id(e.id), "status", None)
               for e in evs]
        if all(st in ("complete", "failed", "canceled") for st in sts):
            return sts
        time.sleep(0.05)
    return [getattr(server.state.eval_by_id(e.id), "status", None)
            for e in evs]


@pytest.mark.slow
class TestProcessPoolIntegration:
    def _server(self, workers=2):
        from nomad_tpu.core.server import Server
        s = Server(dev_mode=False, num_workers=workers, eval_batch=8,
                   heartbeat_ttl=1e9, nack_timeout=600.0,
                   worker_mode="process", mesh=False)
        s.establish_leadership()
        return s

    def test_networked_waves_complete_without_refutes(self):
        s = self._server()
        try:
            s.state.upsert_nodes(_build_cluster(60))
            evs = [s.register_job(_make_batch_job(8, net=True),
                                  now=time.time())
                   for _ in range(6)]
            s.start_scheduling()
            sts = _drain(s, evs)
            s.stop_scheduling()
            assert sts == ["complete"] * len(evs), sts
            # exact placement count: 6 jobs x 8 allocs, none duplicated
            snap = s.state.snapshot()
            allocs = [a for n in snap.nodes()
                      for a in snap.allocs_by_node(n.id)
                      if not a.terminal_status()]
            assert len(allocs) == 48
            assert len({a.id for a in allocs}) == 48
            # every networked alloc carries a port; no (node, port) dup
            seen = set()
            for a in allocs:
                assert a.allocated_ports, a.id
                for port in a.allocated_ports.values():
                    key = (a.node_id, port)
                    assert key not in seen
                    seen.add(key)
            assert s.plan_applier.stats["plans_refuted"] == 0
            assert s.worker_pool.pool_stats()["alive"] == 2
        finally:
            s.shutdown()

    def test_worker_crash_recovers_and_respawns(self):
        s = self._server()
        try:
            s.state.upsert_nodes(_build_cluster(30))
            s.start_scheduling()
            # let the children finish coming up, then kill one
            deadline = time.time() + 60
            while (s.worker_pool.alive_workers() < 2
                   and time.time() < deadline):
                time.sleep(0.1)
            victim = s.worker_pool._children[0]
            victim.proc.terminate()
            victim.proc.join(timeout=30)
            evs = [s.register_job(_make_batch_job(4), now=time.time())
                   for _ in range(4)]
            sts = _drain(s, evs)
            s.stop_scheduling()
            assert sts == ["complete"] * len(evs), sts
            stats = s.worker_pool.pool_stats()
            assert stats["respawns"] >= 1
            assert stats["alive"] == 2
        finally:
            s.shutdown()

    def test_thread_mode_is_the_default_and_poolless(self):
        from nomad_tpu.core.server import Server
        s = Server(dev_mode=True, num_workers=2)
        try:
            assert s.worker_mode == "thread"
            assert s.worker_pool is None
        finally:
            s.shutdown()
