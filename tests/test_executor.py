"""Device-executor seam (nomad_tpu/ops/executor.py): backend selection
and validation, the retained resident-chain slot (claim/retain/
invalidate semantics, store-write coupling), and the telemetry meters
the seam exports.  The cross-backend bit-for-bit parity proof lives in
tests/test_wavepipe.py (TestExecutorResidentParity)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.core.telemetry import REGISTRY
from nomad_tpu.ops import PlacementEngine
from nomad_tpu.ops.executor import (
    EXECUTOR_BACKENDS,
    ExecutorUnavailable,
    JaxExecutor,
    make_executor,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Allocation, Resources

NOW = 1.7e9


def _engine():
    return PlacementEngine(mesh=False)


class TestMakeExecutor:
    def test_default_and_jax(self):
        eng = _engine()
        for name in ("", "jax"):
            ex = make_executor(name, eng)
            assert isinstance(ex, JaxExecutor)
            assert ex.name == "jax"
            assert ex.engine is eng

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="device_executor"):
            make_executor("cuda", _engine())

    def test_bridge_errors_when_unavailable(self):
        from nomad_tpu.native.bridge import bridge_available
        if bridge_available():
            pytest.skip("bridge available: covered by the parity suite")
        with pytest.raises(ExecutorUnavailable, match="bridge"):
            make_executor("bridge", _engine())

    def test_backends_registry(self):
        assert EXECUTOR_BACKENDS == ("jax", "bridge")

    def test_bridge_rejected_on_mesh_at_construction(self):
        """bridge + a multi-device engine is a CONFIG contradiction: it
        must fail as an agent_config validation error (ValueError, not
        ExecutorUnavailable) at make_executor time — i.e. at agent
        start — whether or not the native build exists."""
        import jax
        if jax.device_count() < 2:
            pytest.skip("needs the virtual multi-device mesh")
        eng = PlacementEngine()
        assert eng.mesh is not None
        with pytest.raises(ValueError, match="agent_config.*mesh"):
            make_executor("bridge", eng)


class TestAgentConfigKnob:
    def test_parse_and_default(self):
        from nomad_tpu.agent_config import AgentConfig, parse_agent_config
        assert AgentConfig().device_executor == "jax"
        cfg, fields = parse_agent_config(
            'server { device_executor = "bridge" }')
        assert cfg.device_executor == "bridge"
        assert "device_executor" in fields

    def test_invalid_value_rejected(self):
        from nomad_tpu.agent_config import parse_agent_config
        with pytest.raises(ValueError, match="device_executor"):
            parse_agent_config('server { device_executor = "cuda" }')


class TestChainSlot:
    def test_claim_pops_single_consumer(self):
        ex = JaxExecutor(_engine())
        triple = (object(), 1, 8)
        ex.retain_chain("bid", 3, triple, masked={"n1"})
        got = ex.claim_chain()
        assert got == ("bid", 3, triple, frozenset({"n1"}))
        assert ex.claim_chain() is None

    def test_chain_disabled_is_inert(self):
        ex = JaxExecutor(_engine(), chain_enabled=False)
        ex.retain_chain("bid", 3, (object(), 1, 8))
        assert ex.claim_chain() is None

    def test_invalidate_counts_only_real_drops(self):
        ex = JaxExecutor(_engine())
        ex.invalidate("noop")
        assert ex.stats["invalidations"] == 0
        ex.retain_chain("bid", 3, (object(), 1, 8))
        ex.invalidate("test")
        assert ex.stats["invalidations"] == 1
        assert ex.claim_chain() is None

    def test_foreign_plan_invalidates_own_does_not(self):
        ex = JaxExecutor(_engine())
        ex.retain_chain("bid", 3, (object(), 1, 8))
        ex.note_plan_commit("bid")            # the chain's own commit
        assert ex.stats["invalidations"] == 0
        ex.note_plan_commit("someone-else")   # foreign plan
        assert ex.stats["invalidations"] == 1
        assert ex.claim_chain() is None

    def test_store_writes_invalidate(self):
        store = StateStore()
        ex = JaxExecutor(_engine())
        ex.attach_store(store)

        # node write (register/drain/eligibility)
        ex.retain_chain("bid", 1, (object(), 1, 8))
        store.upsert_node(mock.node())
        assert ex.stats["invalidations"] == 1

        # capacity-freeing (terminal) alloc write
        ex.retain_chain("bid", 2, (object(), 1, 8))
        live = Allocation(id="a-live", namespace="default", job_id="j",
                          task_group="tg", node_id="n1",
                          resources=Resources(cpu=10, memory_mb=10),
                          desired_status="run", client_status="running")
        store.upsert_allocs([live])
        assert ex.stats["invalidations"] == 1, \
            "a live placement must NOT invalidate"
        done = live.copy()
        done.client_status = "complete"
        store.upsert_allocs([done])
        assert ex.stats["invalidations"] == 2

        # snapshot restore
        ex.retain_chain("bid", 3, (object(), 1, 8))
        store.snapshot_restore(store.snapshot_save())
        assert ex.stats["invalidations"] == 3


class TestServerWiring:
    def test_server_builds_and_wires_executor(self):
        s = Server(dev_mode=True, device_executor="jax")
        assert s.executor.name == "jax"
        assert s.executor.engine is s.engine
        assert s.plan_applier.executor is s.executor
        for w in s.workers:
            assert w.pipeline.executor is s.executor

    def test_server_rejects_unknown_backend(self):
        with pytest.raises(ValueError, match="device_executor"):
            Server(dev_mode=True, device_executor="cuda")

    def test_server_rejects_bridge_on_mesh_at_start(self):
        """The guard fires at SERVER CONSTRUCTION (agent start), never
        mid-worker-loop (ISSUE 7 satellite)."""
        import jax
        if jax.device_count() < 2:
            pytest.skip("needs the virtual multi-device mesh")
        with pytest.raises(ValueError, match="agent_config"):
            Server(dev_mode=True, device_executor="bridge")

    def test_residency_metrics_ride_the_registry(self):
        c0 = REGISTRY.counter("nomad.executor.resident_waves")
        u0 = REGISTRY.counter("nomad.executor.uploads")
        s = Server(dev_mode=True, eval_batch=4)
        s.establish_leadership()
        for _ in range(8):
            n = mock.node()
            n.resources.cpu = 8000
            n.resources.memory_mb = 16384
            s.register_node(n, now=NOW)
        for wave in range(2):
            for _ in range(4):
                job = mock.batch_job()
                job.task_groups[0].count = 8
                job.task_groups[0].tasks[0].resources.cpu = 50
                job.task_groups[0].tasks[0].resources.memory_mb = 16
                s.register_job(job, now=NOW)
            s.process_all(now=NOW)
        assert s.executor.stats["resident_waves"] >= 1
        assert REGISTRY.counter("nomad.executor.resident_waves") > c0
        assert REGISTRY.counter("nomad.executor.uploads") > u0
        assert REGISTRY.counter("nomad.executor.upload_bytes") > 0
        assert REGISTRY.histogram("nomad.executor.h2d_s") is not None

    def test_serial_vs_resident_same_aggregate_state(self):
        """The worker-loop A/B the bench's --resident flag runs: chain
        off (host round-trip every wave) and chain on land identical
        live-alloc counts with zero refutes."""
        def run(resident):
            s = Server(dev_mode=True, eval_batch=4)
            s.executor.chain_enabled = resident
            s.establish_leadership()
            for _ in range(8):
                n = mock.node()
                n.resources.cpu = 8000
                n.resources.memory_mb = 16384
                s.register_node(n, now=NOW)
            jobs = []
            for wave in range(3):
                for _ in range(4):
                    job = mock.batch_job()
                    job.task_groups[0].count = 8
                    job.task_groups[0].tasks[0].resources.cpu = 50
                    job.task_groups[0].tasks[0].resources.memory_mb = 16
                    s.register_job(job, now=NOW)
                    jobs.append(job)
                s.process_all(now=NOW)
            snap = s.state.snapshot()
            placed = sum(
                1 for j in jobs
                for a in snap.allocs_by_job(j.namespace, j.id)
                if not a.terminal_status())
            return placed, s.plan_applier.stats["plans_refuted"], \
                dict(s.executor.stats)

        placed_off, refuted_off, st_off = run(False)
        placed_on, refuted_on, st_on = run(True)
        assert placed_off == placed_on == 12 * 8
        assert refuted_off == refuted_on == 0
        assert st_off["resident_waves"] == 0
        assert st_on["resident_waves"] >= 1
