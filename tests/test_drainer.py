"""Node drain orchestration (reference: nomad/drainer/): batched release
via migrate.max_parallel, system-jobs-last ordering, deadline forcing,
drain completion."""

from nomad_tpu import mock
from nomad_tpu.core import Server
from nomad_tpu.structs import DrainStrategy, MigrateStrategy

NOW = 1000.0


def _setup(n_nodes=4, count=4, max_parallel=1):
    s = Server(dev_mode=True)
    s.establish_leadership()
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        s.register_node(n, now=NOW)
    job = mock.job()
    job.task_groups[0].count = count
    job.task_groups[0].migrate = MigrateStrategy(max_parallel=max_parallel)
    s.register_job(job, now=NOW)
    s.process_all(now=NOW)
    return s, nodes, job


def _live_on(s, job, node_id):
    return [a for a in s.state.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status() and a.node_id == node_id
            and a.desired_status == "run"]


def _finish_stops(s, job, now):
    """Simulate clients completing stopped allocs (client_status=complete)."""
    ups = []
    for a in s.state.allocs_by_job(job.namespace, job.id):
        if a.desired_status != "run" and not a.client_terminal_status():
            u = a.copy_skip_job()
            u.client_status = "complete"
            ups.append(u)
    if ups:
        s.state.update_allocs_from_client(ups)


class TestDrainBatching:
    def test_drain_releases_in_max_parallel_batches(self):
        s, nodes, job = _setup(n_nodes=4, count=4, max_parallel=1)
        # concentrate: find a node with >= 2 allocs, else drain the busiest
        by_node = {}
        for a in s.state.allocs_by_job(job.namespace, job.id):
            by_node.setdefault(a.node_id, []).append(a)
        victim = max(by_node, key=lambda k: len(by_node[k]))
        n_victim = len(by_node[victim])
        if n_victim < 2:
            # binpack normally stacks all four on one node; guard anyway
            assert n_victim >= 1

        s.drain_node(victim, DrainStrategy(deadline_s=3600), now=NOW + 1)
        s.process_all(now=NOW + 1)
        migrating = [a for a in s.state.allocs_by_job(job.namespace, job.id)
                     if a.desired_status != "run"
                     and not a.client_terminal_status()]
        assert len(migrating) == 1, \
            "only max_parallel=1 alloc released per batch"

        # old copy finishes -> next tick releases the next one
        _finish_stops(s, job, NOW + 2)
        s.tick(now=NOW + 2)
        s.process_all(now=NOW + 2)
        if n_victim >= 2:
            migrating = [a for a in
                         s.state.allocs_by_job(job.namespace, job.id)
                         if a.desired_status != "run"
                         and not a.client_terminal_status()]
            assert len(migrating) == 1

        # drive to completion
        for i in range(3, 20):
            _finish_stops(s, job, NOW + i)
            s.tick(now=NOW + i)
            s.process_all(now=NOW + i)
            if not _live_on(s, job, victim):
                break
        assert not _live_on(s, job, victim)
        node = s.state.node_by_id(victim)
        assert node.drain is None, "drain cleared on completion"
        assert node.scheduling_eligibility == "ineligible"
        live = [a for a in s.state.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status() and a.desired_status == "run"]
        assert len(live) == 4, "all allocs migrated elsewhere"
        assert all(a.node_id != victim for a in live)

    def test_deadline_forces_all_remaining(self):
        s, nodes, job = _setup(n_nodes=4, count=4, max_parallel=1)
        by_node = {}
        for a in s.state.allocs_by_job(job.namespace, job.id):
            by_node.setdefault(a.node_id, []).append(a)
        victim = max(by_node, key=lambda k: len(by_node[k]))
        s.drain_node(victim, DrainStrategy(deadline_s=10), now=NOW + 1)
        s.process_all(now=NOW + 1)
        # past the deadline: everything left on the node is released
        s.tick(now=NOW + 20)
        s.process_all(now=NOW + 20)
        assert not _live_on(s, job, victim)

    def test_system_allocs_drain_last(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = [mock.node() for _ in range(2)]
        for n in nodes:
            s.register_node(n, now=NOW)
        sysjob = mock.system_job()
        s.register_job(sysjob, now=NOW)
        svc = mock.job()
        svc.task_groups[0].count = 1
        svc.task_groups[0].migrate = MigrateStrategy(max_parallel=1)
        s.register_job(svc, now=NOW)
        s.process_all(now=NOW)

        victim = next(a.node_id for a in
                      s.state.allocs_by_job(svc.namespace, svc.id))
        s.drain_node(victim, DrainStrategy(deadline_s=3600), now=NOW + 1)
        s.process_all(now=NOW + 1)
        # system alloc still running while the service alloc migrates
        assert _live_on(s, sysjob, victim), "system alloc drains last"

        _finish_stops(s, svc, NOW + 2)
        s.tick(now=NOW + 2)
        s.process_all(now=NOW + 2)
        assert not _live_on(s, sysjob, victim), \
            "system alloc released once service allocs are gone"

    def test_ignore_system_jobs(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(2):
            s.register_node(mock.node(), now=NOW)
        sysjob = mock.system_job()
        s.register_job(sysjob, now=NOW)
        s.process_all(now=NOW)
        victim = next(a.node_id for a in
                      s.state.allocs_by_job(sysjob.namespace, sysjob.id))
        s.drain_node(victim,
                     DrainStrategy(deadline_s=3600, ignore_system_jobs=True),
                     now=NOW + 1)
        s.tick(now=NOW + 2)
        s.process_all(now=NOW + 2)
        assert _live_on(s, sysjob, victim), "ignored system alloc untouched"
        # drain still completes (nothing else drainable)
        assert s.state.node_by_id(victim).drain is None
        # a later system eval must NOT stop the preserved alloc just
        # because the drained node is now merely ineligible
        s.apply_eval_update(
            [mock.eval(job_id=sysjob.id, type="system",
                       triggered_by="node-update")], now=NOW + 3)
        s.process_all(now=NOW + 3)
        assert _live_on(s, sysjob, victim), \
            "system alloc survives evals on the ineligible node"

    def test_eligibility_restore_cancels_lingering_drain(self):
        # The drainer clears a finished drain's marker lazily, on its next
        # tick.  An operator restoring eligibility inside that window must
        # not leave the node drain-flagged (ready_nodes skips draining
        # nodes, so the restore's node-update evals would no-op and the
        # node would never host a system alloc again).
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(2):
            s.register_node(mock.node(), now=NOW)
        sysjob = mock.system_job()
        s.register_job(sysjob, now=NOW)
        s.process_all(now=NOW)
        victim = next(a.node_id for a in
                      s.state.allocs_by_job(sysjob.namespace, sysjob.id))
        s.drain_node(victim, DrainStrategy(deadline_s=3600), now=NOW + 1)
        s.process_all(now=NOW + 1)
        _finish_stops(s, sysjob, NOW + 2)
        assert not _live_on(s, sysjob, victim)
        # no tick between completion and restore: marker still set
        assert s.state.node_by_id(victim).drain is not None
        s.set_node_eligibility(victim, True)
        s.process_all(now=NOW + 3)
        node = s.state.node_by_id(victim)
        assert node.drain is None, "restore cancelled the lingering drain"
        assert node.scheduling_eligibility == "eligible"
        assert _live_on(s, sysjob, victim), \
            "restored node regained its system alloc"
