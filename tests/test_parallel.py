"""Sharded placement tests on the 8-device virtual CPU mesh.

Verifies the two-stage top-k / psum'd count-state design produces the SAME
decisions as the single-device kernel."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nomad_tpu import mock
from nomad_tpu.ops import PlacementRequest
from nomad_tpu.ops.select import PlacementInputs, place_jit
from nomad_tpu.pack import ClusterPacker, lower_spreads
from nomad_tpu.parallel import make_mesh, pad_nodes, place_sharded_fn
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import Constraint, Spread, SpreadTarget


def build_inputs(n_nodes=16, count=12, spread=True, pad_to=None):
    h = Harness()
    for i in range(n_nodes):
        n = mock.node(datacenter=f"dc{i % 3 + 1}")
        n.meta = {"rack": f"r{i % 4}"}
        h.state.upsert_node(n)
    job = mock.job()
    job.datacenters = ["dc1", "dc2", "dc3"]
    if spread:
        job.spreads = [Spread(attribute="${node.datacenter}", weight=100,
                              targets=(SpreadTarget("dc1", 50),
                                       SpreadTarget("dc2", 30),
                                       SpreadTarget("dc3", 20)))]
    job.constraints.append(Constraint("${meta.rack}", "distinct_property", "99"))
    job.task_groups[0].count = count
    h.state.upsert_job(job)
    snap = h.snapshot()

    packer = ClusterPacker()
    t = packer.build(snap)
    tgt = packer.lower_task_groups(job, job.task_groups)
    ctx = packer.job_context(job, snap, t)
    sp = lower_spreads(packer, job, t, snap)
    pd = packer.lower_distinct(job, job.task_groups, tgt, t, snap)

    n = t.n
    n_pad = pad_to or n
    def padn(a, fill=0):
        if a.shape[0] == n_pad:
            return a
        pad = np.full((n_pad - a.shape[0],) + a.shape[1:], fill, a.dtype)
        return np.concatenate([a, pad], axis=0)
    def padcols(a, fill):
        if a.shape[1] == n_pad:
            return a
        pad = np.full(a.shape[:1] + (n_pad - a.shape[1],), fill, a.dtype)
        return np.concatenate([a, pad], axis=1)

    p = count
    inp = PlacementInputs(
        attrs=jnp.asarray(padn(t.attrs, -1)),
        cap=jnp.asarray(padn(t.cap)),
        used0=jnp.asarray(padn(t.used)),
        elig=jnp.asarray(padn(t.elig.astype(bool), False)),
        dc_mask=jnp.asarray(padn(ctx.dc_mask, False)),
        pool_mask=jnp.asarray(padn(ctx.pool_mask, False)),
        luts=jnp.asarray(tgt.luts),
        con=jnp.asarray(tgt.con),
        aff=jnp.asarray(tgt.aff),
        req=jnp.asarray(tgt.req),
        desired=jnp.asarray(np.array([tg.count for tg in job.task_groups],
                                     np.int32)),
        dh_limit=jnp.asarray(tgt.dh_limit),
        sp_nodeval=jnp.asarray(padcols(sp.sp_nodeval, -1)),
        sp_weight=jnp.asarray(sp.sp_weight),
        sp_expected=jnp.asarray(sp.sp_expected),
        sp_counts0=jnp.asarray(sp.sp_counts0),
        pd_nodeval=jnp.asarray(padcols(pd.pd_nodeval, -1)),
        pd_limit=jnp.asarray(pd.pd_limit),
        pd_apply=jnp.asarray(pd.pd_apply),
        pd_counts0=jnp.asarray(pd.pd_counts0),
        tg_idx=jnp.zeros(p, jnp.int32),
        prev_row=jnp.full(p, -1, jnp.int32),
        active=jnp.ones(p, bool),
        job_count0=jnp.asarray(padn(ctx.job_count)),
        spread_algo=jnp.asarray(False),
    )
    return h, t, inp


class TestShardedPlacement:
    def test_eight_devices_available(self):
        assert len(jax.devices()) >= 8

    def test_sharded_matches_single_device(self):
        mesh = make_mesh(8)
        n_pad = pad_nodes(16, 8)
        h, t, inp = build_inputs(n_nodes=16, count=12, pad_to=n_pad)
        single = place_jit(inp)
        sharded = place_sharded_fn(mesh)(inp)
        assert (np.asarray(single.picks) >= 0).all()   # non-trivial scenario
        np.testing.assert_array_equal(np.asarray(single.picks),
                                      np.asarray(sharded.picks))
        np.testing.assert_allclose(np.asarray(single.scores),
                                   np.asarray(sharded.scores), atol=1e-5)
        np.testing.assert_array_equal(np.asarray(single.n_feasible),
                                      np.asarray(sharded.n_feasible))
        np.testing.assert_array_equal(np.asarray(single.n_filtered),
                                      np.asarray(sharded.n_filtered))
        # final usage: sharded output is globally identical once gathered
        np.testing.assert_array_equal(np.asarray(single.used),
                                      np.asarray(sharded.used))

    def test_sharded_spread_distribution(self):
        mesh = make_mesh(8)
        n_pad = pad_nodes(12, 8)
        h, t, inp = build_inputs(n_nodes=12, count=10, pad_to=n_pad)
        out = place_sharded_fn(mesh)(inp)
        picks = np.asarray(out.picks)
        assert (picks >= 0).all()
        dcs = {}
        snap = h.snapshot()
        for row in picks:
            dc = snap.node_by_id(t.node_ids[int(row)]).datacenter
            dcs[dc] = dcs.get(dc, 0) + 1
        assert dcs == {"dc1": 5, "dc2": 3, "dc3": 2}

    def test_padding_rows_never_picked(self):
        mesh = make_mesh(8)
        h, t, inp = build_inputs(n_nodes=10, count=8, pad_to=16)
        out = place_sharded_fn(mesh)(inp)
        picks = np.asarray(out.picks)
        assert (picks < 10).all()   # rows 10..15 are padding
