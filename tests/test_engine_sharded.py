"""Engine-level sharded-vs-single-device parity (SURVEY §7 P7).

The conftest forces 8 virtual CPU devices, so PlacementEngine() auto-builds
a node-axis mesh — THE production multi-device path.  These tests pin that
the full engine (packing, padding, caches, unpack) produces the same Plans
sharded as single-device (`mesh=False`) at realistic node counts, for all
three kernels: exact scan, bulk water-fill, and the multi-eval batch.
"""

import random

import jax
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import PlacementEngine
from nomad_tpu.ops.engine import BatchItem
from nomad_tpu.scheduler import Harness

pytestmark = pytest.mark.skipif(
    jax.device_count() < 2, reason="needs the virtual multi-device mesh")


def build(n_nodes, seed=0):
    rng = random.Random(seed)
    h = Harness()
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = f"dc{1 + i % 3}"
        n.attributes["platform.rack"] = f"r{i % 20}"
        n.resources.cpu = rng.choice([4000, 8000, 16000])
        n.resources.memory_mb = rng.choice([8192, 16384, 32768])
        nodes.append(n)
    h.state.upsert_nodes(nodes)
    return h


def engines():
    sharded = PlacementEngine()
    single = PlacementEngine(mesh=False)
    assert sharded.mesh is not None
    assert single.mesh is None
    return sharded, single


class TestShardedEngineParity:
    def test_bulk_plan_parity_5k_nodes(self):
        h = build(5000)
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 2000
        tg.tasks[0].resources.cpu = 100
        tg.tasks[0].resources.memory_mb = 64
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        assert sharded is not None
        bd_s = sharded.place(snap, job, job.task_groups, None,
                             bulk_api=True, seed=13,
                             block=(tg.name, 2000))
        bd_1 = single.place(snap, job, job.task_groups, None,
                            bulk_api=True, seed=13,
                            block=(tg.name, 2000))
        assert np.array_equal(np.sort(bd_s.picks), np.sort(bd_1.picks))
        for m_s, m_1 in zip(bd_s.metrics, bd_1.metrics):
            assert m_s.nodes_filtered == m_1.nodes_filtered
            assert m_s.nodes_exhausted == m_1.nodes_exhausted
            assert m_s.nodes_evaluated == m_1.nodes_evaluated == 5000

    def test_scan_plan_parity_spread_job(self):
        from nomad_tpu.structs import Affinity, OP_EQ, Spread, SpreadTarget
        h = build(1200, seed=7)
        job = mock.job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 90
        tg.tasks[0].resources.cpu = 50
        tg.tasks[0].resources.memory_mb = 32
        job.spreads = [Spread(attribute="${node.datacenter}", weight=50,
                              targets=[SpreadTarget("dc1", 50),
                                       SpreadTarget("dc2", 30),
                                       SpreadTarget("dc3", 20)])]
        job.affinities = [Affinity("${attr.platform.rack}", OP_EQ, "r3",
                                   weight=50)]
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        from nomad_tpu.ops import PlacementRequest
        reqs = [PlacementRequest(tg_name=tg.name)] * 90
        d_s = sharded.place(snap, job, job.task_groups, reqs, seed=13)
        d_1 = single.place(snap, job, job.task_groups, reqs, seed=13)
        picks_s = [d.node_id for d in d_s]
        picks_1 = [d.node_id for d in d_1]
        # spread state updates sequentially: order-exact parity expected
        assert picks_s == picks_1
        for a, b in zip(d_s, d_1):
            assert abs(a.score - b.score) < 1e-5
            assert a.metric.nodes_filtered == b.metric.nodes_filtered

    def test_multi_eval_batch_parity(self):
        h = build(3000, seed=5)
        jobs = []
        for i in range(8):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = [150, 40, 700, 5, 260, 90, 1, 330][i]
            tg.tasks[0].resources.cpu = 80
            tg.tasks[0].resources.memory_mb = 48
            h.state.upsert_job(job)
            jobs.append(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        items = [BatchItem(job=j, tg=j.task_groups[0],
                           count=j.task_groups[0].count) for j in jobs]
        ds = sharded.place_batch(snap, items, seed=21)
        d1 = single.place_batch(snap, items, seed=21)
        for a, b in zip(ds, d1):
            assert np.array_equal(np.sort(a.picks), np.sort(b.picks))

    def test_full_scheduler_on_mesh_engine(self):
        """End-to-end: Harness scheduling through the auto-mesh engine
        produces a valid complete plan (the whole suite also runs on the
        mesh via conftest; this pins the explicit contrast)."""
        sharded, single = engines()
        for eng, h2 in ((sharded, build(500)), (single, build(500))):
            job = mock.job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.task_groups[0].count = 40
            e = mock.eval(job_id=job.id, type="service")
            h2.state.upsert_job(job)
            h2.state.upsert_evals([e])
            err = h2.process("service", e, now=1.7e9, engine=eng)
            assert err is None
            placed = sum(len(a) for a in
                         h2.plans[-1].node_allocation.values())
            assert placed == 40
