"""Engine-level sharded-vs-single-device parity (SURVEY §7 P7).

The conftest forces 8 virtual CPU devices, so PlacementEngine() auto-builds
a node-axis mesh — THE production multi-device path.  These tests pin that
the full engine (packing, padding, caches, unpack) produces the same Plans
sharded as single-device (`mesh=False`) at realistic node counts, for all
three kernels: exact scan, bulk water-fill, and the multi-eval batch.
"""

import random

import jax
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import PlacementEngine
from nomad_tpu.ops.engine import BatchItem
from nomad_tpu.scheduler import Harness

pytestmark = pytest.mark.skipif(
    jax.device_count() < 2, reason="needs the virtual multi-device mesh")


def build(n_nodes, seed=0):
    rng = random.Random(seed)
    h = Harness()
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = f"dc{1 + i % 3}"
        n.attributes["platform.rack"] = f"r{i % 20}"
        n.resources.cpu = rng.choice([4000, 8000, 16000])
        n.resources.memory_mb = rng.choice([8192, 16384, 32768])
        nodes.append(n)
    h.state.upsert_nodes(nodes)
    return h


def engines():
    sharded = PlacementEngine()
    single = PlacementEngine(mesh=False)
    assert sharded.mesh is not None
    assert single.mesh is None
    return sharded, single


class TestShardedEngineParity:
    def test_bulk_plan_parity_5k_nodes(self):
        h = build(5000)
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 2000
        tg.tasks[0].resources.cpu = 100
        tg.tasks[0].resources.memory_mb = 64
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        assert sharded is not None
        bd_s = sharded.place(snap, job, job.task_groups, None,
                             bulk_api=True, seed=13,
                             block=(tg.name, 2000))
        bd_1 = single.place(snap, job, job.task_groups, None,
                            bulk_api=True, seed=13,
                            block=(tg.name, 2000))
        assert np.array_equal(np.sort(bd_s.picks), np.sort(bd_1.picks))
        for m_s, m_1 in zip(bd_s.metrics, bd_1.metrics):
            assert m_s.nodes_filtered == m_1.nodes_filtered
            assert m_s.nodes_exhausted == m_1.nodes_exhausted
            assert m_s.nodes_evaluated == m_1.nodes_evaluated == 5000

    def test_scan_plan_parity_spread_job(self):
        from nomad_tpu.structs import Affinity, OP_EQ, Spread, SpreadTarget
        h = build(1200, seed=7)
        job = mock.job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 90
        tg.tasks[0].resources.cpu = 50
        tg.tasks[0].resources.memory_mb = 32
        job.spreads = [Spread(attribute="${node.datacenter}", weight=50,
                              targets=[SpreadTarget("dc1", 50),
                                       SpreadTarget("dc2", 30),
                                       SpreadTarget("dc3", 20)])]
        job.affinities = [Affinity("${attr.platform.rack}", OP_EQ, "r3",
                                   weight=50)]
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        from nomad_tpu.ops import PlacementRequest
        reqs = [PlacementRequest(tg_name=tg.name)] * 90
        d_s = sharded.place(snap, job, job.task_groups, reqs, seed=13)
        d_1 = single.place(snap, job, job.task_groups, reqs, seed=13)
        picks_s = [d.node_id for d in d_s]
        picks_1 = [d.node_id for d in d_1]
        # spread state updates sequentially: order-exact parity expected
        assert picks_s == picks_1
        for a, b in zip(d_s, d_1):
            assert abs(a.score - b.score) < 1e-5
            assert a.metric.nodes_filtered == b.metric.nodes_filtered

    def test_multi_eval_batch_parity(self):
        h = build(3000, seed=5)
        jobs = []
        for i in range(8):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = [150, 40, 700, 5, 260, 90, 1, 330][i]
            tg.tasks[0].resources.cpu = 80
            tg.tasks[0].resources.memory_mb = 48
            h.state.upsert_job(job)
            jobs.append(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        items = [BatchItem(job=j, tg=j.task_groups[0],
                           count=j.task_groups[0].count) for j in jobs]
        ds = sharded.place_batch(snap, items, seed=21)
        d1 = single.place_batch(snap, items, seed=21)
        for a, b in zip(ds, d1):
            assert np.array_equal(np.sort(a.picks), np.sort(b.picks))

    def test_padded_rows_never_picked(self):
        """N % n_devices != 0: the engine pads the node axis to a mesh
        multiple with INELIGIBLE rows.  Oversubscribe the cluster so the
        kernel would love extra capacity — every pick must still be a
        real node row, and the padded rows must not leak into the
        filtered-node metrics."""
        h = build(13, seed=3)           # 13 % 8 != 0 -> 3 padded rows
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 400                  # far beyond 13 nodes' capacity
        tg.tasks[0].resources.cpu = 2000
        tg.tasks[0].resources.memory_mb = 1024
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        sharded, single = engines()
        bd_s = sharded.place(snap, job, job.task_groups, None,
                             bulk_api=True, seed=5, block=(tg.name, 400))
        bd_1 = single.place(snap, job, job.task_groups, None,
                            bulk_api=True, seed=5, block=(tg.name, 400))
        picks = bd_s.picks
        placed = picks[picks >= 0]
        assert placed.size > 0
        assert placed.max() < 13, "placed onto a padded row"
        assert np.array_equal(np.sort(picks), np.sort(bd_1.picks))
        for m_s, m_1 in zip(bd_s.metrics, bd_1.metrics):
            # padding rows subtracted: filtered counts match single-dev
            assert m_s.nodes_filtered == m_1.nodes_filtered
            assert m_s.nodes_evaluated == 13

    def test_padded_rows_after_gc_shrink_across_shard(self):
        """Node GC shrinks N across a shard boundary (13 -> 7 on an
        8-device mesh: npad 16 -> 8, every row remaps): the rebuilt
        sharded table must still never place onto padding and must stay
        pick-identical to the single-device engine."""
        h = build(13, seed=9)
        sharded, single = engines()

        def place_all(count, seed):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = count
            tg.tasks[0].resources.cpu = 1000
            tg.tasks[0].resources.memory_mb = 512
            h.state.upsert_job(job)
            snap = h.state.snapshot()
            bd_s = sharded.place(snap, job, job.task_groups, None,
                                 bulk_api=True, seed=seed,
                                 block=(tg.name, count))
            bd_1 = single.place(snap, job, job.task_groups, None,
                                bulk_api=True, seed=seed,
                                block=(tg.name, count))
            return bd_s, bd_1

        bd_s, bd_1 = place_all(80, seed=2)
        assert np.array_equal(np.sort(bd_s.picks), np.sort(bd_1.picks))
        # GC 6 nodes -> 7 remain (crosses the 8-row shard boundary)
        snap = h.state.snapshot()
        for nd in snap.nodes()[7:]:
            h.state.delete_node(nd.id)
        bd_s, bd_1 = place_all(80, seed=4)
        picks = bd_s.picks
        placed = picks[picks >= 0]
        assert placed.size > 0
        assert placed.max() < 7, "placed onto a padded row after GC"
        assert np.array_equal(np.sort(picks), np.sort(bd_1.picks))
        assert bd_s.metrics[0].nodes_evaluated == 7

    def test_dirty_shard_patch_uploads_one_shard(self):
        """A single node's eligibility write must re-upload only the
        SHARD holding that node's row (packer row-dirty log -> engine
        _patch_node_shards), not every node tensor — and the patched
        table must stay pick-identical to a fresh single-device
        engine."""
        h = build(64, seed=11)
        sharded = PlacementEngine()
        assert sharded.mesh is not None
        sharded.packer.attach(h.state)
        h2d = {"bytes": 0}
        sharded.h2d_observer = \
            lambda nb, s, cause: h2d.__setitem__("bytes",
                                                 h2d["bytes"] + nb)

        def place(seed):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = 80
            tg.tasks[0].resources.cpu = 100
            tg.tasks[0].resources.memory_mb = 64
            h.state.upsert_job(job)
            snap = h.state.snapshot()
            return job, snap

        job, snap = place(1)
        sharded.place(snap, job, job.task_groups, None, bulk_api=True,
                      seed=1, block=(job.task_groups[0].name, 80))
        full_bytes = h2d["bytes"]
        assert full_bytes > 0
        shard_b0 = sharded.shard_h2d_bytes

        # one node write -> one dirty shard
        nid = h.state.snapshot().nodes()[0].id
        h.state.update_node_eligibility(nid, "ineligible")
        h2d["bytes"] = 0
        job, snap = place(2)
        bd_s = sharded.place(snap, job, job.task_groups, None,
                             bulk_api=True, seed=2,
                             block=(job.task_groups[0].name, 80))
        assert sharded.shard_h2d_bytes > shard_b0, \
            "dirty-shard patch never engaged"
        # the re-sync moved one shard (1/8th of the rows), not the
        # whole table: generous 2x slack for the used-tensor heal
        assert h2d["bytes"] <= 2 * (full_bytes // 8) + 256, \
            (h2d["bytes"], full_bytes)
        single = PlacementEngine(mesh=False)
        bd_1 = single.place(snap, job, job.task_groups, None,
                            bulk_api=True, seed=2,
                            block=(job.task_groups[0].name, 80))
        assert np.array_equal(np.sort(bd_s.picks), np.sort(bd_1.picks))
        # the drained node is gone from both engines' picks
        row = 0
        assert row not in bd_s.picks.tolist()

    def test_full_scheduler_on_mesh_engine(self):
        """End-to-end: Harness scheduling through the auto-mesh engine
        produces a valid complete plan (the whole suite also runs on the
        mesh via conftest; this pins the explicit contrast)."""
        sharded, single = engines()
        for eng, h2 in ((sharded, build(500)), (single, build(500))):
            job = mock.job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.task_groups[0].count = 40
            e = mock.eval(job_id=job.id, type="service")
            h2.state.upsert_job(job)
            h2.state.upsert_evals([e])
            err = h2.process("service", e, now=1.7e9, engine=eng)
            assert err is None
            placed = sum(len(a) for a in
                         h2.plans[-1].node_allocation.values())
            assert placed == 40
