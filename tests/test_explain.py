"""Placement explainability (ISSUE 5): eval decision records and their
bounded ring, the `/v1/eval/<id>/explain` and
`/v1/job/<id>/placement-failures` surfaces, `PlacementFailure` event
delivery + replay, the CLI renderings, and the live scheduling-quality
gauges exported through the Prometheus endpoint."""

import time

import pytest

from nomad_tpu import cli, mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.core.explain import (
    blocked_cause,
    explain_doc,
    failure_rollup,
    placement_failures_doc,
)
from nomad_tpu.core.plan_apply import publish_quality
from nomad_tpu.core.telemetry import MetricsRegistry
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.state import StateStore
from nomad_tpu.structs import AllocMetric, EvalDecision, Evaluation, codec


def _wait(fn, timeout=60, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


# ------------------------------------------------------- rollup helpers


class TestRollups:
    def test_failure_rollup_names_exhausted_dimension_first(self):
        m = AllocMetric(nodes_evaluated=5, nodes_filtered=2,
                        nodes_exhausted=3,
                        dimension_exhausted={"memory": 3},
                        constraint_filtered={"missing drivers": 2})
        s = failure_rollup(m)
        assert "memory" in s and "missing drivers" in s
        assert s.index("memory") < s.index("missing drivers")

    def test_failure_rollup_filter_only(self):
        m = AllocMetric(nodes_evaluated=4, nodes_filtered=4)
        assert "4 of 4" in failure_rollup(m)

    def test_failure_rollup_empty_cluster(self):
        assert "no nodes" in failure_rollup(AllocMetric())

    def test_blocked_cause_joins_task_groups(self):
        cause = blocked_cause({
            "web": AllocMetric(dimension_exhausted={"cpu": 1},
                               nodes_exhausted=1),
            "db": AllocMetric(nodes_filtered=2, nodes_evaluated=2),
        })
        assert "web:" in cause and "db:" in cause


# ------------------------------------------------------- decision ring


class TestDecisionRing:
    def test_ring_bounds_and_evicts_oldest(self):
        st = StateStore()
        st._eval_decision_cap = 8
        for i in range(20):
            st.record_eval_decision(EvalDecision(eval_id=f"e{i}"))
        assert st.eval_decision("e0") is None
        assert st.eval_decision("e19") is not None
        assert len(st.eval_decisions()) == 8

    def test_rerecord_refreshes_position(self):
        st = StateStore()
        st._eval_decision_cap = 4
        for i in range(4):
            st.record_eval_decision(EvalDecision(eval_id=f"e{i}"))
        st.record_eval_decision(EvalDecision(eval_id="e0"))   # refresh
        for i in range(3):
            st.record_eval_decision(EvalDecision(eval_id=f"f{i}"))
        assert st.eval_decision("e0") is not None    # survived as newest
        assert st.eval_decision("e1") is None

    def test_filtered_listing(self):
        st = StateStore()
        st.record_eval_decision(EvalDecision(eval_id="a", job_id="j1"))
        st.record_eval_decision(EvalDecision(eval_id="b", job_id="j2"))
        assert [d.eval_id for d in st.eval_decisions(job_id="j2")] == ["b"]


# ------------------------------------------- scheduler capture (harness)


class TestSchedulerCapture:
    def _harness(self, n_nodes=3):
        h = Harness()
        for _ in range(n_nodes):
            h.state.upsert_node(mock.node())
        return h

    def test_placed_eval_records_counts_and_score_table(self):
        h = self._harness()
        job = mock.job()
        job.task_groups[0].count = 2
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type=job.type)
        assert h.process("service", ev) is None
        d = h.state.eval_decision(ev.id)
        assert d is not None and d.status == "complete"
        tg = d.task_groups["web"]
        assert tg.placed == 2 and tg.failed == 0
        # the top-k table the kernel already materialized travels along
        assert tg.score_meta and tg.score_meta[0].node_id
        assert tg.metric.nodes_evaluated == 3

    def test_unplaceable_eval_names_blocking_dimension(self):
        h = self._harness()
        job = mock.job()
        job.id = "huge"
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.memory_mb = 1 << 24
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type=job.type)
        assert h.process("service", ev) is None
        d = h.state.eval_decision(ev.id)
        tg = d.task_groups["web"]
        assert tg.placed == 0 and tg.failed == 1
        assert "memory" in d.blocked_cause
        # a blocked eval was minted and linked on the decision
        assert h.create_evals and h.create_evals[-1].status == "blocked"
        assert d.blocked_eval == h.create_evals[-1].id
        # wire doc: the breakdown identifies the blocking dimension
        doc = explain_doc(h.evals[-1], d)
        m = doc["TaskGroups"]["web"]["Metric"]
        assert m["DimensionExhausted"].get("memory", 0) >= 1
        assert m["NodesEvaluated"] == 3

    def test_system_scheduler_records_decision(self):
        h = self._harness()
        job = mock.system_job()
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type="system")
        assert h.process("system", ev) is None
        d = h.state.eval_decision(ev.id)
        assert d is not None
        tg = d.task_groups[job.task_groups[0].name]
        assert tg.placed == 3 and tg.desired == 3

    def test_explain_doc_synthesizes_without_ring_record(self):
        """Ring evicted (restart/follower): the stored eval's rollups
        still explain the failure."""
        h = self._harness()
        job = mock.job()
        job.task_groups[0].tasks[0].resources.memory_mb = 1 << 24
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type=job.type)
        h.process("service", ev)
        doc = explain_doc(h.evals[-1], None)
        assert doc["DecisionRecorded"] is False
        assert "memory" in doc["TaskGroups"]["web"]["Cause"]

    def test_placement_failures_doc_prefers_blocked_eval(self):
        h = self._harness()
        job = mock.job()
        job.task_groups[0].tasks[0].resources.memory_mb = 1 << 24
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type=job.type)
        h.process("service", ev)
        evals = list(h.evals) + list(h.create_evals)
        pf = placement_failures_doc(job.id, "default", evals)
        assert pf["Blocked"] is True
        tg = pf["TaskGroups"]["web"]
        assert tg["DimensionExhausted"].get("memory", 0) >= 1
        assert tg["Cause"]


# --------------------------------------------------- quality ledger/gauges


class TestQualityLedger:
    def _place(self, h, count=2):
        job = mock.job()
        job.task_groups[0].count = count
        h.state.upsert_job(job)
        ev = Evaluation(job_id=job.id, type=job.type)
        assert h.process("service", ev) is None
        return job

    def test_ledger_tracks_placements_and_terminal_transitions(self):
        h = Harness()
        for _ in range(3):
            h.state.upsert_node(mock.node())
        job = self._place(h)
        q = h.state.quality_summary()
        assert q["nodes_in_use"] >= 1
        assert q["zone_allocs_max"] + q["zone_allocs_min"] > 0
        assert 0 < q["fill_memory"] <= 1
        # terminal transitions release the ledger
        for a in h.state.allocs_by_job("default", job.id):
            stop = a.copy_skip_job()
            stop.client_status = "complete"
            h.state.upsert_allocs([stop])
        q2 = h.state.quality_summary()
        assert q2["nodes_in_use"] == 0
        assert q2["fill_memory"] == 0.0

    def test_ledger_rebuilt_on_snapshot_restore(self):
        h = Harness()
        for _ in range(3):
            h.state.upsert_node(mock.node())
        self._place(h)
        q = h.state.quality_summary()
        st2 = StateStore()
        st2.snapshot_restore(h.state.snapshot_save())
        q2 = st2.quality_summary()
        assert q2["nodes_in_use"] == q["nodes_in_use"]
        assert q2["fill_cpu"] == pytest.approx(q["fill_cpu"])

    def test_publish_quality_sets_gauges(self):
        h = Harness()
        for _ in range(2):
            h.state.upsert_node(mock.node())
        self._place(h)
        reg = MetricsRegistry()
        publish_quality(h.state, registry=reg)
        gauges = reg.snapshot()["gauges"]
        assert gauges["nomad.quality.nodes_in_use"] >= 1
        assert "nomad.quality.zone_balance_max_over_min" in gauges
        assert gauges['nomad.quality.binpack_fill{dimension=memory}'] > 0


# ------------------------------------------------------------ end to end


@pytest.fixture(scope="module")
def agent():
    ag = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600)
    ag.start()
    yield ag
    ag.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(address=agent.address)


def _register_unplaceable(api):
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].resources.memory_mb = 1 << 24
    resp = api.jobs.register(codec.encode(job))
    assert resp["EvalID"]
    return job, resp["EvalID"]


class TestEndToEnd:
    def test_explain_http_roundtrip(self, api):
        job, eval_id = _register_unplaceable(api)

        def settled():
            doc = api.evaluations.explain(eval_id)
            return doc if doc.get("BlockedEval") else None

        doc = _wait(settled, timeout=30)
        assert doc, "eval never produced a blocked eval"
        assert doc["DecisionRecorded"] is True
        tg = doc["TaskGroups"][job.task_groups[0].name]
        assert tg["Failed"] >= 1
        assert tg["Metric"]["DimensionExhausted"].get("memory", 0) >= 1
        assert "memory" in tg["Cause"]
        # the blocked eval explains too — synthesized from the failure
        # rollups it carries in state (no ring record needed)
        bdoc = api.evaluations.explain(doc["BlockedEval"])
        assert bdoc["Status"] == "blocked"
        assert "memory" in bdoc["BlockedCause"]

    def test_job_placement_failures_endpoint(self, api):
        job, _ = _register_unplaceable(api)

        def pending():
            pf = api.jobs.placement_failures(job.id)
            return pf if pf.get("TaskGroups") else None

        pf = _wait(pending, timeout=30)
        assert pf and pf["Blocked"] is True
        tg = pf["TaskGroups"][job.task_groups[0].name]
        assert tg["DimensionExhausted"].get("memory", 0) >= 1
        assert tg["NodesEvaluated"] >= 1
        assert "memory" in pf["Cause"]

    def test_placement_failure_event_delivery_and_replay(self, agent, api):
        sub = agent.server.events.subscribe({"PlacementFailure": ["*"]})
        try:
            job, _ = _register_unplaceable(api)
            deadline = time.time() + 30
            ev = None
            while time.time() < deadline:
                got = sub.next(timeout=1.0)
                if got is not None and got.key == job.id:
                    ev = got
                    break
            assert ev is not None, "no live PlacementFailure event"
            assert ev.topic == "PlacementFailure"
            assert ev.payload.failed_tg_allocs
        finally:
            agent.server.events.unsubscribe(sub)
        # replay: a LATE subscriber gets the same event from the buffer
        sub2 = agent.server.events.subscribe(
            {"PlacementFailure": [job.id]}, from_index=0)
        try:
            ev2 = sub2.next(timeout=5)
            assert ev2 is not None and ev2.key == job.id
            assert ev2.index == ev.index
        finally:
            agent.server.events.unsubscribe(sub2)

    def test_placed_alloc_score_table_http_and_cli(self, agent, api,
                                                   capsys):
        job = mock.batch_job()
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].config = {"run_for_s": 300}
        assert api.jobs.register(codec.encode(job))["EvalID"]

        def placed():
            allocs = api.jobs.allocations(job.id)
            return allocs if allocs and allocs[0].get("NodeID") else None

        allocs = _wait(placed, timeout=30)
        assert allocs, "job never placed"
        info = api.allocations.info(allocs[0]["ID"])
        rows = info["Metrics"]["ScoreMetaData"]
        assert rows and rows[0]["NodeID"]
        # `alloc status -verbose` renders the winning score breakdown
        rc = cli.main(["-address", agent.address, "alloc", "status",
                       allocs[0]["ID"], "-verbose"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Score breakdown" in out
        assert rows[0]["NodeID"][:8] in out

    def test_eval_explain_cli(self, agent, api, capsys):
        job, eval_id = _register_unplaceable(api)
        _wait(lambda: api.evaluations.explain(eval_id).get("BlockedEval"),
              timeout=30)
        rc = cli.main(["-address", agent.address, "eval", "explain",
                       eval_id])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Dimensions Exhausted = memory" in out
        assert "Why pending" in out
        # `job status` surfaces the same rollup as Placement Failures
        rc = cli.main(["-address", agent.address, "job", "status", job.id])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Placement Failures:" in out
        assert "blocked waiting for capacity" in out

    def test_quality_gauges_exported(self, api):
        text = api.agent.metrics(format="prometheus")
        for fam in ("nomad_quality_nodes_in_use",
                    "nomad_quality_zone_allocs_max",
                    "nomad_quality_zone_balance_max_over_min"):
            assert fam in text, fam
        assert 'nomad_quality_binpack_fill{dimension="memory"}' in text
        m = api.agent.metrics()
        assert "nomad.quality.nodes_in_use" in m
