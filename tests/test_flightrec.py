"""Flight recorder, rolling SLO windows, and the dump-on-anomaly health
plane (ISSUE 9): ring bounding + merge semantics, windowed-histogram
rotation with VirtualClock byte-identical double-runs, SLO breach →
dump-bundle schema → HealthBreach event delivery + replay, HTTP/CLI
round-trips, and a seeded flap storm tripping the heartbeat SLO
deterministically."""

import json
import random
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.chaos.clock import VirtualClock
from nomad_tpu.core.flightrec import (
    DEFAULT_SLO,
    FLIGHT,
    FlightRecorder,
    HealthWatchdog,
)
from nomad_tpu.core.logging import RING, log, trace_scope
from nomad_tpu.core.server import Server
from nomad_tpu.core.timeline import Timeline
from nomad_tpu.core.telemetry import (
    MetricsRegistry,
    REGISTRY,
    Tracer,
    WindowedHistogram,
)
from nomad_tpu.structs import codec


def _wait(fn, timeout=30, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


# --------------------------------------------------------- flight rings


class TestFlightRings:
    def test_wave_ring_bounds_and_counts_evictions(self):
        fr = FlightRecorder(max_waves=4)
        for w in range(10):
            fr.record_wave(w, items=2)
        waves = fr.waves()
        assert len(waves) == 4
        assert [w["Wave"] for w in waves] == [6, 7, 8, 9]
        assert fr.stats["wave_evictions"] == 6
        # an evicted wave's key re-records as a FRESH entry (the open
        # table is pruned with the ring — no unbounded growth)
        fr.record_wave(0, items=1)
        assert fr.waves()[-1] == {**fr.waves()[-1], "Wave": 0, "items": 1}

    def test_merge_semantics_accumulate_numeric_overwrite_rest(self):
        fr = FlightRecorder()
        fr.record_wave(7, device_s=0.1, items=3, chained=False, tag="a")
        fr.record_wave(7, device_s=0.2, commit_s=0.05, chained=True,
                       tag="b")
        rec = fr.waves()[-1]
        assert rec["Wave"] == 7
        assert rec["device_s"] == pytest.approx(0.3)   # accumulates
        assert rec["commit_s"] == pytest.approx(0.05)
        assert rec["chained"] is True                  # bool overwrites
        assert rec["tag"] == "b"                       # str overwrites
        # negative / missing wave ids are dropped, not recorded
        fr.record_wave(-1, device_s=1.0)
        fr.record_wave(None, device_s=1.0)
        assert len(fr.waves()) == 1

    def test_eval_and_event_rings_bound(self):
        fr = FlightRecorder(max_evals=3, max_events=2)
        for i in range(5):
            fr.record_eval(f"ev{i}", outcome="ack")
            fr.record_event("executor.invalidation", reason="t")
        assert [e["EvalID"] for e in fr.evals()] == ["ev2", "ev3", "ev4"]
        assert fr.stats["eval_evictions"] == 2
        assert len(fr.events()) == 2
        assert fr.stats["event_evictions"] == 3
        # merging into a live eval record accumulates
        fr.record_eval("ev4", queue_wait_s=0.5)
        fr.record_eval("ev4", queue_wait_s=0.25)
        assert fr.evals()[-1]["queue_wait_s"] == pytest.approx(0.75)
        snap = fr.snapshot(n_waves=1, n_evals=2, n_events=1)
        json.dumps(snap)                               # JSON-safe
        assert len(snap["Evals"]) == 2


# ------------------------------------------------------ rolling windows


class TestWindowedHistogram:
    def test_rotation_forgets_old_samples(self):
        w = WindowedHistogram(window_s=60.0, n_sub=6)
        w.observe(5.0, now=0.0)
        assert w.summary(now=1.0)["count"] == 1
        # inside the window the sample survives sub-rotations
        assert w.summary(now=59.0)["count"] == 1
        # past the window it is gone — a p99 regression can't drown in
        # hours of healthy history, and recovery clears the verdict
        assert w.summary(now=121.0)["count"] == 0

    def test_registry_windowed_series_and_exposition(self):
        reg = MetricsRegistry(clock=VirtualClock())
        reg.observe_windowed("t.lat_s", 0.004)
        reg.observe("t.plain_s", 0.004)
        ws = reg.window_summary("t.lat_s")
        assert ws["count"] == 1 and ws["window_s"] == 60.0
        assert reg.window_summary("t.plain_s") is None
        # the cumulative family records too (lifetime view survives)
        assert reg.histogram("t.lat_s")["count"] == 1
        text = reg.prometheus()
        assert "t_lat_seconds_window_p99" in text
        assert "t_lat_seconds_window_count" in text
        assert "t_plain_seconds_window_p99" not in text
        assert "windows" in reg.snapshot()

    def test_virtualclock_double_run_byte_identical(self):
        def run():
            clk = VirtualClock()
            reg = MetricsRegistry(clock=clk)
            rng = random.Random(99)
            for i in range(300):
                reg.observe_windowed("nomad.plan.queue_wait_s",
                                     rng.random() * 0.01)
                clk.advance(0.37)
            return json.dumps(
                [reg.window_summary("nomad.plan.queue_wait_s"),
                 reg.snapshot()["windows"]], sort_keys=True).encode()

        a, b = run(), run()
        assert a == b
        # the schedule spans >60s of virtual time, so rotation really
        # happened (the parity is over a rotating ring, not one sub)
        assert json.loads(a)[0]["count"] < 300


# ------------------------------------------------------- health watchdog


def _loaded_watchdog(slo, observe):
    """Isolated registry/flight/tracer watchdog on a VirtualClock;
    `observe(reg, clk, flight)` scripts the workload."""
    clk = VirtualClock()
    reg = MetricsRegistry(clock=clk)
    fl = FlightRecorder(clock=clk, max_waves=16)
    tr = Tracer(clock=clk)
    tl = Timeline(clock=clk, registry=reg)
    wd = HealthWatchdog(slo=slo, clock=clk, registry=reg, flight=fl,
                        tracer=tr, log_ring=None, timeline=tl)
    wd.check()                          # baseline for the counter deltas
    observe(reg, clk, fl)
    return wd, clk, reg


class TestHealthWatchdog:
    def test_unknown_slo_key_rejected(self):
        with pytest.raises(ValueError, match="unknown slo"):
            HealthWatchdog(slo={"p99_whatever": 1})

    def test_clean_run_is_healthy_and_no_dump(self):
        wd, clk, _ = _loaded_watchdog(
            {"interval_s": 0.0},
            lambda reg, clk, fl: (
                reg.observe_windowed("nomad.plan.queue_wait_s", 0.001),
                clk.advance(1.0)))
        doc = wd.check()
        assert doc["Healthy"] and doc["Dumps"] == 0
        assert {r["Rule"] for r in doc["Rules"]} == {
            "p99_plan_queue_ms", "refute_rate", "invalidations_per_s",
            "networked_ratio", "heartbeat_misses", "rss_mb",
            "cluster_scrape_failures", "cluster_follower_lag",
            "cluster_heartbeat_misses"}

    def test_negative_threshold_disables_rule(self):
        wd, clk, _ = _loaded_watchdog(
            {"p99_plan_queue_ms": -1.0, "interval_s": 0.0},
            lambda reg, clk, fl: (
                reg.observe_windowed("nomad.plan.queue_wait_s", 9.0),
                clk.advance(1.0)))
        doc = wd.check()
        assert doc["Healthy"], doc

    def test_breach_builds_schema_complete_dump_once(self):
        def load(reg, clk, fl):
            fl.record_wave(1, items=4, device_s=0.002)
            reg.observe_windowed("nomad.plan.queue_wait_s", 0.9)
            clk.advance(1.0)

        wd, clk, reg = _loaded_watchdog(
            {"p99_plan_queue_ms": 5.0, "interval_s": 0.0}, load)
        doc = wd.check()
        assert not doc["Healthy"] and doc["Dumps"] == 1
        bad = [r for r in doc["Rules"] if not r["Ok"]]
        assert [r["Rule"] for r in bad] == ["p99_plan_queue_ms"]
        assert bad[0]["Observed"] > bad[0]["Threshold"]
        bundle = wd.dumps()[0]
        for key in ("Schema", "At", "Breaches", "Verdicts", "SLO",
                    "FlightRecorder", "Windows", "Counters", "Traces",
                    "Spans", "Logs"):
            assert key in bundle, sorted(bundle)
        assert bundle["Schema"] == "nomad-tpu.health-dump.v1"
        assert bundle["FlightRecorder"]["Waves"][0]["items"] == 4
        assert "nomad.plan.queue_wait_s" in bundle["Windows"]
        json.dumps(bundle)
        # STILL breached on the next check: edge-triggered, no 2nd dump
        clk.advance(1.0)
        reg.observe_windowed("nomad.plan.queue_wait_s", 0.9)
        assert wd.check()["Dumps"] == 1
        assert reg.gauge("nomad.health.healthy") == 0.0

    def test_recovery_rearms_the_dump_trigger(self):
        wd, clk, reg = _loaded_watchdog(
            {"p99_plan_queue_ms": 5.0, "interval_s": 0.0},
            lambda reg, clk, fl: (
                reg.observe_windowed("nomad.plan.queue_wait_s", 0.9),
                clk.advance(1.0)))
        assert not wd.check()["Healthy"]
        # the window rotates the spike out -> healthy again
        clk.advance(200.0)
        doc = wd.check()
        assert doc["Healthy"]
        assert reg.gauge("nomad.health.healthy") == 1.0
        # a second spike re-trips and snapshots a SECOND dump
        reg.observe_windowed("nomad.plan.queue_wait_s", 0.9)
        clk.advance(1.0)
        doc = wd.check()
        assert not doc["Healthy"] and doc["Dumps"] == 2

    def test_counter_delta_rules(self):
        def load(reg, clk, fl):
            reg.inc("nomad.plan.plans", 10)
            reg.inc("nomad.plan.plans_refuted", 9)
            reg.inc("nomad.executor.invalidations", 500, reason="a")
            reg.inc("nomad.executor.invalidations", 500, reason="b")
            reg.inc("nomad.ports.batched_rows", 1)
            reg.inc("nomad.ports.sequential_rows", 9)
            clk.advance(10.0)

        wd, clk, reg = _loaded_watchdog({"interval_s": 0.0}, load)
        doc = wd.check()
        by = {r["Rule"]: r for r in doc["Rules"]}
        assert by["refute_rate"]["Observed"] == pytest.approx(0.9)
        assert not by["refute_rate"]["Ok"]
        # 1000 invalidations over 10 virtual seconds = 100/s > 50/s
        assert by["invalidations_per_s"]["Observed"] == pytest.approx(100)
        assert not by["invalidations_per_s"]["Ok"]
        # FLOOR: 10% columnar < the 25% floor
        assert by["networked_ratio"]["Observed"] == pytest.approx(0.1)
        assert not by["networked_ratio"]["Ok"]
        # next interval with NO traffic: deltas are zero -> Observed
        # None -> Ok (no-traffic intervals never breach)
        clk.advance(10.0)
        doc = wd.check()
        by = {r["Rule"]: r for r in doc["Rules"]}
        assert by["refute_rate"]["Observed"] is None
        assert by["refute_rate"]["Ok"]

    def test_tick_throttles_to_interval(self):
        wd, clk, _ = _loaded_watchdog(
            {"interval_s": 5.0}, lambda reg, clk, fl: clk.advance(1.0))
        assert wd.tick(clk.monotonic()) is None        # 1s < 5s
        clk.advance(5.0)
        assert wd.tick(clk.monotonic()) is not None

    def test_seeded_breach_dump_is_deterministic_double_run(self):
        """The acceptance gate: the same seeded virtual-time workload
        produces a byte-identical dump bundle twice."""

        def run():
            def load(reg, clk, fl):
                rng = random.Random(1234)
                for w in range(20):
                    fl.record_wave(w, items=rng.randint(2, 8),
                                   device_s=round(rng.random() / 100, 9))
                    reg.observe_windowed("nomad.plan.queue_wait_s",
                                         round(rng.random() / 100, 9))
                    reg.inc("nomad.plan.plans")
                    clk.advance(0.5)
                reg.inc("nomad.plan.plans_refuted", 19)
                fl.record_event("executor.invalidation", reason="seeded")
                clk.advance(0.5)

            wd, clk, _ = _loaded_watchdog(
                {"refute_rate": 0.5, "interval_s": 0.0}, load)
            doc = wd.check()
            assert not doc["Healthy"]
            return json.dumps(wd.dumps()[0], sort_keys=True).encode()

        a, b = run(), run()
        assert a == b
        assert b"refute_rate" in a


# -------------------------------------------- seeded heartbeat flap storm


class TestFlapStormHeartbeatSLO:
    def _storm(self):
        """A seeded flap storm on the VirtualClock: 12 nodes, a seeded
        survivor subset keeps beating, the rest go silent; the heartbeat
        SLO (ceiling 3 misses/check) must trip when their TTLs lapse."""
        REGISTRY.reset()
        FLIGHT.reset()
        clk = VirtualClock(epoch=1.7e9)
        s = Server(num_workers=1, clock=clk, heartbeat_ttl=5.0,
                   slo={"heartbeat_misses": 3.0, "interval_s": 0.0})
        s.establish_leadership()
        nodes = [mock.node() for _ in range(12)]
        for n in nodes:
            s.register_node(n)
        rng = random.Random(7)
        survivors = set(rng.sample(sorted(n.id for n in nodes), 4))
        s.health.check(clk.monotonic())          # delta baseline
        breach = None
        for _ in range(4):
            clk.advance(2.0)
            for nid in survivors:
                s.heartbeat_node(nid)
            s.tick()
            doc = s.health.check(clk.monotonic())
            if not doc["Healthy"]:
                breach = doc
                break
        assert breach is not None, "flap storm never tripped the SLO"
        by = {r["Rule"]: r for r in breach["Rules"]}
        down = [n for n in s.state.snapshot().nodes()
                if n.status == "down"]
        sub = s.events.subscribe({"HealthBreach": ["*"]}, from_index=0)
        ev = sub.next(timeout=1.0)
        return by["heartbeat_misses"], len(down), ev

    def test_flap_storm_trips_heartbeat_slo_deterministically(self):
        v1, down1, ev1 = self._storm()
        v2, down2, ev2 = self._storm()
        assert not v1["Ok"]
        assert v1["Observed"] == 8.0               # 12 - 4 survivors
        assert down1 == 8
        # byte-identical verdicts across the double run
        assert json.dumps(v1, sort_keys=True) == \
            json.dumps(v2, sort_keys=True)
        # the breach rode the event stream (replay from the buffer)
        assert ev1 is not None and ev1.topic == "HealthBreach"
        assert ev1.key == "heartbeat_misses"
        assert ev1.wire()["Payload"]["Rule"] == "heartbeat_misses"
        assert ev2 is not None and ev2.key == ev1.key


# ------------------------------------------------- event delivery (live)


class TestHealthBreachEvents:
    def test_live_delivery_and_replay(self):
        REGISTRY.reset()
        clk = VirtualClock()
        s = Server(num_workers=1, clock=clk,
                   slo={"p99_plan_queue_ms": 0.001, "interval_s": 0.0})
        s.establish_leadership()
        live = s.events.subscribe({"HealthBreach": ["*"]})
        REGISTRY.observe_windowed("nomad.plan.queue_wait_s", 0.5)
        clk.advance(1.0)
        doc = s.health.check(clk.monotonic())
        assert not doc["Healthy"]
        ev = live.next(timeout=1.0)
        assert ev is not None and ev.type == "HealthBreach"
        assert ev.key == "p99_plan_queue_ms"
        # bucket-interpolated estimate of the 0.5s sample (~497ms)
        assert ev.wire()["Payload"]["Observed"] >= 400.0
        # a LATE subscriber replays it from the buffer
        late = s.events.subscribe({"HealthBreach": ["*"]}, from_index=0)
        ev2 = late.next(timeout=1.0)
        assert ev2 is not None and ev2.key == ev.key


# ----------------------------------------------------- tracer + logging


class TestSatellites:
    def test_tracer_dropped_spans_are_counted(self):
        tr = Tracer(max_spans=4)
        before = REGISTRY.counter("nomad.tracer.dropped_spans")
        for i in range(6):
            tr.record(f"s{i}", "tid", 0.0, 1.0)
        assert tr.dropped == 2
        assert len(tr.spans()) == 4
        assert REGISTRY.counter("nomad.tracer.dropped_spans") == \
            before + 2
        tr.reset()
        assert tr.dropped == 0

    def test_trace_scope_stamps_log_records(self):
        marker = f"flightrec-scope-{random.random()}"
        with trace_scope("trace-abc"):
            log("test", "warn", marker)
            with trace_scope(""):          # empty nests inherit
                log("test", "warn", marker + "-inner")
        log("test", "warn", marker + "-outside")
        recs = {r["msg"]: r for r in RING.tail(50)}
        assert recs[marker]["trace_id"] == "trace-abc"
        assert recs[marker + "-inner"]["trace_id"] == "trace-abc"
        assert "trace_id" not in recs[marker + "-outside"]
        # an explicit trace_id field wins over the ambient scope
        with trace_scope("ambient"):
            log("test", "warn", marker + "-explicit", trace_id="mine")
        recs = {r["msg"]: r for r in RING.tail(50)}
        assert recs[marker + "-explicit"]["trace_id"] == "mine"

    def test_agent_config_slo_block(self):
        from nomad_tpu.agent_config import parse_agent_config
        cfg, set_fields = parse_agent_config("""
        server {
          enabled = true
          slo {
            p99_plan_queue_ms = 25
            heartbeat_misses  = 2
          }
        }
        """)
        assert "slo" in set_fields
        assert cfg.slo == {"p99_plan_queue_ms": 25.0,
                           "heartbeat_misses": 2.0}
        with pytest.raises(ValueError, match="unknown slo"):
            parse_agent_config("server { slo { nope = 1 } }")
        with pytest.raises(ValueError, match="must be a number"):
            parse_agent_config('server { slo { refute_rate = "x" } }')
        # every documented DEFAULT_SLO key parses
        body = "\n".join(f"{k} = 1" for k in DEFAULT_SLO)
        cfg, _ = parse_agent_config("server { slo { %s } }" % body)
        assert set(cfg.slo) == set(DEFAULT_SLO)


# ------------------------------------------------------- HTTP + CLI e2e


@pytest.fixture(scope="module")
def agent():
    ag = Agent(num_clients=1, num_workers=1, heartbeat_ttl=3600)
    ag.start()
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].config = {"run_for_s": 300}
    api = APIClient(address=ag.address)
    eval_id = api.jobs.register(codec.encode(job))["EvalID"]
    assert _wait(lambda: api.evaluations.info(eval_id)
                 .get("Status") == "complete")
    ag.eval_id = eval_id
    yield ag
    ag.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(address=agent.address)


class TestHTTPRoundTrip:
    def test_operator_health(self, api):
        doc = api.operator.health()
        assert doc["Healthy"] is True
        assert len(doc["Rules"]) == 9
        for r in doc["Rules"]:
            assert {"Rule", "Kind", "Threshold", "Observed", "Ok",
                    "Unit", "Source"} <= set(r)
        assert "DumpBundles" not in doc
        assert "DumpBundles" in api.operator.health(dumps=True)

    def test_operator_flight_recorder(self, api, agent):
        rec = api.operator.flight_recorder()
        evs = [e for e in rec["Evals"] if e["EvalID"] == agent.eval_id]
        assert evs, rec["Evals"][-3:]
        e = evs[0]
        assert e["outcome"] == "ack"
        assert e["schedule_s"] > 0
        assert e["trace_id"] == agent.eval_id
        assert "queue_wait_s" in e and "apply_s" in e
        # ?n= caps the tails
        capped = api.operator.flight_recorder(n=1)
        assert len(capped["Evals"]) <= 1

    def test_debug_bundle_folds_health_plane_in(self, api):
        bundle = api.operator.debug()
        for key in ("Health", "HealthDumps", "FlightRecorder",
                    "TracerDroppedSpans"):
            assert key in bundle, sorted(bundle)
        assert bundle["Health"]["Healthy"] is True
        assert isinstance(bundle["TracerDroppedSpans"], int)

    def test_windowed_families_in_exposition(self, api):
        text = api.agent.metrics(format="prometheus")
        assert "nomad_worker_schedule_seconds_window_p99" in text
        assert "nomad_plan_queue_wait_seconds_window_p99" in text


class TestCLIRoundTrip:
    def test_nomad_health(self, agent, capsys):
        from nomad_tpu.cli import main
        rc = main(["-address", agent.address, "health"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Healthy      = True" in out
        for rule in ("p99_plan_queue_ms", "refute_rate",
                     "heartbeat_misses"):
            assert rule in out

    def test_nomad_debug_record(self, agent, capsys):
        from nomad_tpu.cli import main
        rc = main(["-address", agent.address, "debug", "record"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Evals" in out and agent.eval_id[:8] in out

    def test_nomad_debug_record_dump_writes_file(self, agent, tmp_path,
                                                 capsys):
        from nomad_tpu.cli import main
        path = tmp_path / "dumps.json"
        rc = main(["-address", agent.address, "debug", "record",
                   "-dump", "-output", str(path)])
        assert rc == 0
        assert "written to" in capsys.readouterr().out
        assert isinstance(json.loads(path.read_text()), list)
