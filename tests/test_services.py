"""Nomad-native service discovery + checks (reference:
client/serviceregistration/, Service RPC endpoints) and volume
feasibility (HostVolumeChecker / CSIVolumeChecker parity)."""

import http.server
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    Service,
    UpdateStrategy,
    VolumeRequest,
    codec,
)


class TestVolumeFeasibility:
    def test_host_volume_constrains_placement(self):
        h = Harness()
        good = mock.node()
        good.host_volumes = {"certs": "/etc/certs"}
        from nomad_tpu.structs import compute_class
        good.computed_class = compute_class(good)
        h.state.upsert_node(good)
        for _ in range(4):
            h.state.upsert_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        job.task_groups[0].volumes = {
            "certs": VolumeRequest(name="certs", type="host",
                                   source="certs")}
        h.state.upsert_job(job)
        h.process("service", mock.eval(job_id=job.id, type=job.type))
        placed = [a for allocs in h.plans[-1].node_allocation.values()
                  for a in allocs]
        assert len(placed) == 1
        assert placed[0].node_id == good.id, \
            "host-volume job must land on the node with the volume"


class TestServiceDiscovery:
    def test_services_register_and_checks_drive_status(self):
        # real HTTP endpoint the check probes
        class Ok(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def log_message(self, *a):
                pass

        backend = http.server.HTTPServer(("127.0.0.1", 0), Ok)
        port = backend.server_port
        threading.Thread(target=backend.serve_forever, daemon=True).start()

        ag = Agent(num_clients=1, heartbeat_ttl=3600)
        ag.start()
        try:
            api = APIClient(address=ag.address)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.update = UpdateStrategy(max_parallel=1,
                                       health_check="checks",
                                       min_healthy_time_s=0.2)
            tg.tasks[0].driver = "mock"
            tg.tasks[0].config = {"run_for_s": 600}
            tg.services = [Service(
                name="web-api", provider="nomad", tags=["v1"],
                checks=[{"type": "http", "port": port,
                         "path": "/", "interval": "1s",
                         "timeout": "2s"}])]
            api.jobs.register(codec.encode(job))

            deadline = time.time() + 60
            regs = []
            while time.time() < deadline:
                try:
                    regs = api.services.info("web-api")
                except Exception:
                    regs = []
                if regs and regs[0].get("Status") == "passing":
                    break
                time.sleep(0.5)
            assert regs, "service never registered"
            assert regs[0]["ServiceName"] == "web-api"
            assert regs[0]["Status"] == "passing"
            assert regs[0]["Tags"] == ["v1"]

            listed = api.services.list()
            assert any(s["ServiceName"] == "web-api"
                       for row in listed for s in row["Services"])

            # passing checks drive deployment health -> successful
            deadline = time.time() + 60
            dep = None
            while time.time() < deadline:
                dep = ag.server.state.latest_deployment_by_job(
                    job.namespace, job.id)
                if dep is not None and dep.status == "successful":
                    break
                time.sleep(0.5)
            assert dep is not None and dep.status == "successful"

            # stopping the job deregisters
            api.jobs.deregister(job.id)
            deadline = time.time() + 30
            while time.time() < deadline:
                if not ag.server.state.service_registrations(
                        name="web-api"):
                    break
                time.sleep(0.5)
            assert not ag.server.state.service_registrations(
                name="web-api")
        finally:
            ag.shutdown()
            backend.shutdown()

    def test_failing_check_reports_critical(self):
        ag = Agent(num_clients=1, heartbeat_ttl=3600)
        ag.start()
        try:
            api = APIClient(address=ag.address)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].driver = "mock"
            tg.tasks[0].config = {"run_for_s": 600}
            tg.services = [Service(
                name="dead-api", provider="nomad",
                checks=[{"type": "tcp", "port": 1,
                         "interval": "1s", "timeout": "1s"}])]
            api.jobs.register(codec.encode(job))
            deadline = time.time() + 60
            regs = []
            while time.time() < deadline:
                try:
                    regs = api.services.info("dead-api")
                    break
                except Exception:
                    time.sleep(0.5)
            assert regs and regs[0]["Status"] == "critical"
        finally:
            ag.shutdown()
