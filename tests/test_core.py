"""Server-core tests: broker, blocked evals, plan applier, worker, server
(reference scenarios: nomad/eval_broker_test.go, blocked_evals_test.go,
plan_apply_test.go, worker_test.go)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.core import EvalBroker, PlanQueue, PlanApplier, Server
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Plan, Resources

NOW = 1_700_000_000.0


class TestEvalBroker:
    def test_priority_order(self):
        b = EvalBroker()
        b.set_enabled(True)
        lo = mock.eval(priority=10)
        hi = mock.eval(priority=90)
        b.enqueue(lo, now=NOW)
        b.enqueue(hi, now=NOW)
        ev, tok = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev.id == hi.id
        b.ack(ev.id, tok)
        ev2, tok2 = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev2.id == lo.id

    def test_per_job_serialization(self):
        b = EvalBroker()
        b.set_enabled(True)
        e1 = mock.eval(job_id="j1")
        e2 = mock.eval(job_id="j1")
        b.enqueue(e1, now=NOW)
        b.enqueue(e2, now=NOW)
        ev, tok = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev.id == e1.id
        # second eval for the same job is held
        none, _ = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert none is None
        b.ack(e1.id, tok)
        ev2, _ = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev2.id == e2.id

    def test_nack_requeues_then_fails(self):
        b = EvalBroker(delivery_limit=2)
        b.set_enabled(True)
        e = mock.eval()
        b.enqueue(e, now=NOW)
        for i in range(2):
            ev, tok = b.dequeue(["service"], now=NOW, timeout=0.0)
            assert ev is not None
            b.nack(ev.id, tok, now=NOW)
        none, _ = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert none is None
        assert len(b.failed_evals()) == 1

    def test_nack_timeout_requeues(self):
        b = EvalBroker(nack_timeout=10)
        b.set_enabled(True)
        e = mock.eval()
        b.enqueue(e, now=NOW)
        ev, tok = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev is not None
        # worker dies; timeout passes
        b.tick(NOW + 11)
        ev2, tok2 = b.dequeue(["service"], now=NOW + 11, timeout=0.0)
        assert ev2.id == e.id
        # stale token no longer acks
        assert b.ack(e.id, tok) is not None
        assert b.ack(e.id, tok2) is None

    def test_delayed_eval_held_until_wait_until(self):
        b = EvalBroker()
        b.set_enabled(True)
        e = mock.eval()
        e.wait_until = NOW + 100
        b.enqueue(e, now=NOW)
        none, _ = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert none is None
        b.tick(NOW + 101)
        ev, _ = b.dequeue(["service"], now=NOW + 101, timeout=0.0)
        assert ev.id == e.id

    def test_disabled_drops(self):
        b = EvalBroker()
        b.enqueue(mock.eval(), now=NOW)
        assert b.pending_evals() == 0


class TestPlanApplier:
    def _setup(self):
        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        return state, q, applier

    def test_refutes_overcommitted_node(self):
        state, q, applier = self._setup()
        n = mock.node()
        state.upsert_node(n)
        job = mock.job()
        state.upsert_job(job)
        # two workers racing: plan A commits 3000MHz, plan B (stale) wants
        # 3000MHz more -> B must be refuted
        a1 = mock.alloc(job=job, node_id=n.id)
        a1.resources = Resources(cpu=3000, memory_mb=100)
        plan_a = Plan(eval_id="ea", job=job)
        plan_a.append_alloc(a1)
        pa = q.enqueue(plan_a)
        applier.apply_one(pa)
        res_a, err_a = pa.wait(0.1)
        assert err_a is None and not res_a.refuted_nodes

        a2 = mock.alloc(job=job, node_id=n.id)
        a2.resources = Resources(cpu=3000, memory_mb=100)
        plan_b = Plan(eval_id="eb", job=job)
        plan_b.append_alloc(a2)
        pb = q.enqueue(plan_b)
        applier.apply_one(pb)
        res_b, err_b = pb.wait(0.1)
        assert err_b is None
        assert res_b.refuted_nodes == [n.id]
        full, expected, actual = res_b.full_commit(plan_b)
        assert not full and expected == 1 and actual == 0
        # state must NOT contain the refuted alloc
        assert state.snapshot().alloc_by_id(a2.id) is None

    def test_plan_with_stop_frees_capacity(self):
        state, q, applier = self._setup()
        n = mock.node()
        state.upsert_node(n)
        job = mock.job()
        state.upsert_job(job)
        old = mock.alloc(job=job, node_id=n.id)
        old.resources = Resources(cpu=3500, memory_mb=100)
        state.upsert_allocs([old])
        stopped = old.copy_skip_job()
        new = mock.alloc(job=job, node_id=n.id)
        new.resources = Resources(cpu=3500, memory_mb=100)
        plan = Plan(eval_id="e", job=job)
        plan.append_stopped_alloc(stopped, "update")
        plan.append_alloc(new)
        p = q.enqueue(plan)
        applier.apply_one(p)
        res, err = p.wait(0.1)
        assert err is None and not res.refuted_nodes

    def test_down_node_refused(self):
        state, q, applier = self._setup()
        n = mock.node(status="down")
        state.upsert_node(n)
        job = mock.job()
        plan = Plan(eval_id="e", job=job)
        plan.append_alloc(mock.alloc(job=job, node_id=n.id))
        p = q.enqueue(plan)
        applier.apply_one(p)
        res, _ = p.wait(0.1)
        assert res.refuted_nodes == [n.id]


class TestServer:
    def test_register_to_running_end_to_end(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(3):
            s.register_node(mock.node(), now=NOW)
        job = mock.job()
        job.task_groups[0].count = 3
        s.register_job(job, now=NOW)
        n = s.process_all(now=NOW)
        assert n == 1
        live = [a for a in s.state.snapshot().allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 3
        ev = s.state.snapshot().evals_by_job(job.namespace, job.id)
        assert any(e.status == "complete" for e in ev)

    def test_blocked_eval_released_on_new_node(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        # no nodes: everything blocks
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        assert s.blocked_evals.num_blocked() == 1
        # capacity arrives
        s.register_node(mock.node(), now=NOW + 1)
        processed = s.process_all(now=NOW + 1)
        assert processed >= 1
        live = [a for a in s.state.snapshot().allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 2

    def test_heartbeat_expiry_reschedules(self):
        s = Server(dev_mode=True, heartbeat_ttl=30)
        s.establish_leadership()
        n1, n2 = mock.node(), mock.node()
        s.register_node(n1, now=NOW)
        s.register_node(n2, now=NOW)
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        victim = next(a.node_id for a in
                      s.state.snapshot().allocs_by_job(job.namespace, job.id))
        other = n2.id if victim == n1.id else n1.id
        # victim stops heartbeating; the other keeps beating
        s.heartbeat_node(other, now=NOW + 25)
        s.tick(now=NOW + 31)
        assert s.state.node_by_id(victim).status == "down"
        s.process_all(now=NOW + 31)
        live = [a for a in s.state.snapshot().allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 1 and live[0].node_id == other

    def test_deregister_stops_allocs(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.register_node(mock.node(), now=NOW)
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        s.deregister_job(job.namespace, job.id, now=NOW + 1)
        s.process_all(now=NOW + 1)
        live = [a for a in s.state.snapshot().allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert live == []

    def test_threaded_mode_smoke(self):
        import time as _t
        s = Server(num_workers=2, dev_mode=False)
        s.start()
        try:
            for _ in range(3):
                s.register_node(mock.node())
            job = mock.job()
            job.task_groups[0].count = 5
            s.register_job(job)
            deadline = _t.time() + 30
            while _t.time() < deadline:
                live = [a for a in
                        s.state.snapshot().allocs_by_job(job.namespace, job.id)
                        if not a.terminal_status()]
                if len(live) == 5:
                    break
                _t.sleep(0.1)
            assert len(live) == 5
        finally:
            s.shutdown()


class TestFailedEvalFollowUp:
    def test_failed_eval_creates_delayed_follow_up(self):
        # reference: leader.go reapFailedEvaluations — a failed eval must
        # leave a delayed follow-up so its job isn't stranded until the
        # next unrelated state change.
        s = Server(dev_mode=True, failed_follow_up_delay=(5.0, 5.0))
        s.establish_leadership()
        ev = mock.eval(job_id="j-stranded")
        ev.status = "failed"
        ev.status_description = "maximum attempts reached (2)"
        s.apply_eval_update([ev], now=100.0)
        snap = s.state.snapshot()
        fus = [e for e in snap.evals()
               if e.triggered_by == "failed-follow-up"]
        assert len(fus) == 1
        fu = fus[0]
        assert fu.job_id == "j-stranded"
        assert fu.previous_eval == ev.id
        assert fu.wait_until == 105.0
        assert fu.status == "pending"
        # held by the broker until its time arrives
        got, _ = s.eval_broker.dequeue(["service"], now=101.0, timeout=0.0)
        assert got is None
        s.eval_broker.tick(106.0)
        got, _ = s.eval_broker.dequeue(["service"], now=106.0, timeout=0.0)
        assert got is not None and got.id == fu.id
        # re-upserting the same failed eval (redelivery) must NOT mint
        # another follow-up — only the transition to failed does
        s.apply_eval_update([ev], now=110.0)
        fus2 = [e for e in s.state.snapshot().evals()
                if e.triggered_by == "failed-follow-up"]
        assert len(fus2) == 1

    def test_delivery_limit_failure_reaped_on_tick(self):
        s = Server(dev_mode=True, failed_follow_up_delay=(5.0, 5.0))
        s.eval_broker.delivery_limit = 1
        s.establish_leadership()
        ev = mock.eval(job_id="j-nacked")
        s.apply_eval_update([ev], now=100.0)
        got, tok = s.eval_broker.dequeue(["service"], now=100.0, timeout=0.0)
        s.eval_broker.nack(got.id, tok, now=100.0)   # limit 1 -> failed
        s.tick(now=101.0)
        snap = s.state.snapshot()
        stored = snap.eval_by_id(ev.id)
        assert stored.status == "failed"
        fus = [e for e in snap.evals()
               if e.triggered_by == "failed-follow-up"]
        assert len(fus) == 1 and fus[0].previous_eval == ev.id


class TestReviewRegressions:
    def test_waiters_released_when_eval_fails(self):
        # An eval hitting the delivery limit must not strand same-job waiters.
        b = EvalBroker(delivery_limit=1)
        b.set_enabled(True)
        e1 = mock.eval(job_id="j1")
        e2 = mock.eval(job_id="j1")
        b.enqueue(e1, now=NOW)
        ev, tok = b.dequeue(["service"], now=NOW, timeout=0.0)
        b.enqueue(e2, now=NOW)   # stashed behind in-flight e1
        b.nack(ev.id, tok, now=NOW)       # limit 1 -> e1 fails
        assert len(b.failed_evals()) == 1
        ev2, _ = b.dequeue(["service"], now=NOW, timeout=0.0)
        assert ev2 is not None and ev2.id == e2.id

    def test_core_gc_eval(self):
        from nomad_tpu.structs import Evaluation
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.register_node(mock.node(), now=NOW)
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        s.deregister_job(job.namespace, job.id, now=NOW)
        s.process_all(now=NOW)
        # force-GC via a _core eval (the `nomad system gc` path)
        gc = Evaluation(type="_core", job_id="force-gc", priority=100)
        s.apply_eval_update([gc], now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        assert snap.job_by_id(job.namespace, job.id) is None
        assert all(e.id == gc.id or e.status != "complete"
                   or e.job_id != job.id for e in snap.evals())

    def test_preemption_respects_distinct_hosts(self):
        from nomad_tpu.structs import (Constraint, PreemptionConfig, Resources,
                                       SchedulerConfiguration)
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(service_scheduler_enabled=True)))
        n = mock.node()
        s.register_node(n, now=NOW)
        low = mock.batch_job(priority=10)
        low.task_groups[0].count = 4
        low.task_groups[0].tasks[0].resources = Resources(cpu=900, memory_mb=256)
        s.register_job(low, now=NOW)
        s.process_all(now=NOW)
        hi = mock.job(priority=90)
        hi.constraints.append(Constraint("", "distinct_hosts", ""))
        hi.task_groups[0].count = 2
        hi.task_groups[0].tasks[0].resources = Resources(cpu=1000, memory_mb=128)
        s.register_job(hi, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(hi.namespace, hi.id)
                if not a.terminal_status()]
        # only one node exists: distinct_hosts allows exactly ONE placement
        # even though preemption could free room for both
        assert len(live) == 1

    def test_heterogeneous_preemption_candidates(self):
        # Same-priority victims with different resource vectors: eviction
        # selection must not crash and must pick the best distance match.
        from nomad_tpu.structs import (PreemptionConfig, Resources,
                                       SchedulerConfiguration)
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(service_scheduler_enabled=True)))
        n = mock.node()
        s.register_node(n, now=NOW)
        low = mock.batch_job(priority=10)
        from nomad_tpu.structs import Task, TaskGroup
        low.task_groups = [
            TaskGroup(name="small", count=2,
                      tasks=[Task(name="t", driver="exec",
                                  resources=Resources(cpu=400, memory_mb=3000))]),
            TaskGroup(name="big", count=2,
                      tasks=[Task(name="t", driver="exec",
                                  resources=Resources(cpu=1500, memory_mb=500))]),
        ]
        s.register_job(low, now=NOW)
        s.process_all(now=NOW)
        hi = mock.job(priority=90)
        hi.task_groups[0].count = 1
        hi.task_groups[0].tasks[0].resources = Resources(cpu=1400, memory_mb=200)
        s.register_job(hi, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(hi.namespace, hi.id)
                if not a.terminal_status()]
        assert len(live) == 1
        evicted = [a for a in snap.allocs_by_job(low.namespace, low.id)
                   if a.desired_status == "evict"]
        # one 1500MHz victim suffices and matches the shortfall best
        assert len(evicted) == 1 and evicted[0].task_group == "big"

    def test_worker_survives_scheduler_crash(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.register_node(mock.node(), now=NOW)
        job = mock.job()
        s.register_job(job, now=NOW)
        # sabotage: make the engine raise for this eval
        orig = s.engine.place
        calls = {"n": 0}
        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("injected engine failure")
        s.engine.place = boom
        s.process_all(now=NOW)
        # worker nacked rather than dying; eval retried to delivery limit
        assert calls["n"] >= 1
        assert s.eval_broker.stats["nacked"] >= 1
        s.engine.place = orig

    def test_duplicate_blocked_eval_cancelled(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        job = mock.job()   # no nodes -> blocks
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        assert s.blocked_evals.num_blocked() == 1
        # trigger a second failing eval for the same job
        from nomad_tpu.structs import Evaluation
        e2 = Evaluation(namespace=job.namespace, job_id=job.id,
                        type="service", triggered_by="node-update")
        s.apply_eval_update([e2], now=NOW)
        s.process_all(now=NOW)
        assert s.blocked_evals.num_blocked() == 1
        snap = s.state.snapshot()
        blocked = [e for e in snap.evals_by_job(job.namespace, job.id)
                   if e.status == "blocked"]
        cancelled = [e for e in snap.evals_by_job(job.namespace, job.id)
                     if e.status == "canceled"]
        assert len(blocked) == 1
        assert len(cancelled) >= 1

    def test_threaded_heartbeat_expiry(self):
        import time as _t
        s = Server(num_workers=1, dev_mode=False, heartbeat_ttl=0.5)
        s.start(tick_interval=0.1)
        try:
            n1, n2 = mock.node(), mock.node()
            s.register_node(n1)
            s.register_node(n2)
            job = mock.job()
            job.task_groups[0].count = 1
            s.register_job(job)
            deadline = _t.time() + 15
            victim = None
            while _t.time() < deadline:
                allocs = [a for a in
                          s.state.snapshot().allocs_by_job(job.namespace, job.id)
                          if not a.terminal_status()]
                if allocs:
                    victim = allocs[0].node_id
                    break
                _t.sleep(0.05)
            assert victim is not None
            other = n2.id if victim == n1.id else n1.id
            # only the other node keeps heartbeating
            deadline = _t.time() + 15
            moved = False
            while _t.time() < deadline:
                s.heartbeat_node(other)
                live = [a for a in
                        s.state.snapshot().allocs_by_job(job.namespace, job.id)
                        if not a.terminal_status()]
                if live and live[0].node_id == other:
                    moved = True
                    break
                _t.sleep(0.1)
            assert moved, "alloc never moved off the dead node in threaded mode"
        finally:
            s.shutdown()


class TestBlockedEvalRaceGuard:
    def test_stale_snapshot_block_requeues(self):
        """A blocked eval whose scheduling snapshot predates the newest
        capacity change must re-enqueue, not park — parking would miss
        that unblock forever (reference: blocked_evals unblock indexes)."""
        from nomad_tpu.structs import Evaluation

        s = Server(dev_mode=True)
        s.establish_leadership()
        stale_index = s.state.latest_index()
        # capacity change AFTER the snapshot the eval was scheduled on
        s.register_node(mock.node(), now=NOW)
        ev = Evaluation(job_id="raced-job", type="batch",
                        status="blocked", snapshot_index=stale_index)
        assert s.blocked_evals.block(ev)
        assert s.blocked_evals.num_blocked() == 0      # not parked
        assert s.blocked_evals.stats["raced"] == 1
        assert s.eval_broker.pending_evals() == 1      # retrying instead

    def test_fresh_snapshot_block_parks(self):
        from nomad_tpu.structs import Evaluation

        s = Server(dev_mode=True)
        s.establish_leadership()
        s.register_node(mock.node(), now=NOW)
        ev = Evaluation(job_id="parked-job", type="batch",
                        status="blocked",
                        snapshot_index=s.state.latest_index())
        assert s.blocked_evals.block(ev)
        assert s.blocked_evals.num_blocked() == 1
