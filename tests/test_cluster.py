"""Multi-server cluster tests: raft election/replication/failover, gossip
membership, RPC leader forwarding, autopilot
(reference scenarios: nomad/leader_test.go, raft integration via
TestServer(t, cb) + WaitForLeader — multi-node without a real cluster =
in-process instances on loopback, SURVEY.md §5)."""

import pickle
import tempfile
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.cluster import ClusterServer, RemoteRPC
from nomad_tpu.core.membership import Gossip
from nomad_tpu.core.raft import NotLeaderError, RaftNode

try:                                  # the image may lack the optional
    import cryptography  # noqa: F401 - AEAD/RSA dep (gated, not assumed)
    HAS_CRYPTO = True
except ModuleNotFoundError:
    HAS_CRYPTO = False

requires_crypto = pytest.mark.skipif(
    not HAS_CRYPTO, reason="cryptography not installed in this image")


FAST = dict(heartbeat_interval=0.04, election_timeout=(0.15, 0.3))


def wait_for(fn, timeout=8.0, interval=0.05, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# --------------------------------------------------------------------- raft


class KVFSM:
    """Tiny deterministic FSM for raft unit tests."""

    def __init__(self):
        self.data = {}
        self.applied = []

    def apply(self, cmd: bytes):
        k, v = pickle.loads(cmd)
        self.data[k] = v
        self.applied.append((k, v))
        return len(self.applied)

    def snapshot(self) -> bytes:
        return pickle.dumps((self.data, self.applied))

    def restore(self, data: bytes) -> None:
        self.data, self.applied = pickle.loads(data)


def make_raft_trio(**kw):
    fsms = [KVFSM() for _ in range(3)]
    nodes = [RaftNode(f"s{i}", ("127.0.0.1", 0),
                      fsm_apply=fsms[i].apply,
                      fsm_snapshot=fsms[i].snapshot,
                      fsm_restore=fsms[i].restore,
                      **{**FAST, **kw})
             for i in range(3)]
    addrs = {n.name: n.addr for n in nodes}
    for n in nodes:
        n.set_peers(addrs)
        n.start()
    return nodes, fsms


def leader_of(nodes):
    leaders = [n for n in nodes if n.is_leader()]
    return leaders[0] if len(leaders) == 1 else None


class TestRaft:
    def test_election_and_replication(self):
        nodes, fsms = make_raft_trio()
        try:
            leader = wait_for(lambda: leader_of(nodes), msg="leader")
            for i in range(5):
                leader.apply(pickle.dumps((f"k{i}", i)))
            wait_for(lambda: all(len(f.applied) == 5 for f in fsms),
                     msg="replication")
            assert all(f.data == fsms[0].data for f in fsms)
        finally:
            for n in nodes:
                n.stop()

    def test_follower_rejects_apply(self):
        nodes, _ = make_raft_trio()
        try:
            leader = wait_for(lambda: leader_of(nodes), msg="leader")
            follower = next(n for n in nodes if n is not leader)
            with pytest.raises(NotLeaderError):
                follower.apply(b"nope")
        finally:
            for n in nodes:
                n.stop()

    def test_leader_failover_preserves_log(self):
        nodes, fsms = make_raft_trio()
        try:
            leader = wait_for(lambda: leader_of(nodes), msg="leader")
            for i in range(3):
                leader.apply(pickle.dumps((f"k{i}", i)))
            leader.stop()
            rest = [n for n in nodes if n is not leader]
            new_leader = wait_for(lambda: leader_of(rest),
                                  msg="new leader")
            assert new_leader is not leader
            new_leader.apply(pickle.dumps(("post", 1)))
            live_fsms = [fsms[nodes.index(n)] for n in rest]
            wait_for(lambda: all(f.data.get("post") == 1
                                 and len(f.applied) == 4
                                 for f in live_fsms),
                     msg="post-failover replication")
            assert all(f.data.get("k2") == 2 for f in live_fsms)
        finally:
            for n in nodes:
                n.stop()

    def test_lagging_follower_catches_up_via_snapshot(self):
        nodes, fsms = make_raft_trio(max_log_entries=8)
        try:
            leader = wait_for(lambda: leader_of(nodes), msg="leader")
            follower = next(n for n in nodes if n is not leader)
            follower.stop()
            for i in range(40):    # force compaction past the dead follower
                leader.apply(pickle.dumps((f"k{i}", i)))
            wait_for(lambda: leader.snap_index > 0, msg="compaction")
            # a fresh node with the same identity rejoins
            fsm = KVFSM()
            reborn = RaftNode(follower.name, ("127.0.0.1", 0),
                              fsm_apply=fsm.apply, fsm_snapshot=fsm.snapshot,
                              fsm_restore=fsm.restore, **FAST)
            addrs = {n.name: n.addr for n in nodes if n is not follower}
            addrs[reborn.name] = reborn.addr
            reborn.set_peers(addrs)
            reborn.start()
            for n in nodes:
                if n is not follower:
                    n.set_peers(addrs)
            wait_for(lambda: fsm.data.get("k39") == 39,
                     msg="snapshot install + catch-up")
            reborn.stop()
        finally:
            for n in nodes:
                n.stop()

    def test_durable_restart_replays_log(self):
        with tempfile.TemporaryDirectory() as d:
            fsm = KVFSM()
            n = RaftNode("solo", ("127.0.0.1", 0), fsm_apply=fsm.apply,
                         fsm_snapshot=fsm.snapshot, fsm_restore=fsm.restore,
                         data_dir=d, **FAST)
            n.start()
            wait_for(lambda: n.is_leader(), msg="solo leader")
            for i in range(5):
                n.apply(pickle.dumps((f"k{i}", i)))
            term = n.term
            n.stop()

            fsm2 = KVFSM()
            n2 = RaftNode("solo", ("127.0.0.1", 0), fsm_apply=fsm2.apply,
                          fsm_snapshot=fsm2.snapshot,
                          fsm_restore=fsm2.restore, data_dir=d, **FAST)
            assert n2.term >= term
            assert len([e for e in n2.log if e.cmd]) == 5
            n2.start()
            wait_for(lambda: fsm2.data.get("k4") == 4, msg="log replay")
            n2.stop()

    def test_durable_restart_after_snapshot_install(self):
        """A follower that catches up via snapshot install must survive a
        restart: the durable log header is the snapshot's only home, so
        every log rewrite must embed it (regression: _persist_log wrote
        snapshot=None after install, leaving snap_index > 0 with no bytes
        to restore — FSM silently empty after restart)."""
        with tempfile.TemporaryDirectory() as d:
            fsms = [KVFSM() for _ in range(3)]
            dirs = [None, None, d]   # only the lagging follower durable
            nodes = [RaftNode(f"s{i}", ("127.0.0.1", 0),
                              fsm_apply=fsms[i].apply,
                              fsm_snapshot=fsms[i].snapshot,
                              fsm_restore=fsms[i].restore,
                              data_dir=dirs[i],
                              max_log_entries=8, **FAST)
                     for i in range(3)]
            addrs = {n.name: n.addr for n in nodes}
            for n in nodes:
                n.set_peers(addrs)
                n.start()
            try:
                wait_for(lambda: leader_of(nodes), msg="leader")
                nodes[2].stop()   # works whether or not s2 won
                leader = wait_for(lambda: leader_of(nodes[:2]),
                                  msg="leader among s0/s1")
                for i in range(40):
                    leader.apply(pickle.dumps((f"k{i}", i)))
                wait_for(lambda: leader.snap_index > 0, msg="compaction")
                # reborn follower catches up via snapshot install
                fsm = KVFSM()
                reborn = RaftNode("s2", ("127.0.0.1", 0),
                                  fsm_apply=fsm.apply,
                                  fsm_snapshot=fsm.snapshot,
                                  fsm_restore=fsm.restore,
                                  data_dir=d, max_log_entries=8, **FAST)
                addrs2 = {n.name: n.addr for n in nodes[:2]}
                addrs2["s2"] = reborn.addr
                reborn.set_peers(addrs2)
                for n in nodes[:2]:
                    n.set_peers(addrs2)
                reborn.start()
                wait_for(lambda: fsm.data.get("k39") == 39,
                         msg="snapshot install")
                reborn.stop()
                # restart from the same data_dir: the installed snapshot
                # must come back from disk
                fsm2 = KVFSM()
                again = RaftNode("s2", ("127.0.0.1", 0),
                                 fsm_apply=fsm2.apply,
                                 fsm_snapshot=fsm2.snapshot,
                                 fsm_restore=fsm2.restore,
                                 data_dir=d, max_log_entries=8, **FAST)
                assert again.snap_index > 0
                # the regression left snap_index > 0 with NO snapshot
                # bytes: last_applied stuck at 0, FSM empty.  Entries
                # past snap_index stay unapplied until a leader confirms
                # commit (the node is not started here) — so assert the
                # snapshot itself came back, not the full k39 tail.
                assert again.last_applied == again.snap_index, \
                    "snapshot lost on restart (durable header missing it)"
                assert fsm2.data.get("k0") == 0
                # snapshot covers everything up to snap_index (minus the
                # leadership noop barrier entries)
                assert len(fsm2.data) >= again.snap_index - 3
            finally:
                for n in nodes[:2]:
                    n.stop()

    def test_compaction_keeps_replication_tail(self):
        """After compaction the leader retains an in-memory tail of
        compacted entries so a slightly-lagging follower gets a normal
        append, not a full snapshot transfer."""
        fsm = KVFSM()
        n = RaftNode("tail", ("127.0.0.1", 0), fsm_apply=fsm.apply,
                     fsm_snapshot=fsm.snapshot, fsm_restore=fsm.restore,
                     max_log_entries=8, **FAST)
        n.start()
        try:
            wait_for(lambda: n.is_leader(), msg="solo leader")
            for i in range(40):
                n.apply(pickle.dumps((f"k{i}", i)))
            wait_for(lambda: n.snap_index > 0, msg="compaction")
            with n._lock:
                tail = list(n._tail)
                snap_index = n.snap_index
            assert tail, "no replication tail retained"
            assert tail[-1].index == snap_index
            # contiguous, ending at the compaction point
            for a, b in zip(tail, tail[1:]):
                assert b.index == a.index + 1
            # a follower within the tail window gets an append
            nxt = tail[0].index + 1
            with n._lock:
                msg = n._tail_append_msg(nxt)
            assert msg is not None and msg["type"] == "append"
            assert msg["prev_idx"] == nxt - 1
            assert msg["entries"][0][1] == nxt
            # a follower before the tail window falls back to snapshot
            with n._lock:
                assert n._tail_append_msg(tail[0].index) is None
        finally:
            n.stop()

    def test_lagging_follower_catches_up_via_tail_append(self):
        """A durable follower restarting just behind the compaction point
        catches up from the replication tail WITHOUT a snapshot install
        (restore-count stays zero)."""
        with tempfile.TemporaryDirectory() as d:
            fsms = [KVFSM() for _ in range(3)]
            dirs = [None, None, d]
            nodes = [RaftNode(f"s{i}", ("127.0.0.1", 0),
                              fsm_apply=fsms[i].apply,
                              fsm_snapshot=fsms[i].snapshot,
                              fsm_restore=fsms[i].restore,
                              data_dir=dirs[i],
                              max_log_entries=20, **FAST)
                     for i in range(3)]
            addrs = {n.name: n.addr for n in nodes}
            for n in nodes:
                n.set_peers(addrs)
                n.start()
            try:
                leader = wait_for(lambda: leader_of(nodes), msg="leader")
                for i in range(15):
                    leader.apply(pickle.dumps((f"k{i}", i)))
                wait_for(lambda: fsms[2].data.get("k14") == 14,
                         msg="follower caught up to 15")
                nodes[2].stop()   # works whether or not s2 won
                leader = wait_for(lambda: leader_of(nodes[:2]),
                                  msg="leader among s0/s1")
                # push past compaction: keep-window is 10, follower is
                # ~7 entries behind the cut -> inside the tail
                for i in range(15, 22):
                    leader.apply(pickle.dumps((f"k{i}", i)))
                wait_for(lambda: leader.snap_index > 0, msg="compaction")
                restores = []
                fsm = KVFSM()
                orig_restore = fsm.restore

                def counting_restore(data):
                    restores.append(1)
                    orig_restore(data)

                reborn = RaftNode("s2", ("127.0.0.1", 0),
                                  fsm_apply=fsm.apply,
                                  fsm_snapshot=fsm.snapshot,
                                  fsm_restore=counting_restore,
                                  data_dir=d, max_log_entries=20, **FAST)
                boot_restores = len(restores)   # disk replay, not wire
                addrs2 = {n.name: n.addr for n in nodes[:2]}
                addrs2["s2"] = reborn.addr
                reborn.set_peers(addrs2)
                for n in nodes[:2]:
                    n.set_peers(addrs2)
                reborn.start()
                wait_for(lambda: fsm.data.get("k21") == 21,
                         msg="tail catch-up")
                assert len(restores) == boot_restores, \
                    "caught up via snapshot install, not tail append"
                reborn.stop()
            finally:
                for n in nodes[:2]:
                    n.stop()


# ------------------------------------------------------------------- gossip


class TestGossip:
    def test_join_and_failure_detection(self):
        g1 = Gossip("a", ("127.0.0.1", 0), probe_interval=0.1,
                    suspect_timeout=0.4)
        g2 = Gossip("b", ("127.0.0.1", 0), probe_interval=0.1,
                    suspect_timeout=0.4)
        g3 = Gossip("c", ("127.0.0.1", 0), probe_interval=0.1,
                    suspect_timeout=0.4)
        for g in (g1, g2, g3):
            g.start()
        assert g2.join(g1.addr)
        assert g3.join(g1.addr)
        try:
            wait_for(lambda: len(g1.alive_members()) == 3
                     and len(g3.alive_members()) == 3, msg="convergence")
            g2.stop()
            wait_for(lambda: "b" not in g1.alive_members(),
                     msg="failure detection")
        finally:
            for g in (g1, g3):
                g.stop()


# ------------------------------------------------------------ full cluster


@pytest.fixture
def trio():
    s1 = ClusterServer("s1", autopilot_grace=1.0, bootstrap_expect=3,
                       **FAST)
    s2 = ClusterServer("s2", autopilot_grace=1.0, bootstrap_expect=3,
                       **FAST)
    s3 = ClusterServer("s3", autopilot_grace=1.0, bootstrap_expect=3,
                       **FAST)
    s1.start(tick_interval=0.2)
    s2._join_seeds = [s1.gossip.addr]
    s3._join_seeds = [s1.gossip.addr]
    s2.start(tick_interval=0.2)
    s3.start(tick_interval=0.2)
    servers = [s1, s2, s3]
    yield servers
    for s in servers:
        try:
            s.shutdown()
        except Exception:
            pass


def cluster_leader(servers):
    leaders = [s for s in servers if s.is_leader()]
    return leaders[0] if len(leaders) == 1 else None


class TestClusterServer:
    # slow: multi-second wall-clock runs over real TCP.  The same
    # behaviors run in virtual time in tests/test_chaos.py (workload
    # forwarding + replication in every scenario; failover-keeps-
    # scheduling is the leader_partition scenario) — ci.sh's chaos
    # stage executes both this class and the chaos suite.
    @pytest.mark.slow
    def test_replicated_scheduling_with_forwarding(self, trio):
        leader = wait_for(lambda: cluster_leader(trio), msg="leader")
        follower = next(s for s in trio if s is not leader)

        # node + job registered THROUGH A FOLLOWER (forwarded to leader)
        rpc = RemoteRPC([follower.rpc.addr])
        node = mock.node()
        rpc.register_node(node)
        job = mock.job()
        job.task_groups[0].count = 3
        rpc.call("register_job", job)

        # the leader schedules; state replicates to every server
        def placed_everywhere():
            return all(
                len([a for a in s.state.allocs_by_job("default", job.id)
                     if not a.terminal_status()]) == 3
                for s in trio)
        wait_for(placed_everywhere, msg="replicated placement")

        # follower reads agree with leader reads
        f_allocs = follower.state.allocs_by_job("default", job.id)
        l_allocs = leader.state.allocs_by_job("default", job.id)
        assert {a.id for a in f_allocs} == {a.id for a in l_allocs}

    @pytest.mark.slow
    def test_leader_failover_keeps_scheduling(self, trio):
        leader = wait_for(lambda: cluster_leader(trio), msg="leader")
        rpc = RemoteRPC([s.rpc.addr for s in trio])
        node = mock.node()
        rpc.register_node(node)
        job1 = mock.job()
        job1.task_groups[0].count = 2
        rpc.call("register_job", job1)
        wait_for(lambda: len(leader.state.allocs_by_job(
            "default", job1.id)) == 2, msg="initial placement")

        leader.shutdown()
        rest = [s for s in trio if s is not leader]
        new_leader = wait_for(lambda: cluster_leader(rest),
                              msg="failover leader")

        # autopilot reaps the dead server once grace passes
        wait_for(lambda: leader.name not in new_leader.raft.peers,
                 timeout=10.0, msg="autopilot reap")

        job2 = mock.job()
        job2.task_groups[0].count = 2
        rpc.call("register_job", job2)
        wait_for(lambda: all(
            len(s.state.allocs_by_job("default", job2.id)) == 2
            for s in rest), msg="post-failover placement")
        # pre-failover state survived
        assert all(len(s.state.allocs_by_job("default", job1.id)) == 2
                   for s in rest)


@requires_crypto
class TestEncryptedCluster:
    def test_encrypted_cluster_forms_and_schedules(self):
        """A cluster with the `encrypt` key set must elect, forward
        follower writes, and schedule — every raft/gossip/RPC frame rides
        the authenticated channel-bound wire (core/wire.py).  This is the
        end-to-end proof the per-frame unit tests can't give."""
        import time as _t

        from nomad_tpu import mock
        from nomad_tpu.core import wire
        from nomad_tpu.core.cluster import ClusterServer

        wire.set_key("cluster-e2e-secret", force=True)
        servers = []
        try:
            s1 = ClusterServer("enc-1", bootstrap_expect=2,
                               heartbeat_interval=0.05,
                               election_timeout=(0.2, 0.4))
            s1.start(tick_interval=0.2)
            servers.append(s1)   # appended as started: a failure
            s2 = ClusterServer("enc-2", bootstrap_expect=2,
                               join=[s1.gossip.addr],
                               heartbeat_interval=0.05,
                               election_timeout=(0.2, 0.4))
            s2.start(tick_interval=0.2)
            servers.append(s2)   # mid-setup still shuts down s1
            deadline = _t.time() + 20
            leader = None
            while _t.time() < deadline and leader is None:
                leader = next((s for s in servers if s.is_leader()), None)
                _t.sleep(0.05)
            assert leader is not None, "no leader on encrypted wire"
            follower = next(s for s in servers if s is not leader)
            deadline = _t.time() + 10
            while (_t.time() < deadline
                   and follower.leader_rpc_addr() is None):
                _t.sleep(0.05)
            # write through the follower: rpc-channel forwarding frames
            follower.register_node(mock.node())
            job = mock.batch_job()
            job.task_groups[0].count = 2
            follower.register_job(job)
            deadline = _t.time() + 20
            placed = 0
            while _t.time() < deadline:
                # re-resolve per iteration: short election timeouts can
                # flip leadership mid-test and a stale leader pointer
                # would poll a stepped-down node's frozen state forever
                cur = next((s for s in servers if s.is_leader()), leader)
                placed = len([
                    a for a in cur.state.snapshot()
                    .allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()])
                if placed == 2:
                    break
                _t.sleep(0.1)
            assert placed == 2
            # replication carried the state to the follower too
            deadline = _t.time() + 10
            while _t.time() < deadline:
                if len(follower.state.snapshot().allocs_by_job(
                        job.namespace, job.id)) >= 2:
                    break
                _t.sleep(0.1)
            assert len(follower.state.snapshot().allocs_by_job(
                job.namespace, job.id)) >= 2
        finally:
            for s in servers:
                s.shutdown()
            wire.set_key(None)
