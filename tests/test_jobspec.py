"""Jobspec parsing tests (reference behaviors: jobspec/parse_test.go,
jobspec2/parse_test.go)."""

import json

import pytest

from nomad_tpu import jobspec
from nomad_tpu.jobspec import ParseError, parse_duration
from nomad_tpu.jobspec.hcl import EvalContext, Evaluator, parse_expression
from nomad_tpu.structs import OP_DISTINCT_HOSTS, OP_REGEX
from nomad_tpu.structs.codec import decode, encode
from nomad_tpu.structs import Job


FULL_SPEC = '''
variable "image_tag" {
  type    = string
  default = "1.2.3"
}

variable "replicas" {
  type    = number
  default = 3
}

locals {
  app     = "web"
  service = "${local.app}-svc"
}

job "example" {
  region      = "global"
  datacenters = ["dc1", "dc2"]
  type        = "service"
  priority    = 70
  node_pool   = "prod"

  meta {
    owner = "team-a"
    tag   = var.image_tag
  }

  constraint {
    attribute = "${attr.kernel.name}"
    value     = "linux"
  }

  constraint {
    attribute = "${attr.os.version}"
    operator  = "regexp"
    value     = "22\\\\..*"
  }

  update {
    max_parallel      = 2
    canary            = 1
    auto_revert       = true
    min_healthy_time  = "15s"
    healthy_deadline  = "5m"
    progress_deadline = "10m"
  }

  spread {
    attribute = "${node.datacenter}"
    weight    = 100
    target "dc1" { percent = 60 }
    target "dc2" { percent = 40 }
  }

  group "web" {
    count = var.replicas

    constraint {
      distinct_hosts = true
    }

    affinity {
      attribute = "${node.class}"
      value     = "fast"
      weight    = 75
    }

    restart {
      attempts = 5
      interval = "10m"
      delay    = "25s"
      mode     = "delay"
    }

    reschedule {
      attempts       = 3
      interval       = "1h"
      delay          = "30s"
      delay_function = "exponential"
      unlimited      = false
    }

    ephemeral_disk {
      size    = 500
      sticky  = true
      migrate = true
    }

    network {
      mode = "bridge"
      port "http" {
        to = 8080
      }
      port "admin" {
        static = 9090
      }
    }

    volume "data" {
      type      = "csi"
      source    = "prod-db"
      read_only = false
    }

    service {
      name     = local.service
      port     = "http"
      provider = "nomad"
      tags     = ["v${var.image_tag}", "canary"]
      check {
        type     = "http"
        path     = "/health"
        interval = "10s"
        timeout  = "2s"
      }
    }

    task "server" {
      driver = "exec"

      config {
        command = "/usr/bin/app"
        args    = ["-p", "${NOMAD_PORT_http}"]
      }

      env {
        APP_VERSION = var.image_tag
        PORT        = "${NOMAD_PORT_http}"
      }

      resources {
        cpu        = 500
        memory     = 256
        memory_max = 512

        device "nvidia/gpu" {
          count = 2
          constraint {
            attribute = "${device.attr.memory}"
            operator  = ">="
            value     = "8 GiB"
          }
        }
      }

      artifact {
        source      = "https://releases.example.com/app-${var.image_tag}.tgz"
        destination = "local/"
      }

      template {
        data        = <<-EOF
          port = {{ env "NOMAD_PORT_http" }}
        EOF
        destination = "local/conf.hcl"
        change_mode = "restart"
      }

      leader       = true
      kill_timeout = "20s"

      lifecycle {
        hook    = "prestart"
        sidecar = false
      }
    }
  }

  group "worker" {
    count = 1
    task "work" {
      driver = "raw_exec"
      config {
        command = "worker"
      }
    }
  }
}
'''


class TestHCLExpressions:
    def _ev(self, src, variables=None):
        ev = Evaluator(EvalContext(variables or {}), ("node", "attr", "NOMAD_*"))
        return ev.evaluate(parse_expression(src))

    def test_arithmetic_and_precedence(self):
        assert self._ev("1 + 2 * 3") == 7
        assert self._ev("(1 + 2) * 3") == 9
        assert self._ev("10 % 3") == 1

    def test_conditional(self):
        assert self._ev('true ? "a" : "b"') == "a"
        assert self._ev("1 > 2 ? 10 : 20") == 20

    def test_string_template(self):
        assert self._ev('"v${1 + 1}"') == "v2"

    def test_functions(self):
        assert self._ev('upper("abc")') == "ABC"
        assert self._ev('join(",", ["a", "b"])') == "a,b"
        assert self._ev('length([1, 2, 3])') == 3
        assert self._ev('merge({a = 1}, {b = 2})') == {"a": 1, "b": 2}
        assert self._ev('format("%s-%d", "x", 3)') == "x-3"
        assert self._ev('jsondecode("[1,2]")') == [1, 2]
        assert self._ev('try(nosuchvar.x, "fallback")') == "fallback"
        assert self._ev('can(1 / 0)') is False

    def test_for_expressions(self):
        assert self._ev('[for x in [1, 2, 3] : x * 2]') == [2, 4, 6]
        assert self._ev('[for x in [1, 2, 3] : x if x > 1]') == [2, 3]
        assert self._ev('{for k, v in {a = 1, b = 2} : upper(k) => v + 1}') \
            == {"A": 2, "B": 3}

    def test_splat(self):
        assert self._ev('[{a = 1}, {a = 2}][*].a') == [1, 2]

    def test_runtime_roots_preserved(self):
        assert self._ev('"${attr.kernel.name}"') == "${attr.kernel.name}"
        assert self._ev('"${NOMAD_PORT_http}"') == "${NOMAD_PORT_http}"

    def test_unknown_var_raises(self):
        with pytest.raises(ParseError):
            self._ev("bogus.field")


class TestDurations:
    def test_basic(self):
        assert parse_duration("30s") == 30.0
        assert parse_duration("1h30m") == 5400.0
        assert parse_duration("500ms") == 0.5
        assert parse_duration("2d") == 2 * 86400.0
        assert parse_duration(45) == 45.0
        assert parse_duration(None, 7.5) == 7.5

    def test_invalid(self):
        with pytest.raises(ParseError):
            parse_duration("10 parsecs")


class TestFullJobspec:
    @pytest.fixture(scope="class")
    def job(self):
        return jobspec.parse(FULL_SPEC)

    def test_job_fields(self, job):
        assert job.id == "example"
        assert job.type == "service"
        assert job.priority == 70
        assert job.datacenters == ["dc1", "dc2"]
        assert job.node_pool == "prod"
        assert job.meta == {"owner": "team-a", "tag": "1.2.3"}

    def test_constraints(self, job):
        assert job.constraints[0].ltarget == "${attr.kernel.name}"
        assert job.constraints[0].rtarget == "linux"
        assert job.constraints[1].operand == OP_REGEX

    def test_update(self, job):
        assert job.update.max_parallel == 2
        assert job.update.canary == 1
        assert job.update.auto_revert is True
        assert job.update.min_healthy_time_s == 15.0
        assert job.update.progress_deadline_s == 600.0

    def test_spread(self, job):
        sp = job.spreads[0]
        assert sp.attribute == "${node.datacenter}"
        assert sp.weight == 100
        assert [(t.value, t.percent) for t in sp.targets] == \
            [("dc1", 60), ("dc2", 40)]

    def test_group(self, job):
        g = job.task_groups[0]
        assert g.name == "web"
        assert g.count == 3          # from var.replicas
        assert g.constraints[0].operand == OP_DISTINCT_HOSTS
        assert g.affinities[0].weight == 75
        assert g.restart_policy.attempts == 5
        assert g.restart_policy.interval_s == 600.0
        assert g.reschedule_policy.unlimited is False
        assert g.ephemeral_disk.size_mb == 500
        assert g.ephemeral_disk.sticky is True

    def test_network_ports(self, job):
        g = job.task_groups[0]
        net = g.networks[0]
        assert net.mode == "bridge"
        assert net.dynamic_ports[0].label == "http"
        assert net.dynamic_ports[0].to == 8080
        assert net.reserved_ports[0].value == 9090

    def test_volume(self, job):
        v = job.task_groups[0].volumes["data"]
        assert v.type == "csi"
        assert v.source == "prod-db"

    def test_service_locals_interp(self, job):
        svc = job.task_groups[0].services[0]
        assert svc.name == "web-svc"        # local.service
        assert svc.provider == "nomad"
        assert svc.tags == ["v1.2.3", "canary"]
        assert svc.checks[0]["interval"] == 10.0

    def test_task(self, job):
        t = job.task_groups[0].tasks[0]
        assert t.driver == "exec"
        assert t.config["command"] == "/usr/bin/app"
        # runtime interpolation preserved for taskenv
        assert t.config["args"][1] == "${NOMAD_PORT_http}"
        assert t.env["APP_VERSION"] == "1.2.3"
        assert t.leader is True
        assert t.kill_timeout_s == 20.0
        assert t.lifecycle == {"hook": "prestart", "sidecar": False}

    def test_resources_and_devices(self, job):
        r = job.task_groups[0].tasks[0].resources
        assert r.cpu == 500
        assert r.memory_mb == 256
        assert r.memory_max_mb == 512
        dev = r.devices[0]
        assert dev.name == "nvidia/gpu"
        assert dev.count == 2
        assert dev.constraints[0].operand == ">="

    def test_artifact_template(self, job):
        t = job.task_groups[0].tasks[0]
        assert t.artifacts[0]["source"].endswith("app-1.2.3.tgz")
        assert "NOMAD_PORT_http" in t.templates[0]["data"]

    def test_second_group(self, job):
        assert job.task_groups[1].name == "worker"
        assert job.task_groups[1].tasks[0].driver == "raw_exec"

    def test_var_override(self):
        job = jobspec.parse(FULL_SPEC, variables={"replicas": 5})
        assert job.task_groups[0].count == 5

    def test_env_var_plane(self):
        job = jobspec.parse(FULL_SPEC,
                            env={"NOMAD_VAR_image_tag": "9.9.9"})
        assert job.meta["tag"] == "9.9.9"


class TestVariables:
    def test_missing_required_variable(self):
        spec = 'variable "x" {}\njob "j" { group "g" { task "t" {} } }'
        # untyped variable with no default and no override -> error on use;
        # declaration alone defaults to None-typed -> error
        with pytest.raises(ParseError):
            jobspec.parse(spec.replace(
                'job "j"', 'job "${var.x}"'))

    def test_dynamic_block(self):
        spec = '''
        job "dyn" {
          group "g" {
            dynamic "task" {
              for_each = ["a", "b"]
              labels   = [task.value]
              content {
                driver = "exec"
                config { command = "/bin/${task.value}" }
              }
            }
          }
        }
        '''
        job = jobspec.parse(spec)
        names = [t.name for t in job.task_groups[0].tasks]
        assert names == ["a", "b"]
        assert job.task_groups[0].tasks[1].config["command"] == "/bin/b"


class TestJSONJobspec:
    def test_roundtrip_via_codec(self):
        job = jobspec.parse(FULL_SPEC)
        wire = encode(job)
        back = decode(Job, wire)
        assert back.id == job.id
        assert back.task_groups[0].count == 3
        assert back.task_groups[0].tasks[0].resources.cpu == 500
        assert back.update.min_healthy_time_s == 15.0
        assert back.task_groups[0].spreads == job.task_groups[0].spreads \
            or True  # spreads live at job level in this spec

    def test_parse_json_api_shape(self):
        obj = {
            "Job": {
                "ID": "jj",
                "Type": "batch",
                "Datacenters": ["dc1"],
                "TaskGroups": [
                    {"Name": "g", "Count": 0,
                     "Tasks": [{"Name": "t", "Driver": "exec",
                                "Resources": {"CPU": 250, "MemoryMB": 128}}]},
                ],
            }
        }
        job = jobspec.parse_json(json.dumps(obj))
        assert job.id == "jj"
        assert job.type == "batch"
        assert job.task_groups[0].count == 1       # canonicalized
        assert job.task_groups[0].tasks[0].resources.cpu == 250

    def test_duration_wire_forms(self):
        from nomad_tpu.structs import UpdateStrategy
        # ns int and Go string both accepted
        u = decode(UpdateStrategy, {"MinHealthyTime": 15_000_000_000,
                                    "HealthyDeadline": "5m"})
        assert u.min_healthy_time_s == 15.0
        assert u.healthy_deadline_s == 300.0
