"""Deployment lifecycle: rolling updates, canaries, promotion, auto-revert,
progress deadline (reference: nomad/deploymentwatcher/ +
scheduler/reconcile.go canary/rolling semantics)."""

import copy

from nomad_tpu import mock
from nomad_tpu.core import Server
from nomad_tpu.structs import (
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    UpdateStrategy,
)

NOW = 1000.0


def _service_job(count=4, **update_kw):
    j = mock.job()
    j.task_groups[0].count = count
    j.update = UpdateStrategy(max_parallel=1, progress_deadline_s=600.0,
                              **update_kw)
    return j


def _mutate(job):
    """New version of `job` requiring destructive updates."""
    j2 = copy.deepcopy(job)
    j2.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    return j2


def _live(server, job):
    return [a for a in server.state.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status()]


def _set_health(server, allocs, healthy=True):
    ups = []
    for a in allocs:
        u = a.copy_skip_job()
        u.client_status = "running"
        u.deployment_status = {"healthy": healthy, "ts": NOW}
        ups.append(u)
    server.state.update_allocs_from_client(ups)


def _drive_to_completion(s, job, now=NOW, rounds=30):
    """process evals + mark new-version allocs healthy + tick, until the
    active deployment leaves the running state."""
    for i in range(rounds):
        s.process_all(now=now + i)
        dep = s.state.latest_deployment_by_job(job.namespace, job.id)
        if dep is None or dep.status != DEPLOYMENT_STATUS_RUNNING:
            return dep
        fresh = [a for a in _live(s, job)
                 if a.deployment_id == dep.id
                 and not (a.deployment_status or {}).get("healthy")]
        _set_health(s, fresh, healthy=True)
        s.deployments.tick(now=now + i)
    return s.state.latest_deployment_by_job(job.namespace, job.id)


def _stable_v0(s, job):
    """Initial registration driven to a successful deployment."""
    s.register_job(job, now=NOW)
    dep = _drive_to_completion(s, job)
    assert dep is not None and dep.status == DEPLOYMENT_STATUS_SUCCESSFUL
    assert s.state.job_by_id(job.namespace, job.id).stable
    return dep


class TestRollingUpdate:
    def test_initial_deploy_completes_and_marks_stable(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)
        assert len(_live(s, job)) == 4

    def test_rolling_is_health_gated_by_max_parallel(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)

        v1 = _mutate(job)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        new = [a for a in _live(s, v1) if a.job_version == 1]
        assert len(new) == 1, "first wave must respect max_parallel=1"

        # a second eval without health progress must NOT widen the wave
        s.apply_eval_update([mock.eval(job_id=v1.id, type=v1.type)],
                            now=NOW + 101)
        s.process_all(now=NOW + 101)
        assert len([a for a in _live(s, v1) if a.job_version == 1]) == 1, \
            "unhealthy in-flight wave consumes the max_parallel budget"

        dep = _drive_to_completion(s, v1, now=NOW + 110)
        assert dep.status == DEPLOYMENT_STATUS_SUCCESSFUL
        final = _live(s, v1)
        assert len(final) == 4
        assert all(a.job_version == dep.job_version for a in final)

    def test_unhealthy_alloc_fails_deployment(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)

        v1 = _mutate(job)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        new = [a for a in _live(s, v1) if a.deployment_id == dep.id]
        _set_health(s, new, healthy=False)
        s.deployments.tick(now=NOW + 101)
        dep = s.state.deployment_by_id(dep.id)
        assert dep.status == DEPLOYMENT_STATUS_FAILED
        assert "unhealthy" in dep.status_description.lower()


class TestCanaries:
    def _setup(self, auto_promote=False, auto_revert=False):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(8):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)
        v1 = _mutate(job)
        v1.update = UpdateStrategy(max_parallel=1, canary=1,
                                   auto_promote=auto_promote,
                                   auto_revert=auto_revert,
                                   progress_deadline_s=600.0)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        return s, v1

    def test_canary_placed_alongside_old_version(self):
        s, v1 = self._setup()
        live = _live(s, v1)
        old = [a for a in live if a.job_version == 0]
        new = [a for a in live if a.job_version == 1]
        assert len(old) == 4, "old version must keep running"
        assert len(new) == 1, "exactly `canary` new-version allocs"
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        st = dep.task_groups["web"]
        assert st.desired_canaries == 1
        assert st.placed_canaries == [new[0].id]
        assert not st.promoted

    def test_unpromoted_deployment_does_not_roll(self):
        s, v1 = self._setup()
        canaries = [a for a in _live(s, v1) if a.job_version == 1]
        _set_health(s, canaries, healthy=True)
        s.deployments.tick(now=NOW + 101)
        s.process_all(now=NOW + 101)
        live = _live(s, v1)
        assert len([a for a in live if a.job_version == 1]) == 1, \
            "no rollout before promotion"

    def test_manual_promote_then_rollout(self):
        s, v1 = self._setup()
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        canaries = [a for a in _live(s, v1) if a.job_version == 1]

        err = s.deployments.promote(dep.id, now=NOW + 101)
        assert err == "canaries are not healthy"

        _set_health(s, canaries, healthy=True)
        err = s.deployments.promote(dep.id, now=NOW + 102)
        assert err is None
        dep = s.state.deployment_by_id(dep.id)
        assert dep.task_groups["web"].promoted

        final_dep = _drive_to_completion(s, v1, now=NOW + 110)
        assert final_dep.status == DEPLOYMENT_STATUS_SUCCESSFUL
        live = _live(s, v1)
        assert len(live) == 4
        assert all(a.job_version == dep.job_version for a in live)

    def test_auto_promote(self):
        s, v1 = self._setup(auto_promote=True)
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        canaries = [a for a in _live(s, v1) if a.job_version == 1]
        _set_health(s, canaries, healthy=True)
        s.deployments.tick(now=NOW + 101)
        dep = s.state.deployment_by_id(dep.id)
        assert dep.task_groups["web"].promoted


class TestAutoRevert:
    def test_unhealthy_reverts_to_stable_version(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)
        v0_cmd = job.task_groups[0].tasks[0].config["command"]

        v1 = _mutate(job)
        v1.update = UpdateStrategy(max_parallel=1, auto_revert=True,
                                   progress_deadline_s=600.0)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        new = [a for a in _live(s, v1) if a.deployment_id == dep.id]
        _set_health(s, new, healthy=False)
        s.deployments.tick(now=NOW + 101)

        dep = s.state.deployment_by_id(dep.id)
        assert dep.status == DEPLOYMENT_STATUS_FAILED
        assert "rolling back to job version 0" in dep.status_description

        cur = s.state.job_by_id(v1.namespace, v1.id)
        assert cur.version == 2, "revert mints a new version"
        assert cur.task_groups[0].tasks[0].config["command"] == v0_cmd
        # the revert eval reconciles the cluster back to the old spec
        s.process_all(now=NOW + 102)
        live = _live(s, v1)
        assert all(a.job is not None and
                   a.job.task_groups[0].tasks[0].config["command"] == v0_cmd
                   for a in live if a.job_version == 2)


class TestSupersededDeployment:
    def test_new_version_cancels_running_deployment(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)

        v1 = _mutate(job)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        dep_v1 = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        assert dep_v1.status == DEPLOYMENT_STATUS_RUNNING

        v2 = _mutate(v1)
        v2.task_groups[0].tasks[0].config = {"command": "/bin/true"}
        s.register_job(v2, now=NOW + 110)
        s.process_all(now=NOW + 110)
        old = s.state.deployment_by_id(dep_v1.id)
        assert old.status == "cancelled"
        cur = s.state.latest_deployment_by_job(v2.namespace, v2.id)
        assert cur.id != dep_v1.id
        assert cur.status == DEPLOYMENT_STATUS_RUNNING


class TestReviewRegressions:
    def test_replacement_after_success_does_not_restart_deployment(self):
        # A node failure after a successful deployment must not mint a
        # fresh deployment (whose progress deadline would later fail and
        # auto-revert a perfectly healthy job).
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = [mock.node() for _ in range(6)]
        for n in nodes:
            s.register_node(n, now=NOW)
        job = _service_job(auto_revert=True)
        dep0 = _stable_v0(s, job)

        victim = _live(s, job)[0]
        s.update_node_status(victim.node_id, "down", now=NOW + 50)
        s.process_all(now=NOW + 50)
        assert len(_live(s, job)) == 4, "replacement placed"
        cur = s.state.latest_deployment_by_job(job.namespace, job.id)
        assert cur.id == dep0.id and cur.status == DEPLOYMENT_STATUS_SUCCESSFUL
        # far-future tick: nothing to deadline-fail, job not reverted
        s.deployments.tick(now=NOW + 10000)
        assert s.state.job_by_id(job.namespace, job.id).version == 0

    def test_failed_canary_is_refilled_not_replaced(self):
        # A failed canary must be replaced by a NEW canary, not stop a
        # healthy old-version alloc / mint an untagged new-version alloc.
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(8):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)
        v1 = _mutate(job)
        v1.update = UpdateStrategy(max_parallel=1, canary=1,
                                   progress_deadline_s=600.0)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        canary = [a for a in _live(s, v1) if a.job_version == 1][0]

        u = canary.copy_skip_job()
        u.client_status = "failed"
        s.state.update_allocs_from_client([u])
        s.apply_eval_update([mock.eval(job_id=v1.id, type=v1.type)],
                            now=NOW + 101)
        s.process_all(now=NOW + 101)

        live = _live(s, v1)
        old = [a for a in live if a.job_version == 0]
        new = [a for a in live if a.job_version == 1]
        assert len(old) == 4, "old version untouched by canary failure"
        assert len(new) == 1, "exactly one replacement canary"
        assert new[0].id != canary.id
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        assert new[0].id in dep.task_groups["web"].placed_canaries

    def test_superseded_deployment_cancelled_without_successor(self):
        # Dropping the update stanza must still cancel the running
        # deployment (cancellation is unconditional, not tied to the
        # successor creating its own deployment).
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)
        v1 = _mutate(job)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        dep_v1 = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        assert dep_v1.status == DEPLOYMENT_STATUS_RUNNING

        v2 = _mutate(v1)
        v2.task_groups[0].tasks[0].config = {"command": "/bin/true"}
        v2.update = None
        v2.task_groups[0].update = None
        s.register_job(v2, now=NOW + 110)
        s.process_all(now=NOW + 110)
        assert s.state.deployment_by_id(dep_v1.id).status == "cancelled"


class TestProgressDeadline:
    def test_no_progress_fails_deployment(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        for _ in range(6):
            s.register_node(mock.node(), now=NOW)
        job = _service_job()
        _stable_v0(s, job)

        v1 = _mutate(job)
        v1.update = UpdateStrategy(max_parallel=1, progress_deadline_s=10.0)
        s.register_job(v1, now=NOW + 100)
        s.process_all(now=NOW + 100)
        s.deployments.tick(now=NOW + 101)    # arms the deadline
        dep = s.state.latest_deployment_by_job(v1.namespace, v1.id)
        assert dep.status == DEPLOYMENT_STATUS_RUNNING
        s.deployments.tick(now=NOW + 120)    # past deadline, no health
        dep = s.state.deployment_by_id(dep.id)
        assert dep.status == DEPLOYMENT_STATUS_FAILED
        assert "progress deadline" in dep.status_description.lower()
