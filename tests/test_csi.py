"""CSI volume scheduling: plugin presence, accessible topology, claims,
and the volume watcher (reference: scheduler/feasible.go CSIVolumeChecker,
nomad/volumewatcher/)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import CSIVolume, VolumeRequest

NOW = 1.7e9


def make_cluster(s, n=12, plugin="ebs0", plugin_on_all=True):
    nodes = []
    for i in range(n):
        nd = mock.node()
        if plugin_on_all or i % 2 == 0:
            nd.csi_node_plugins[plugin] = True
        s.register_node(nd, now=NOW)
        nodes.append(nd)
    return nodes


def csi_job(source, count=4, read_only=True):
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].volumes = {
        "data": VolumeRequest(name="data", type="csi", source=source,
                              read_only=read_only)}
    return job


class TestCSITopology:
    def test_topology_restricts_placement(self):
        """A volume accessible from a node subset must pull every claiming
        placement into that subset — the device-side feasibility mask, not
        just the plan-apply claim re-check."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = make_cluster(s, n=12)
        zone = tuple(nd.id for nd in nodes[:3])
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-z", plugin_id="ebs0", topology_node_ids=zone))
        job = csi_job("vol-z", count=6)
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 6
        assert {a.node_id for a in live} <= set(zone)

    def test_without_topology_any_plugin_node(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = make_cluster(s, n=8, plugin_on_all=False)  # every 2nd node
        s.state.upsert_csi_volume(CSIVolume(id="vol-a", plugin_id="ebs0"))
        job = csi_job("vol-a", count=4)
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 4
        plugin_nodes = {nd.id for i, nd in enumerate(nodes) if i % 2 == 0}
        assert {a.node_id for a in live} <= plugin_nodes

    def test_topology_exhaustion_blocks(self):
        """Topology narrower than demand: the surplus parks in a blocked
        eval; adding a node to the topology (volume re-registration)
        releases it."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = make_cluster(s, n=6)
        small = nodes[0]
        # tighten the node so only 2 allocs fit
        small.resources.cpu = 4000
        small.resources.memory_mb = 8192
        s.register_node(small, now=NOW)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-tight", plugin_id="ebs0",
            topology_node_ids=(small.id,)))
        job = csi_job("vol-tight", count=4)
        job.task_groups[0].tasks[0].resources.cpu = 1500
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 2
        assert s.blocked_evals.num_blocked() == 1
        # widen the topology: volume re-registration + node capacity event
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-tight", plugin_id="ebs0",
            topology_node_ids=(small.id, nodes[1].id)))
        s.register_node(nodes[1], now=NOW + 1)   # capacity signal
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 4
        assert {a.node_id for a in live} <= {small.id, nodes[1].id}

    def test_volume_watcher_reaps_vanished_alloc_claim(self):
        """A claim whose alloc was GC'd (never upserted terminal) is
        invisible to the store's terminal-release path — the watcher must
        reap it so the volume is schedulable again without operator
        action."""
        import dataclasses

        # big TTL: the test ticks far ahead to promote the delayed
        # follow-up eval, which must not expire the nodes' heartbeats
        s = Server(dev_mode=True, heartbeat_ttl=1e9)
        s.establish_leadership()
        make_cluster(s, n=4)
        vol = CSIVolume(id="vol-reap", plugin_id="ebs0",
                        access_mode="single-node-writer")
        # claim by an alloc id that does not exist in state (GC'd)
        vol = dataclasses.replace(vol,
                                  write_allocs={"ghost-alloc": True})
        s.state.upsert_csi_volume(vol)
        # single-writer with a ghost claim: a new write job cannot place
        j = csi_job("vol-reap", count=1, read_only=False)
        s.register_job(j, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        assert not [a for a in snap.allocs_by_job(j.namespace, j.id)
                    if not a.terminal_status()]
        # the watcher sweep releases the ghost claim -> schedulable
        released = s.volumes.tick(NOW + 1)
        assert released == 1
        vol2 = s.state.snapshot().csi_volume_by_id("default", "vol-reap")
        assert vol2.write_allocs == {}
        # the claim refusal happened at plan apply (refute -> retry
        # exhaustion -> failed eval + delayed follow-up), so advance past
        # the follow-up window: the tick promotes it and the job places
        s.tick(now=NOW + 400)
        s.process_all(now=NOW + 400)
        snap = s.state.snapshot()
        assert [a for a in snap.allocs_by_job(j.namespace, j.id)
                if not a.terminal_status()]

    def test_volume_watcher_unpublish_retry_backoff(self):
        """A failing unpublish (flaky storage controller) retries with
        backoff instead of releasing the claim or wedging the tick."""
        import dataclasses

        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=2)
        vol = dataclasses.replace(
            CSIVolume(id="vol-flaky", plugin_id="ebs0"),
            read_allocs={"ghost": True})
        s.state.upsert_csi_volume(vol)
        calls = []

        def flaky(v, alloc_id):
            calls.append(alloc_id)
            if len(calls) < 3:
                raise RuntimeError("controller timeout")

        s.volumes.unpublish = flaky
        assert s.volumes.tick(NOW) == 0          # fail #1 -> backoff
        assert s.volumes.tick(NOW + 0.1) == 0    # inside backoff: no call
        assert len(calls) == 1
        assert s.volumes.tick(NOW + 2) == 0      # fail #2, longer backoff
        assert s.volumes.tick(NOW + 2.5) == 0    # still backing off
        assert len(calls) == 2
        assert s.volumes.tick(NOW + 10) == 1     # succeeds, claim released
        v2 = s.state.snapshot().csi_volume_by_id("default", "vol-flaky")
        assert v2.read_allocs == {}
        assert s.volumes.stats["unpublish_failures"] == 2

    def test_volumes_survive_snapshot_roundtrip(self):
        """CSI volumes (with live claims and topology) must ride operator
        snapshots — they are scheduling state, and a restore that loses
        them leaves every volume-claiming job unschedulable."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        nodes = make_cluster(s, n=3)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-snap", plugin_id="ebs0",
            access_mode="single-node-writer",
            topology_node_ids=(nodes[0].id,)))
        job = csi_job("vol-snap", count=1, read_only=False)
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        vol = s.state.snapshot().csi_volume_by_id("default", "vol-snap")
        assert vol.write_allocs
        doc = s.save_snapshot()

        s2 = Server(dev_mode=True)
        s2.restore_snapshot(doc)
        vol2 = s2.state.snapshot().csi_volume_by_id("default", "vol-snap")
        assert vol2 is not None
        assert vol2.plugin_id == "ebs0"
        assert vol2.topology_node_ids == (nodes[0].id,)
        assert set(vol2.write_allocs) == set(vol.write_allocs)
        # a stale pre-restore volume must NOT survive into the restored
        # state (restore REPLACES, not merges)
        s3 = Server(dev_mode=True)
        s3.establish_leadership()
        s3.state.upsert_csi_volume(CSIVolume(id="ghost", plugin_id="x"))
        s3.restore_snapshot(doc)
        assert s3.state.snapshot().csi_volume_by_id("default",
                                                    "ghost") is None

    def test_single_writer_claim_refused_at_apply(self):
        """single-node-writer: the second job's write claim is refused at
        the serialization point even though feasibility passes."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=4)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-w", plugin_id="ebs0",
            access_mode="single-node-writer"))
        j1 = csi_job("vol-w", count=1, read_only=False)
        s.register_job(j1, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        assert [a for a in snap.allocs_by_job(j1.namespace, j1.id)
                if not a.terminal_status()]
        j2 = csi_job("vol-w", count=1, read_only=False)
        s.register_job(j2, now=NOW + 1)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        live2 = [a for a in snap.allocs_by_job(j2.namespace, j2.id)
                 if not a.terminal_status()]
        assert live2 == []

    def test_single_writer_two_claims_in_one_plan(self):
        """Two writers to a single-node-writer volume inside ONE plan:
        the applier must count in-plan claims, committing exactly one
        (VERDICT r3 weak #6: both were checked against the pre-plan
        claim set and both committed)."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=4)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-w1", plugin_id="ebs0",
            access_mode="single-node-writer"))
        # one job, count=2 -> both placements ride one plan
        j = csi_job("vol-w1", count=2, read_only=False)
        s.register_job(j, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(j.namespace, j.id)
                if not a.terminal_status()]
        assert len(live) == 1, [a.node_id for a in live]
        vol = snap.csi_volume_by_id("default", "vol-w1")
        assert len(vol.write_allocs) == 1

    def test_refuted_release_does_not_credit_new_writer(self):
        """A writer admitted on the credit of a release must not commit
        when the releasing node refutes (its stop is withheld): the old
        writer keeps running and the volume must not end up with two live
        write claims (code-review r4 finding)."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan, Resources

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        na, nb = mock.node(), mock.node()
        state.upsert_node(na)
        state.upsert_node(nb)
        vol = CSIVolume(id="vol-m", plugin_id="ebs0",
                        access_mode="single-node-writer")
        state.upsert_csi_volume(vol)
        job = csi_job("vol-m", count=1, read_only=False)
        state.upsert_job(job)
        # X: current writer, running on node B, holding the write claim
        x = mock.alloc(job=job, node_id=nb.id)
        x.task_group = job.task_groups[0].name
        state.upsert_allocs([x])
        plan0 = Plan(eval_id="seed", job=job)
        plan0.node_allocation[nb.id] = [state.alloc_by_id(x.id)]
        state.upsert_plan_results(plan0, applier.evaluate_plan(plan0))
        assert state.snapshot().csi_volume_by_id(
            "default", "vol-m").write_allocs

        # migration plan: stop X on B + overfitting replacement on B
        # (forces B to refute, withholding the stop), new writer Y on A
        plan = Plan(eval_id="mig", job=job)
        stopped = state.alloc_by_id(x.id).copy_skip_job()
        stopped.desired_status = "stop"
        plan.node_update[nb.id] = [stopped]
        big = mock.alloc(job=job, node_id=nb.id)
        big.task_group = job.task_groups[0].name
        big.resources = Resources(cpu=10 ** 9, memory_mb=10 ** 9)
        plan.node_allocation[nb.id] = [big]
        y = mock.alloc(job=job, node_id=na.id)
        y.task_group = job.task_groups[0].name
        plan.node_allocation[na.id] = [y]

        result = applier.evaluate_plan(plan)
        # B refuted (overfit) -> X's stop withheld -> Y must NOT be
        # admitted on the strength of that release
        assert nb.id in result.refuted_nodes
        assert na.id in result.refuted_nodes
        state.upsert_plan_results(plan, result)
        vol2 = state.snapshot().csi_volume_by_id("default", "vol-m")
        assert list(vol2.write_allocs) == [x.id]

    def test_release_credit_reaches_fixpoint_regardless_of_order(self):
        """Node A places a writer that needs node B's release, while A
        itself carries an unrelated stop (so no static ordering puts B
        first): the fixpoint pass must admit A after B accepts."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        na, nb = mock.node(), mock.node()
        state.upsert_node(na)
        state.upsert_node(nb)
        state.upsert_csi_volume(CSIVolume(
            id="vol-f", plugin_id="ebs0",
            access_mode="single-node-writer"))
        vjob = csi_job("vol-f", count=1, read_only=False)
        state.upsert_job(vjob)
        plain = mock.job()
        state.upsert_job(plain)
        # X: current writer on node B; U: unrelated alloc on node A
        x = mock.alloc(job=vjob, node_id=nb.id)
        x.task_group = vjob.task_groups[0].name
        u = mock.alloc(job=plain, node_id=na.id)
        state.upsert_allocs([x, u])
        seed = Plan(eval_id="seed", job=vjob)
        seed.node_allocation[nb.id] = [state.alloc_by_id(x.id)]
        state.upsert_plan_results(seed, applier.evaluate_plan(seed))

        plan = Plan(eval_id="mig", job=vjob)
        # node A FIRST in insertion order, carrying a stop of U (so the
        # releasing-first sort cannot separate A and B) + new writer Y
        ustop = state.alloc_by_id(u.id).copy_skip_job()
        ustop.desired_status = "stop"
        plan.node_update[na.id] = [ustop]
        y = mock.alloc(job=vjob, node_id=na.id)
        y.task_group = vjob.task_groups[0].name
        plan.node_allocation[na.id] = [y]
        # node B: stop X + unrelated replacement Z that fits
        xstop = state.alloc_by_id(x.id).copy_skip_job()
        xstop.desired_status = "stop"
        plan.node_update[nb.id] = [xstop]
        z = mock.alloc(job=plain, node_id=nb.id)
        plan.node_allocation[nb.id] = [z]

        result = applier.evaluate_plan(plan)
        assert result.refuted_nodes == []
        state.upsert_plan_results(plan, result)
        vol = state.snapshot().csi_volume_by_id("default", "vol-f")
        assert list(vol.write_allocs) == [y.id]

    def test_single_node_reader_only_pins_one_node(self):
        """single-node-* access modes attach to ONE node — READERS
        included (round-5 verdict #7): once the first reader claims, the
        feasibility pin routes every later reader to the same node."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=6)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-snro", plugin_id="ebs0",
            access_mode="single-node-reader-only"))
        r1 = csi_job("vol-snro", count=1, read_only=True)
        s.register_job(r1, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        first = [a for a in snap.allocs_by_job(r1.namespace, r1.id)
                 if not a.terminal_status()]
        assert len(first) == 1
        pinned = first[0].node_id
        r2 = csi_job("vol-snro", count=3, read_only=True)
        s.register_job(r2, now=NOW + 1)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        later = [a for a in snap.allocs_by_job(r2.namespace, r2.id)
                 if not a.terminal_status()]
        assert later and all(a.node_id == pinned for a in later), (
            pinned, [a.node_id for a in later])

    def test_single_node_readers_two_nodes_one_commits(self):
        """The verdict's adversarial case: ONE plan carrying readers of a
        single-node-reader-only volume on TWO different nodes — exactly
        one node's claim commits, the other is refused at the applier."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        na, nb = mock.node(), mock.node()
        state.upsert_node(na)
        state.upsert_node(nb)
        state.upsert_csi_volume(CSIVolume(
            id="vol-sn", plugin_id="ebs0",
            access_mode="single-node-reader-only"))
        job = csi_job("vol-sn", count=2, read_only=True)
        state.upsert_job(job)
        plan = Plan(eval_id="adv", job=job)
        for nd in (na, nb):
            a = mock.alloc(job=job, node_id=nd.id)
            a.task_group = job.task_groups[0].name
            plan.node_allocation[nd.id] = [a]
        result = applier.evaluate_plan(plan)
        committed = set(result.node_allocation)
        assert len(committed) == 1, committed
        assert len(result.refuted_nodes) == 1
        state.upsert_plan_results(plan, result)
        vol = state.snapshot().csi_volume_by_id("default", "vol-sn")
        assert len(vol.read_allocs) == 1
        assert len(vol.live_claim_nodes()) == 1

    def test_single_node_writer_joins_live_readers_node(self):
        """single-node-writer: a writer placed on a different node than
        the volume's LIVE readers is refused — the node axis binds across
        claim types (round-4 weak #5: writer-after-release could land
        anywhere)."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        na, nb = mock.node(), mock.node()
        state.upsert_node(na)
        state.upsert_node(nb)
        state.upsert_csi_volume(CSIVolume(
            id="vol-snw", plugin_id="ebs0",
            access_mode="single-node-writer"))
        rjob = csi_job("vol-snw", count=1, read_only=True)
        state.upsert_job(rjob)
        r = mock.alloc(job=rjob, node_id=na.id)
        r.task_group = rjob.task_groups[0].name
        plan0 = Plan(eval_id="seed", job=rjob)
        plan0.node_allocation[na.id] = [r]
        state.upsert_plan_results(plan0, applier.evaluate_plan(plan0))

        wjob = csi_job("vol-snw", count=1, read_only=False)
        state.upsert_job(wjob)
        w = mock.alloc(job=wjob, node_id=nb.id)       # WRONG node
        w.task_group = wjob.task_groups[0].name
        plan = Plan(eval_id="w", job=wjob)
        plan.node_allocation[nb.id] = [w]
        result = applier.evaluate_plan(plan)
        assert nb.id in result.refuted_nodes
        # on the readers' node it is admitted
        w2 = mock.alloc(job=wjob, node_id=na.id)
        w2.task_group = wjob.task_groups[0].name
        plan2 = Plan(eval_id="w2", job=wjob)
        plan2.node_allocation[na.id] = [w2]
        result2 = applier.evaluate_plan(plan2)
        assert na.id in result2.node_allocation

    def test_multi_node_single_writer_and_reader_only_modes(self):
        """multi-node-single-writer admits exactly one writer anywhere;
        reader-only modes refuse write claims outright."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=4)
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-mnsw", plugin_id="ebs0",
            access_mode="multi-node-single-writer"))
        s.state.upsert_csi_volume(CSIVolume(
            id="vol-ro", plugin_id="ebs0",
            access_mode="multi-node-reader-only"))
        w = csi_job("vol-mnsw", count=2, read_only=False)
        s.register_job(w, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(w.namespace, w.id)
                if not a.terminal_status()]
        assert len(live) == 1          # one writer, cluster-wide
        # a write claim against a reader-only volume never places
        bad = csi_job("vol-ro", count=1, read_only=False)
        s.register_job(bad, now=NOW + 1)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        assert [a for a in snap.allocs_by_job(bad.namespace, bad.id)
                if not a.terminal_status()] == []
        # readers against the same volume are fine
        ok = csi_job("vol-ro", count=2, read_only=True)
        s.register_job(ok, now=NOW + 2)
        s.process_all(now=NOW + 2)
        snap = s.state.snapshot()
        assert len([a for a in snap.allocs_by_job(ok.namespace, ok.id)
                    if not a.terminal_status()]) == 2


class TestColumnarBlockClaims:
    """Block-granular claim ledger: a columnar commit appends ONE
    read_blocks entry per volume instead of O(members) dict entries —
    the claim ledger's COW cost scales with blocks, not claim history
    (no reference analog; the per-alloc semantics it compresses are
    nomad/structs/csi.go claims)."""

    def _place_block(self, s, source="vol-b", count=80):
        make_cluster(s, n=8)
        s.state.upsert_csi_volume(CSIVolume(id=source, plugin_id="ebs0"))
        job = csi_job(source, count=count)
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        return job

    def test_bulk_commit_claims_by_block(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        job = self._place_block(s, count=80)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 80
        vol = snap.csi_volume_by_id("default", "vol-b")
        # the claim is ONE block entry, not six dict rows
        assert vol.read_allocs == {}
        assert len(vol.read_blocks) == 1
        assert vol.n_read_claims() == 80
        (block,) = vol.read_blocks.values()
        assert set(block.ids) == {a.id for a in live}
        # claimed volume cannot be deleted
        assert s.state.delete_csi_volume("default", "vol-b") \
            == "volume has active claims"

    def test_materialize_migrates_block_claims(self):
        s = Server(dev_mode=True)
        s.establish_leadership()
        job = self._place_block(s, count=70)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        # a member write (client status update) materializes the block;
        # its claims must migrate to per-alloc entries WITH node values
        victim = live[0]
        upd = victim.copy_skip_job()
        upd.client_status = "running"
        s.state.update_allocs_from_client([upd])
        vol = s.state.snapshot().csi_volume_by_id("default", "vol-b")
        assert vol.read_blocks == {}
        assert set(vol.read_allocs) == {a.id for a in live}
        assert vol.read_allocs[victim.id] == victim.node_id
        # terminal members now release through the normal per-alloc path
        term = []
        for a in live:
            u = a.copy_skip_job()
            u.client_status = "complete"
            term.append(u)
        s.state.update_allocs_from_client(term)
        s.volumes.tick(NOW + 1)
        vol2 = s.state.snapshot().csi_volume_by_id("default", "vol-b")
        assert not vol2.has_claims()
        assert s.state.delete_csi_volume("default", "vol-b") is None

    def test_watcher_reaps_vanished_block_claim(self):
        import dataclasses

        s = Server(dev_mode=True)
        s.establish_leadership()
        self._place_block(s, count=64)
        vol = s.state.snapshot().csi_volume_by_id("default", "vol-b")
        (bid,) = vol.read_blocks
        # simulate a hand-GC'd block: claim survives, block gone
        with s.state.locked():
            blocks, bj, bn = s.state._writable_block_tables()
            blk = blocks.pop(bid)
            jkey = (blk.template.namespace, blk.template.job_id)
            bj.pop(jkey, None)
            for nid in blk.node_table:
                bn.pop(nid, None)
        # the sweep CONVERTS the block claim to per-alloc claims (so
        # each member rides the unpublish-with-backoff ladder
        # independently) and, with the default always-succeeding
        # unpublish, reaps all of them in the same tick
        released = s.volumes.tick(NOW + 1)
        assert released == 64
        vol2 = s.state.snapshot().csi_volume_by_id("default", "vol-b")
        assert vol2.read_blocks == {}
        assert vol2.read_allocs == {}
        assert s.state.delete_csi_volume("default", "vol-b") is None

    def test_block_claims_snapshot_isolated_from_per_alloc_cow(self):
        """Mixed per-alloc + block claims in ONE snapshot cycle: the
        per-alloc claim path's copy-on-first-touch must cover the
        read_blocks ledger too, or a later block commit mutates the dict
        a pre-existing snapshot aliases (code-review r5: the leak let
        the volume watcher release a LIVE block claim)."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        make_cluster(s, n=8)
        s.state.upsert_csi_volume(CSIVolume(id="vol-mix",
                                            plugin_id="ebs0"))
        # per-alloc claim first (count below the block threshold)
        small = csi_job("vol-mix", count=2)
        s.register_job(small, now=NOW)
        s.process_all(now=NOW)
        snap_before = s.state.snapshot()
        vol_before = snap_before.csi_volume_by_id("default", "vol-mix")
        # same cycle: another per-alloc claim (marks the volume fresh),
        # then a columnar block claim
        small2 = csi_job("vol-mix", count=2)
        s.register_job(small2, now=NOW + 1)
        big = csi_job("vol-mix", count=80)
        s.register_job(big, now=NOW + 1)
        s.process_all(now=NOW + 1)
        vol_after = s.state.snapshot().csi_volume_by_id(
            "default", "vol-mix")
        assert len(vol_after.read_blocks) == 1
        # the old snapshot's view must be untouched by the later writes
        assert vol_before.read_blocks == {}
        assert len(vol_before.read_allocs) == 2

    def test_volume_detail_api_serializes_block_claims(self):
        """GET /v1/volume/csi/<id> with a live block claim: the wire form
        expands block members into ordinary read claims (AllocBlock holds
        numpy arrays json.dumps cannot encode)."""
        import json
        import urllib.request

        from nomad_tpu.agent import Agent

        import time as _t

        ag = Agent(num_clients=0, num_workers=1, heartbeat_ttl=3600)
        ag.start()
        try:
            s = ag.server
            t = _t.time()
            for i in range(8):
                nd = mock.node()
                nd.csi_node_plugins["ebs0"] = True
                s.register_node(nd, now=t)
            s.state.upsert_csi_volume(CSIVolume(id="vol-api",
                                                plugin_id="ebs0"))
            job = csi_job("vol-api", count=80)
            s.register_job(job, now=t)
            deadline = _t.time() + 60
            vol = None
            while _t.time() < deadline:
                vol = s.state.snapshot().csi_volume_by_id("default",
                                                          "vol-api")
                if vol.read_blocks:
                    break
                _t.sleep(0.2)
            assert vol.read_blocks, "expected a columnar block claim"
            with urllib.request.urlopen(
                    ag.address + "/v1/volume/csi/vol-api") as r:
                raw = r.read().decode()
            doc = json.loads(raw)
            assert len(doc.get("ReadAllocs", {})) == 80
            # block objects never reach the wire (numpy picks + embedded
            # job template are unserializable); the key is empty
            assert doc.get("ReadBlocks") in (None, {})
        finally:
            ag.shutdown()

    def test_vanished_block_claim_survives_snapshot_roundtrip(self):
        """A vanished-block claim at snapshot-save time must CONVERT to
        per-alloc claims in the document, not silently drop — the
        restored store's watcher still owes each member an unpublish
        before release (detach-before-release survives restore)."""
        from nomad_tpu.state.state_store import StateStore

        s = Server(dev_mode=True, heartbeat_ttl=1e9)
        s.establish_leadership()
        self._place_block(s, count=64)
        vol = s.state.snapshot().csi_volume_by_id("default", "vol-b")
        (bid,) = vol.read_blocks
        member_ids = set(vol.read_blocks[bid].ids)
        with s.state.locked():
            blocks, bj, bn = s.state._writable_block_tables()
            blk = blocks.pop(bid)
            jkey = (blk.template.namespace, blk.template.job_id)
            bj.pop(jkey, None)
            for nid in blk.node_table:
                bn.pop(nid, None)
        doc = s.state.snapshot_save()
        st2 = StateStore()
        st2.snapshot_restore(doc)
        v2 = st2.csi_volume_by_id("default", "vol-b")
        assert v2.read_blocks == {}
        assert set(v2.read_allocs) == member_ids
