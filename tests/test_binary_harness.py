"""External-binary harness (reference: testutil/server.go — tests that
shell out to a BUILT nomad binary with a config file and drive it over
HTTP, rather than importing the server in-process).

The analog here boots `python -m nomad_tpu agent` as a real subprocess
and drives it through the public surfaces only: the HTTP API and the CLI
binary.  This is the closest thing to the reference's external-binary
tier this environment supports (no Go, no containers)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def agent_proc():
    port = free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "nomad_tpu", "agent",
         "-bind", f"127.0.0.1:{port}", "-clients", "1"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 180
    last = None
    while time.time() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read().decode(errors="replace")
            raise AssertionError(f"agent died at boot:\n{out[-2000:]}")
        try:
            with urllib.request.urlopen(base + "/v1/status/leader",
                                        timeout=1) as r:
                r.read()
            break
        except Exception as e:  # noqa: BLE001 - booting
            last = e
            time.sleep(0.25)
    else:
        proc.kill()
        raise AssertionError(f"agent HTTP never came up: {last}")
    try:
        yield proc, base
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def cli(base, *args, check=True):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, "-m", "nomad_tpu", "-address", base, *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    if check:
        assert r.returncode == 0, (args, r.stdout, r.stderr)
    return r


class TestExternalBinaryHarness:
    def test_cli_job_lifecycle_against_live_binary(self, agent_proc):
        proc, base = agent_proc
        r = cli(base, "job", "run", "examples/web.hcl")
        assert "registered" in r.stdout
        # wait for a running alloc through the HTTP API
        deadline = time.time() + 180
        allocs = []
        while time.time() < deadline:
            with urllib.request.urlopen(
                    base + "/v1/job/web/allocations?namespace=default",
                    timeout=5) as resp:
                allocs = json.load(resp)
            if allocs and any(a["ClientStatus"] == "running"
                              for a in allocs):
                break
            time.sleep(0.3)
        assert allocs, "no allocations appeared"
        r = cli(base, "job", "status", "web")
        assert "web" in r.stdout
        r = cli(base, "eval", "list")
        assert "job-register" in r.stdout or "web" in r.stdout
        r = cli(base, "job", "stop", "web")
        assert "stop" in r.stdout or "deregistered" in r.stdout

    def test_node_and_operator_surface(self, agent_proc):
        proc, base = agent_proc
        with urllib.request.urlopen(base + "/v1/nodes", timeout=5) as r:
            nodes = json.load(r)
        assert nodes
        r = cli(base, "node", "status")
        assert nodes[0]["ID"][:8] in r.stdout
        r = cli(base, "operator", "raft", "list-peers")
        assert "leader" in r.stdout
        r = cli(base, "version")
        assert "nomad-tpu" in r.stdout

    def test_snapshot_roundtrip_through_binary(self, agent_proc, tmp_path):
        proc, base = agent_proc
        snap = tmp_path / "state.snap"
        cli(base, "operator", "snapshot", "save", str(snap))
        assert snap.exists() and snap.stat().st_size > 10
