"""Chaos suite: the deterministic fault-injection subsystem
(nomad_tpu/chaos/).

Three layers of coverage:

  - unit: VirtualClock semantics (advance is the only way time moves,
    waiters park and wake), SimNetwork fault routing (partitions, drop,
    latency, crash/restart), canonical trace serialization, and the
    agent-config knobs that select transport/clock.
  - scenarios (slow): every named scenario from chaos/scenarios.py runs
    against a real 3-server cluster on the simulated fabric + virtual
    clock, with the safety invariants (single leader per term, no
    committed entry lost, no deposed-leader commit, membership and
    alloc coherence) asserted by chaos/invariants.py.
  - determinism (slow): the same (scenario, seed) twice yields
    byte-identical canonical traces, and a recorded trace replays —
    without the seed — to the same state-store fingerprint.

Scenario runs are cached per (name, seed) so the scenario, determinism,
and replay tests share executions; the full suite stays within the CI
chaos-stage budget.  The heavy runs are @pytest.mark.slow: tier-1 runs
the unit layer; scripts/ci.sh's chaos stage runs this file in full.
"""

import threading
import time

import pytest

from nomad_tpu.chaos.clock import VirtualClock, resolve_clock
from nomad_tpu.chaos.scenarios import SCENARIOS, ScenarioRunner, run_scenario
from nomad_tpu.chaos.trace import Trace, schedule_from_trace
from nomad_tpu.chaos.transport import (
    SimNetwork,
    TCPTransport,
    resolve_transport,
)

# pinned seeds: the CI contract is that THESE runs are green and
# deterministic; a new scenario picks its seed by running a few and
# pinning one with a healthy trace
SEEDS = {
    "leader_partition": 1,
    "split_brain_attempt": 7,
    "gossip_flap_storm": 7,
    "lossy_link_raft_append": 7,
    "heartbeat_expiry_during_drain": 7,
}

# ------------------------------------------------------ shared scenario runs

_cache = {}


def _liveness_only(result) -> bool:
    """True when the run held every SAFETY invariant and only missed
    the liveness half — convergence within the virtual budget, or a
    workload op that never landed.  Jepsen discipline: safety failures
    are never retried — they are the bug — but liveness inside a fixed
    virtual budget also depends on how much real CPU the host gave the
    cluster threads, so a liveness-only miss earns one retry."""
    return (not result.ok
            and all(v.startswith("cluster failed to converge")
                    or v.startswith("workload op failed")
                    for v in result.violations))


def _trace_diff(a, b) -> str:
    """First differing canonical line between two runs' traces — the
    assert message a CI flake needs to be actionable."""
    la, lb = a.trace.canonical_lines(), b.trace.canonical_lines()
    for i, (x, y) in enumerate(zip(la, lb)):
        if x != y:
            return (f"canonical traces diverge at line {i}:\n"
                    f"  a: {x}\n  b: {y}")
    return (f"canonical traces differ in length: "
            f"{len(la)} vs {len(lb)} lines")


def _fresh(name, seed, schedule=None):
    r = run_scenario(name, seed=seed, schedule=schedule)
    if _liveness_only(r):
        r = run_scenario(name, seed=seed, schedule=schedule)
    return r


def _run(name, seed):
    key = (name, seed)
    if key not in _cache:
        _cache[key] = _fresh(name, seed)
    return _cache[key]


# ================================================================== unit


class TestVirtualClock:
    def test_advance_is_the_only_time_source(self):
        clk = VirtualClock()
        assert clk.monotonic() == 0.0
        assert clk.advance(1.5) == 1.5
        assert clk.monotonic() == 1.5
        # real time passing does not move virtual time
        time.sleep(0.01)
        assert clk.monotonic() == 1.5
        clk.close()

    def test_sleep_parks_until_advance(self):
        clk = VirtualClock()
        woke = threading.Event()

        def sleeper():
            clk.sleep(1.0)
            woke.set()

        t = threading.Thread(target=sleeper, daemon=True,
                             name="chaos-test-sleeper")
        t.start()
        time.sleep(0.1)
        assert not woke.is_set()          # wall time alone never wakes it
        clk.advance(2.0)
        assert woke.wait(2.0)
        t.join(timeout=2)
        clk.close()

    def test_wait_returns_on_event_before_deadline(self):
        clk = VirtualClock()
        ev = threading.Event()
        ev.set()
        assert clk.wait(ev, 100.0) is True
        clk.close()

    def test_close_releases_sleepers(self):
        clk = VirtualClock()
        done = threading.Event()

        def sleeper():
            clk.sleep(1e9)
            done.set()

        threading.Thread(target=sleeper, daemon=True,
                         name="chaos-test-sleeper").start()
        time.sleep(0.05)
        clk.close()
        assert done.wait(2.0)

    def test_epoch_anchored_time(self):
        clk = VirtualClock(epoch=1000.0)
        assert clk.time() == 1000.0
        clk.advance(5.0)
        assert clk.time() == 1005.0
        clk.close()

    def test_resolve_clock(self):
        assert resolve_clock("wall").kind == "wall"
        assert resolve_clock(None).kind == "wall"
        assert resolve_clock("virtual").kind == "virtual"
        clk = VirtualClock()
        assert resolve_clock(clk) is clk
        with pytest.raises(ValueError):
            resolve_clock("sundial")
        clk.close()


class TestSimNetwork:
    def _pair(self, net, a="a", b="b", channel="rpc"):
        lst = net.node(b).listen(("127.0.0.1", 0), channel)
        conn_a = net.node(a).dial(lst.addr, channel)
        conn_b = lst.accept()
        return lst, conn_a, conn_b

    def test_roundtrip_through_wire_codec(self):
        net = SimNetwork()
        lst, a, b = self._pair(net)
        a.send({"type": "ping", "n": 7})
        msg = b.recv(timeout=1.0)
        assert msg == {"type": "ping", "n": 7}
        b.send({"type": "ack"})
        assert a.recv(timeout=1.0) == {"type": "ack"}
        a.close(), b.close(), lst.close()

    def test_unencodable_payload_raises(self):
        net = SimNetwork()
        lst, a, b = self._pair(net)
        with pytest.raises(Exception):
            a.send({"bad": object()})     # must raise, not look dropped
        lst.close()

    def test_partition_blocks_dial_and_heal_restores(self):
        net = SimNetwork()
        lst = net.node("b").listen(("127.0.0.1", 0), "rpc")
        net.partition(["a"], ["b"])
        with pytest.raises(OSError):
            net.node("a").dial(lst.addr, "rpc")
        net.heal()
        conn = net.node("a").dial(lst.addr, "rpc")
        conn.close(), lst.close()

    def test_asymmetric_partition_starves_one_direction(self):
        net = SimNetwork()
        lst, a, b = self._pair(net)
        net.partition(["a"], ["b"], bidirectional=False)   # a->b cut only
        a.send({"x": 1})                        # swallowed (blackhole)
        assert b.recv(timeout=0.2) is None
        b.send({"y": 2})                        # reverse path still up
        assert a.recv(timeout=1.0) == {"y": 2}
        a.close(), b.close(), lst.close()

    def test_drop_probability_one_loses_everything(self):
        net = SimNetwork(seed=3)
        lst, a, b = self._pair(net)
        net.set_drop("a", "b", 1.0)
        for _ in range(5):
            a.send({"x": 1})
        assert b.recv(timeout=0.2) is None
        net.clear_link_faults()
        a.send({"x": 2})
        assert b.recv(timeout=1.0) == {"x": 2}
        a.close(), b.close(), lst.close()

    def test_latency_delivers_in_clock_time(self):
        clk = VirtualClock()
        net = SimNetwork(clock=clk)
        lst, a, b = self._pair(net)
        net.set_latency("a", "b", 5.0, 5.0)
        a.send({"x": 1})
        # delivery time (vt=5) has not passed: nothing to read yet
        assert b.recv(timeout=0.0) is None
        clk.advance(6.0)
        assert b.recv(timeout=1.0) == {"x": 1}
        a.close(), b.close(), lst.close(), clk.close()

    def test_crash_resets_connections_and_refuses_dials(self):
        net = SimNetwork()
        lst, a, b = self._pair(net)
        net.crash("b")
        with pytest.raises(OSError):
            a.send({"x": 1})
        with pytest.raises(OSError):
            net.node("a").dial(lst.addr, "rpc")
        net.restart("b")
        lst2 = net.node("b").listen(("127.0.0.1", 0), "rpc")
        conn = net.node("a").dial(lst2.addr, "rpc")
        conn.close(), lst2.close(), lst.close()

    def test_request_round_trip_and_failure_is_none(self):
        net = SimNetwork()
        lst = net.node("srv").listen(("127.0.0.1", 0), "rpc")

        def serve():
            try:
                conn = lst.accept()
                msg = conn.recv(timeout=2.0)
                conn.send({"echo": msg})
                conn.close()
            except OSError:
                pass

        t = threading.Thread(target=serve, daemon=True,
                             name="chaos-test-echo")
        t.start()
        r = net.node("cli").request(lst.addr, {"q": 1}, timeout=2.0)
        assert r == {"echo": {"q": 1}}
        t.join(timeout=2)
        net.partition(["cli"], ["srv"])
        assert net.node("cli").request(lst.addr, {"q": 2}) is None
        lst.close()


class TestTCPTransport:
    def test_roundtrip_over_real_sockets(self):
        t = TCPTransport()
        lst = t.listen(("127.0.0.1", 0), "rpc")

        def serve():
            conn = lst.accept()
            msg = conn.recv(timeout=2.0)
            conn.send({"echo": msg})
            conn.close()

        th = threading.Thread(target=serve, daemon=True,
                              name="chaos-test-tcp-echo")
        th.start()
        r = t.request(lst.addr, {"q": 41}, timeout=2.0)
        assert r == {"echo": {"q": 41}}
        th.join(timeout=2)
        lst.close()

    def test_resolve_transport(self):
        assert resolve_transport("tcp").kind == "tcp"
        assert resolve_transport(None).kind == "tcp"
        sim = resolve_transport("sim", node_name="n1")
        assert sim.kind == "sim" and sim.node_name == "n1"
        tcp = TCPTransport()
        assert resolve_transport(tcp) is tcp
        with pytest.raises(ValueError):
            resolve_transport("carrier-pigeon")


class TestTrace:
    def test_canonical_bytes_stable_and_debug_excluded(self):
        def build():
            tr = Trace()
            tr.record(1.0, "partition", a=["s1"], b=["s2"],
                      bidirectional=True)
            tr.record(0.5, "workload", op="register_job", job="j0",
                      count=2)
            tr.record(2.0, "verdict", ok=True, violations=[])
            return tr

        t1, t2 = build(), build()
        t2.debug(1.1, "msg_dropped", src="s1", dst="s2")   # noncanonical
        assert t1.canonical_bytes() == t2.canonical_bytes()
        assert t1.digest() == t2.digest()

    def test_schedule_from_trace_round_trip(self):
        tr = Trace()
        tr.record(3.0, "partition", a=["@leader"], b=["@others"],
                  bidirectional=True)
        tr.record(0.5, "workload", op="register_node", node="n0")
        tr.record(7.0, "heal")
        tr.record(12.0, "verdict", ok=True, violations=[])
        tr.record(12.0, "fingerprint", sha256="ab")
        sched = schedule_from_trace(tr)
        assert [e["kind"] for e in sched] == ["workload", "partition",
                                             "heal"]
        # placeholders survive verbatim — leader-relative faults replay
        assert sched[1]["a"] == ["@leader"]


class TestAgentConfigKnobs:
    def test_parse_transport_and_clock(self):
        from nomad_tpu.agent_config import parse_agent_config
        cfg, set_fields = parse_agent_config(
            'server { transport = "sim"\n  clock = "virtual" }')
        assert cfg.transport == "sim" and cfg.clock == "virtual"
        assert {"transport", "clock"} <= set_fields

    def test_defaults_are_production(self):
        from nomad_tpu.agent_config import AgentConfig
        cfg = AgentConfig()
        assert cfg.transport == "tcp" and cfg.clock == "wall"

    def test_rejects_unknown_values(self):
        from nomad_tpu.agent_config import parse_agent_config
        with pytest.raises(ValueError):
            parse_agent_config('server { transport = "udp" }')
        with pytest.raises(ValueError):
            parse_agent_config('server { clock = "sundial" }')


def test_schedule_expansion_is_seed_deterministic():
    """The expanded fault/workload schedule — the canonical trace's
    core — is a pure function of (scenario, seed), without running."""
    for name in SCENARIOS:
        a = ScenarioRunner(name, seed=11).spec
        b = ScenarioRunner(name, seed=11).spec
        assert a == b, name
        c = ScenarioRunner(name, seed=12).spec
        assert isinstance(c["schedule"], list), name


# ============================================================= scenarios


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_invariants(name):
    """Every named scenario holds every cluster invariant: at most one
    leader per term, no committed entry lost or reordered, no commit
    from a deposed leader, membership + leadership re-converge after
    heal, and the state store's allocs stay coherent."""
    r = _run(name, SEEDS[name])
    assert r.violations == [], f"{name}: {r.violations}"
    assert r.failed_ops == []
    assert r.converged
    assert r.ok


@pytest.mark.slow
def test_scenario_captures_eval_trace_shape():
    """The telemetry hook (core/telemetry.py): a scenario run captures
    the eval-lifecycle spans its workload produced, so chaos tests can
    assert on TRACE SHAPE — which stages each eval passed through — on
    top of the state/log invariants.  Under faults an eval may be
    mid-flight at capture time, so the assertion is over the whole run's
    span set, with per-trace parent links still consistent."""
    name = "leader_partition"
    r = _run(name, SEEDS[name])
    names = r.span_names()
    assert {"eval", "broker.wait", "worker.schedule",
            "plan.apply"} <= set(names), names
    by_trace = {}
    for sp in r.spans:
        by_trace.setdefault(sp["TraceID"], []).append(sp)
    assert by_trace
    for spans in by_trace.values():
        ids = {sp["SpanID"] for sp in spans}
        for sp in spans:
            # a parent either resolves in-trace or is the root marker of
            # a span still open at capture (the eval span ends at ack)
            assert sp["ParentID"] == "" or sp["ParentID"] in ids \
                or sp["ParentID"].endswith("-eval") \
                or sp["ParentID"].endswith("-worker.schedule"), sp


@pytest.mark.slow
def test_quality_gauges_survive_leader_failover():
    """The scheduling-quality gauges (core/plan_apply.publish_quality)
    keep flowing after a leader failover: the NEW leader's applier
    publishes `nomad.quality.*` from ITS OWN store's incremental
    ledger, so the series never goes stale when leadership moves.  The
    registry is reset first so only THIS run's commits — which include
    post-partition scheduling on the new leader (the scenario's
    job-landed invariant) — can satisfy the assertion."""
    from nomad_tpu.core.telemetry import REGISTRY
    REGISTRY.reset()
    name = "leader_partition"
    r = _fresh(name, SEEDS[name])
    assert not r.violations, r.violations
    gauges = REGISTRY.snapshot()["gauges"]
    assert "nomad.quality.nodes_in_use" in gauges, sorted(gauges)[:30]
    assert "nomad.quality.binpack_fill{dimension=memory}" in gauges
    # the workload's jobs landed, so the ledger saw live allocs
    assert gauges["nomad.quality.nodes_in_use"] >= 1


@pytest.mark.slow
def test_seed_determinism_full_run():
    """Two full executions with one seed produce byte-identical
    canonical traces and the same state fingerprint."""
    name = "leader_partition"
    a = _run(name, SEEDS[name])
    b = _fresh(name, SEEDS[name])
    assert a.trace.canonical_bytes() == b.trace.canonical_bytes(), \
        _trace_diff(a, b)
    assert a.fingerprint == b.fingerprint


@pytest.mark.slow
def test_trace_replay_reaches_same_fingerprint():
    """A recorded canonical trace re-executes — schedule taken from the
    trace, not re-expanded from the seed — to the same converged
    state-store fingerprint: every found failure is a replayable
    regression test."""
    name = "heartbeat_expiry_during_drain"
    a = _run(name, SEEDS[name])
    sched = schedule_from_trace(a.trace)
    b = _fresh(name, SEEDS[name], schedule=sched)
    assert b.violations == [], f"replay violations: {b.violations}"
    assert b.fingerprint == a.fingerprint
    assert b.trace.canonical_bytes() == a.trace.canonical_bytes(), \
        _trace_diff(a, b)
