"""Randomized validity properties: every placement any kernel path emits
must satisfy the independently-written host oracles — capacity
(structs.allocs_fit), static constraints (re-derived checkConstraint),
datacenter membership, and distinct_hosts — across random clusters and
random jobs.  Catches whole classes of lowering/padding/masking bugs the
hand-built scenario tests can't enumerate."""

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import Constraint, allocs_fit

from test_ops import host_check  # the independent constraint oracle

NOW = 1.7e9

OPS = [("=", lambda v: v), ("!=", lambda v: v),
       ("set_contains_any", lambda v: f"{v},zzz"),
       ("regexp", lambda v: v[:2])]


def random_cluster(rng, n_nodes):
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = f"dc{1 + rng.randrange(3)}"
        n.attributes["rack"] = f"r{rng.randrange(4)}"
        n.attributes["gen"] = str(rng.randrange(3))
        n.resources.cpu = rng.choice([2000, 4000, 8000])
        n.resources.memory_mb = rng.choice([4096, 8192])
        nodes.append(n)
    return nodes


def random_job(rng, i):
    job = mock.batch_job()
    job.datacenters = rng.sample(["dc1", "dc2", "dc3"],
                                 k=rng.randrange(1, 4))
    tg = job.task_groups[0]
    tg.count = rng.randrange(1, 40)
    t = tg.tasks[0]
    t.resources.cpu = rng.choice([50, 200, 700])
    t.resources.memory_mb = rng.choice([32, 128, 512])
    cons = []
    if rng.random() < 0.7:
        attr = rng.choice(["rack", "gen"])
        target = f"r{rng.randrange(4)}" if attr == "rack" \
            else str(rng.randrange(3))
        op, mk = rng.choice(OPS)
        cons.append(Constraint(f"${{attr.{attr}}}", op, mk(target)))
    if rng.random() < 0.2:
        cons.append(Constraint("", "distinct_hosts", "2"))
    tg.constraints = cons
    return job


def node_props(n):
    out = {"node.datacenter": n.datacenter}
    for k, v in n.attributes.items():
        out["attr." + k] = v
    return out


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_random_workloads_place_validly(seed):
    rng = random.Random(seed)
    s = Server(dev_mode=True, eval_batch=rng.choice([0, 8, 64]))
    s.establish_leadership()
    nodes = random_cluster(rng, rng.randrange(20, 120))
    s.state.upsert_nodes(nodes)
    by_id = {n.id: n for n in nodes}
    jobs = [random_job(rng, i) for i in range(rng.randrange(4, 16))]
    for j in jobs:
        s.register_job(j, now=NOW)
    s.process_all(now=NOW)
    snap = s.state.snapshot()

    total_live = 0
    for job in jobs:
        tg = job.task_groups[0]
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        total_live += len(live)
        assert len(live) <= tg.count
        per_node = {}
        for a in live:
            node = by_id[a.node_id]
            props = node_props(node)
            # datacenter membership
            assert node.datacenter in job.datacenters, (
                job.id, node.datacenter, job.datacenters)
            # every static constraint holds on the chosen node
            for c in tg.constraints:
                if c.operand == "distinct_hosts":
                    continue
                assert host_check(props, c), (job.id, c, props)
            per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
        # distinct_hosts limit
        for c in tg.constraints:
            if c.operand == "distinct_hosts":
                limit = int(c.rtarget)
                assert all(v <= limit for v in per_node.values()), (
                    job.id, per_node)
        # unplaced remainder must be accounted: blocked eval or failed
        if len(live) < tg.count:
            evs = snap.evals_by_job(job.namespace, job.id)
            assert any(e.status in ("blocked", "pending", "failed")
                       for e in evs), (job.id, len(live), tg.count,
                                       [e.status for e in evs])

    # capacity: the committed alloc set fits every node per the oracle
    for n in nodes:
        allocs = [a for a in snap.allocs_by_node(n.id)
                  if not a.terminal_status()]
        if not allocs:
            continue
        ok, dim, _ = allocs_fit(n, allocs)
        assert ok, (n.id, dim, len(allocs))
    assert total_live > 0     # the scenario actually exercised placement


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_padded_rows_unreachable_at_odd_node_counts(seed):
    """Mesh-padding property (ISSUE 7): with N % n_devices != 0 the
    sharded engine pads the node axis with ineligible rows — no
    workload, however oversubscribed, may ever produce an alloc whose
    node_id is not a live node, and capacity must hold on every real
    node.  Then node GC shrinks N across a shard boundary (full table
    rebuild + row remap) and the property must still hold for fresh
    placements."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs the virtual multi-device mesh")
    rng = random.Random(seed)
    ndev = jax.device_count()
    # an explicitly non-multiple node count, small enough to oversubscribe
    n_nodes = rng.randrange(3 * ndev, 6 * ndev)
    if n_nodes % ndev == 0:
        n_nodes += 1 + rng.randrange(ndev - 1)
    s = Server(dev_mode=True, eval_batch=rng.choice([0, 8]))
    assert s.engine.mesh is not None
    s.establish_leadership()
    nodes = random_cluster(rng, n_nodes)
    s.state.upsert_nodes(nodes)

    def assert_valid():
        snap = s.state.snapshot()
        live_nodes = {nd.id for nd in snap.nodes()}
        placed = 0
        for job in snap.jobs():
            for a in snap.allocs_by_job(job.namespace, job.id):
                if a.terminal_status():
                    continue
                assert a.node_id in live_nodes, \
                    (job.id, a.node_id, "padded/ghost row placed")
                placed += 1
        for nd in snap.nodes():
            allocs = [a for a in snap.allocs_by_node(nd.id)
                      if not a.terminal_status()]
            if allocs:
                ok, dim, _ = allocs_fit(nd, allocs)
                assert ok, (nd.id, dim)
        return placed

    # oversubscribe: ask for far more than the cluster holds
    for i in range(4):
        job = random_job(rng, i)
        job.task_groups[0].count = 200
        s.register_job(job, now=NOW)
    s.process_all(now=NOW)
    assert assert_valid() > 0

    # GC enough nodes to cross a shard boundary (row remap + repad);
    # real GC only reaps drained nodes, so their allocs terminate first
    snap = s.state.snapshot()
    keep = (n_nodes // ndev - 1) * ndev + 1     # still non-multiple
    for nd in snap.nodes()[keep:]:
        gone = []
        for a in snap.allocs_by_node(nd.id):
            if a.terminal_status():
                continue
            dead = a.copy()
            dead.desired_status = "stop"
            dead.client_status = "complete"
            gone.append(dead)
        if gone:
            s.state.upsert_allocs(gone)
        s.state.delete_node(nd.id)
    for i in range(3):
        job = random_job(rng, 100 + i)
        job.task_groups[0].count = 150
        s.register_job(job, now=NOW)
    s.process_all(now=NOW)
    assert_valid()


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_block_reads_equal_classic_reads(seed):
    """Columnar-block state is INVISIBLE to readers: for random bulk
    workloads, every read surface (by job, by node, by id, counts,
    snapshot vs head) returns the same allocs whether placements
    committed as blocks or were flattened to table rows."""
    rng = random.Random(seed)
    s = Server(dev_mode=True, eval_batch=64)
    s.establish_leadership()
    for n in random_cluster(rng, 30):
        n.resources.cpu = 16000
        n.resources.memory_mb = 32768
        s.register_node(n, now=NOW)
    jobs = []
    for i in range(4):
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = rng.randrange(64, 150)   # >= 64 -> block path
        tg.tasks[0].resources.cpu = 10
        tg.tasks[0].resources.memory_mb = 10
        s.register_job(job, now=NOW)
        jobs.append(job)
    s.process_all(now=NOW)
    assert s.state._alloc_blocks, "expected columnar commits"

    def read_everything():
        snap = s.state.snapshot()
        out = {}
        for job in jobs:
            rows = sorted(
                (a.id, a.name, a.node_id)
                for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status())
            out[job.id] = rows
        by_node = {}
        for nid in {r[2] for rows in out.values() for r in rows}:
            by_node[nid] = sorted(a.id for a in snap.allocs_by_node(nid))
        some_ids = [rows[0][0] for rows in out.values() if rows]
        by_id = {aid: snap.alloc_by_id(aid) is not None
                 for aid in some_ids}
        return out, by_node, by_id

    before = read_everything()
    # flatten EVERY block (the cold path) and re-read: identical
    for b in list(s.state._alloc_blocks.values()):
        with s.state.locked():
            s.state._materialize_block_locked(b)
    assert not s.state._alloc_blocks
    after = read_everything()
    assert before == after
    # counts match the asked counts
    for job in jobs:
        assert len(before[0][job.id]) == job.task_groups[0].count
