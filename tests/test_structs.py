"""Data-model and oracle tests (reference semantics: nomad/structs)."""

import math

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import (
    MAX_FIT_SCORE,
    NetworkIndex,
    NetworkResource,
    Port,
    Resources,
    allocs_fit,
    alloc_name,
    compute_class,
    score_fit_binpack,
    score_fit_spread,
)


class TestScoreFit:
    def test_empty_node_binpack_is_zero(self):
        # free=1.0 per dim -> total=20 -> score 0 (worst bin-pack fit)
        assert score_fit_binpack(4000, 8192, 0, 0) == pytest.approx(0.0)

    def test_full_node_binpack_is_max(self):
        # used == capacity -> total=2 -> score 18 (perfect fit)
        assert score_fit_binpack(4000, 8192, 4000, 8192) == pytest.approx(MAX_FIT_SCORE)

    def test_half_utilized(self):
        got = score_fit_binpack(100, 100, 50, 50)
        want = 20.0 - 2 * math.pow(10, 0.5)
        assert got == pytest.approx(want)

    def test_monotone_in_utilization(self):
        prev = -1.0
        for used in range(0, 4001, 250):
            s = score_fit_binpack(4000, 8192, used, used * 2)
            assert s >= prev
            prev = s

    def test_spread_is_inverse(self):
        # spread algorithm rewards empty nodes
        assert score_fit_spread(4000, 8192, 0, 0) == pytest.approx(MAX_FIT_SCORE)
        assert score_fit_spread(4000, 8192, 4000, 8192) == pytest.approx(0.0)

    def test_overcommit_clamped(self):
        assert 0.0 <= score_fit_binpack(100, 100, 500, 500) <= MAX_FIT_SCORE

    def test_zero_capacity(self):
        assert score_fit_binpack(0, 0, 0, 0) == 0.0


class TestAllocsFit:
    def _alloc(self, cpu, mem, ports=()):
        a = mock.alloc()
        a.resources = Resources(cpu=cpu, memory_mb=mem)
        a.allocated_ports = {f"p{p}": p for p in ports}
        return a

    def test_fits_empty(self):
        n = mock.node()
        ok, dim, used = allocs_fit(n, [])
        assert ok and dim == ""
        assert used.cpu == 0

    def test_fits_exact_capacity(self):
        n = mock.node()
        cap_cpu = n.resources.cpu - n.reserved.cpu
        cap_mem = n.resources.memory_mb - n.reserved.memory_mb
        ok, dim, _ = allocs_fit(n, [self._alloc(cap_cpu, cap_mem)])
        assert ok, dim

    def test_cpu_exhausted(self):
        n = mock.node()
        ok, dim, _ = allocs_fit(n, [self._alloc(n.resources.cpu + 1, 10)])
        assert not ok and dim == "cpu"

    def test_memory_exhausted(self):
        n = mock.node()
        ok, dim, _ = allocs_fit(n, [self._alloc(1, n.resources.memory_mb + 1)])
        assert not ok and dim == "memory"

    def test_terminal_allocs_ignored(self):
        n = mock.node()
        a = self._alloc(n.resources.cpu * 2, 10)
        a.desired_status = "stop"
        ok, _, used = allocs_fit(n, [a])
        assert ok and used.cpu == 0

    def test_port_collision(self):
        n = mock.node()
        ok, dim, _ = allocs_fit(
            n, [self._alloc(10, 10, ports=[8080]),
                self._alloc(10, 10, ports=[8080])])
        assert not ok and "port" in dim

    def test_reserved_node_port_collision(self):
        n = mock.node()
        n.reserved.reserved_ports = [22]
        ni = NetworkIndex()
        ni.set_node(n)
        got, err = ni.assign_ports(
            [NetworkResource(reserved_ports=[Port("ssh", 22)])])
        assert got is None and "collision" in err

    def test_dynamic_port_assignment(self):
        ni = NetworkIndex()
        got, err = ni.assign_ports(
            [NetworkResource(dynamic_ports=[Port("http"), Port("rpc")])])
        assert err == "" and len(set(got.values())) == 2


class TestComputedClass:
    def test_same_attrs_same_class(self):
        n1, n2 = mock.node(), mock.node()
        # unique.hostname differs but must not affect class
        assert n1.attributes["unique.hostname"] != n2.attributes["unique.hostname"]
        assert compute_class(n1) == compute_class(n2)

    def test_different_dc_different_class(self):
        n1 = mock.node()
        n2 = mock.node(datacenter="dc2")
        assert compute_class(n1) != compute_class(n2)

    def test_different_attr_different_class(self):
        n1 = mock.node()
        n2 = mock.node()
        n2.attributes = {**n2.attributes, "os.name": "debian"}
        assert compute_class(n1) != compute_class(n2)


class TestAllocHelpers:
    def test_alloc_name_index(self):
        a = mock.alloc()
        a.name = alloc_name("job", "web", 7)
        assert a.index() == 7

    def test_terminal_status(self):
        a = mock.alloc()
        assert not a.terminal_status()
        a.client_status = "failed"
        assert a.terminal_status()
        b = mock.alloc()
        b.desired_status = "evict"
        assert b.terminal_status()

    def test_copy_skip_job_keeps_job_ref(self):
        a = mock.alloc()
        c = a.copy_skip_job()
        assert c.job is a.job
        assert c is not a


class TestMockFixtures:
    def test_job_shape(self):
        j = mock.job()
        assert j.type == "service"
        assert j.task_groups[0].count == 10
        assert j.task_groups[0].tasks[0].resources.cpu == 500

    def test_combined_resources(self):
        tg = mock.job().task_groups[0]
        r = tg.combined_resources()
        assert r.cpu == 500 and r.memory_mb == 256
        assert r.disk_mb == tg.ephemeral_disk.size_mb

    def test_system_job(self):
        j = mock.system_job()
        assert j.type == "system" and j.priority == 100

    def test_eval(self):
        e = mock.eval()
        assert e.should_enqueue()
