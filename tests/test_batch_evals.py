"""Multi-eval batched scheduling (DP over evals — SURVEY §3.6 row 1).

The reference processes one eval per worker goroutine (nomad/worker.go);
here compatible pending evals share ONE device launch
(ops.select.place_multi_packed via engine.place_batch) and their plans are
mutually consistent by construction.  These tests pin:
  - kernel parity: a batch of one == the single-eval bulk kernel
  - capacity coupling: plans inside one batch never oversubscribe and
    never refute each other at the serialized applier
  - end-to-end: Server.process_all with eval_batch handles a mixed queue
    (batchable + system + spread jobs) equivalently to solo processing
"""

import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.ops import PlacementEngine
from nomad_tpu.ops.engine import BatchItem
from nomad_tpu.scheduler import Harness

NOW = 1.7e9


def build_cluster(n_nodes=200, n_dcs=3, seed=0):
    rng = random.Random(seed)
    h = Harness()
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = f"dc{1 + i % n_dcs}"
        n.resources.cpu = rng.choice([4000, 8000, 16000])
        n.resources.memory_mb = rng.choice([8192, 16384, 32768])
        nodes.append(n)
    h.state.upsert_nodes(nodes)
    return h, nodes


def batch_jobs(h, counts, cpu=100, mem=64):
    jobs = []
    for c in counts:
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = c
        tg.tasks[0].resources.cpu = cpu
        tg.tasks[0].resources.memory_mb = mem
        h.state.upsert_job(job)
        jobs.append(job)
    return jobs


class TestPlaceBatchKernel:
    def test_single_item_matches_bulk_kernel(self):
        h, _ = build_cluster(150)
        (job,) = batch_jobs(h, [200])
        snap = h.state.snapshot()
        eng = PlacementEngine()
        bd_batch = eng.place_batch(
            snap, [BatchItem(job=job, tg=job.task_groups[0], count=200)],
            seed=9)[0]
        bd_bulk = eng.place(snap, job, job.task_groups, None, bulk_api=True,
                            seed=9, block=(job.task_groups[0].name, 200))
        assert np.array_equal(np.sort(bd_batch.picks),
                              np.sort(bd_bulk.picks))
        # metric parity for the first round
        m_batch, m_bulk = bd_batch.metrics[0], bd_bulk.metrics[0]
        assert m_batch.nodes_filtered == m_bulk.nodes_filtered
        assert m_batch.nodes_exhausted == m_bulk.nodes_exhausted

    def test_capacity_coupling_across_items(self):
        """Items in one batch see each other's proposed usage: total
        per-node commitment never exceeds capacity even when the batch
        oversubscribes the cluster."""
        h, nodes = build_cluster(20, seed=3)
        for n in nodes:
            n.resources.cpu = 4000
            n.resources.memory_mb = 8192
        h.state.upsert_nodes(nodes)
        jobs = batch_jobs(h, [30, 30, 30], cpu=1000, mem=512)
        snap = h.state.snapshot()
        eng = PlacementEngine()
        items = [BatchItem(job=j, tg=j.task_groups[0],
                           count=j.task_groups[0].count) for j in jobs]
        decisions = eng.place_batch(snap, items, seed=5)
        used = {}
        placed = 0
        for d in decisions:
            for p in d.picks:
                if p < 0:
                    continue
                used[int(p)] = used.get(int(p), 0) + 1000
                placed += 1
        # usable cpu is 4000 minus the node's reserved 100 -> 3 slots
        # per node; 20 nodes x 3 = 60 total capacity for 90 asks
        assert placed == 60, placed
        for row, cpu in used.items():
            assert cpu <= 3900, (row, cpu)
        # failed picks report exhaustion, not filtering
        failed_rounds = [m for d in decisions for m in d.metrics
                         if m.dimension_exhausted]
        assert failed_rounds

    def test_job_anti_affinity_rows_isolated_per_job(self):
        """Each item's anti-affinity sees only ITS job's allocs: two jobs
        placing in one batch spread independently."""
        h, _ = build_cluster(10)
        jobs = batch_jobs(h, [4, 4], cpu=10, mem=10)
        for j in jobs:
            j.type = "service"
            h.state.upsert_job(j)
        snap = h.state.snapshot()
        eng = PlacementEngine()
        items = [BatchItem(job=j, tg=j.task_groups[0], count=4)
                 for j in jobs]
        d1, d2 = eng.place_batch(snap, items, seed=11)
        assert (d1.picks >= 0).all() and (d2.picks >= 0).all()


class TestBatchedWorkerPath:
    def _run(self, eval_batch, n_jobs=6, count=25, system_too=True):
        s = Server(dev_mode=True, eval_batch=eval_batch)
        s.establish_leadership()
        rng = random.Random(1)
        for i in range(60):
            n = mock.node()
            n.datacenter = f"dc{1 + i % 3}"
            n.resources.cpu = rng.choice([8000, 16000])
            n.resources.memory_mb = 16384
            s.register_node(n, now=NOW)
        jobs = []
        for _ in range(n_jobs):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.task_groups[0].count = count
            # small asks: eval processing ORDER between concurrently
            # pending evals is not a guarantee (coupled batches run
            # before solos), so the fixture must not be capacity-tight
            job.task_groups[0].tasks[0].resources.cpu = 10
            job.task_groups[0].tasks[0].resources.memory_mb = 16
            s.register_job(job, now=NOW)
            jobs.append(job)
        sysjob = None
        if system_too:
            sysjob = mock.system_job()
            s.register_job(sysjob, now=NOW)
        n = s.process_all(now=NOW)
        return s, jobs, sysjob, n

    def test_mixed_queue_batched_equals_solo(self):
        s_b, jobs_b, sys_b, n_b = self._run(eval_batch=64)
        s_s, jobs_s, sys_s, n_s = self._run(eval_batch=0)
        assert n_b == n_s
        for s, jobs, sysjob in ((s_b, jobs_b, sys_b), (s_s, jobs_s, sys_s)):
            snap = s.state.snapshot()
            for job in jobs:
                live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                        if not a.terminal_status()]
                assert len(live) == 25, (job.id, len(live))
                evs = snap.evals_by_job(job.namespace, job.id)
                assert any(e.status == "complete" for e in evs)
            live = [a for a in snap.allocs_by_job(sysjob.namespace,
                                                  sysjob.id)
                    if not a.terminal_status()]
            # system job defaults to dc1 only: a third of the nodes
            assert len(live) == 20

    def test_batched_plans_do_not_refute_each_other(self):
        s, jobs, _, _ = self._run(eval_batch=64, n_jobs=8, count=40,
                                  system_too=False)
        # every plan committed in full: no worker retries happened
        assert s.workers[0].stats["nacked"] == 0
        snap = s.state.snapshot()
        for job in jobs:
            evs = snap.evals_by_job(job.namespace, job.id)
            assert all(e.status in ("complete",) for e in evs), \
                [(e.status, e.status_description) for e in evs]

    def test_batch_oversubscription_creates_blocked_evals(self):
        s = Server(dev_mode=True, eval_batch=64)
        s.establish_leadership()
        for _ in range(4):
            n = mock.node()
            n.resources.cpu = 4000
            n.resources.memory_mb = 8192
            s.register_node(n, now=NOW)
        jobs = []
        for _ in range(3):
            job = mock.batch_job()
            job.task_groups[0].count = 3
            job.task_groups[0].tasks[0].resources.cpu = 2000
            job.task_groups[0].tasks[0].resources.memory_mb = 64
            s.register_job(job, now=NOW)
            jobs.append(job)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        placed = sum(
            1 for job in jobs
            for a in snap.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status())
        # usable cpu 3900 fits ONE 2000-cpu alloc per node: 4 of 9 place
        assert placed == 4
        assert s.blocked_evals.num_blocked() >= 1
        # capacity arrives -> blocked evals release and place the rest
        big = mock.node()
        big.resources.cpu = 16000
        big.resources.memory_mb = 32768
        s.register_node(big, now=NOW + 1)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        placed = sum(
            1 for job in jobs
            for a in snap.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status())
        assert placed == 9

    def test_spread_job_falls_back_to_exact_path_in_batch(self):
        from nomad_tpu.structs import Spread, SpreadTarget
        s = Server(dev_mode=True, eval_batch=64)
        s.establish_leadership()
        for i in range(30):
            n = mock.node()
            n.datacenter = f"dc{1 + i % 3}"
            s.register_node(n, now=NOW)
        plain = mock.batch_job()
        plain.datacenters = ["dc1", "dc2", "dc3"]
        plain.task_groups[0].count = 10
        s.register_job(plain, now=NOW)
        spread = mock.job()
        spread.datacenters = ["dc1", "dc2", "dc3"]
        spread.task_groups[0].count = 9
        spread.spreads = [Spread(attribute="${node.datacenter}", weight=50,
                                 targets=[SpreadTarget("dc1", 34),
                                          SpreadTarget("dc2", 33),
                                          SpreadTarget("dc3", 33)])]
        s.register_job(spread, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        for job, want in ((plain, 10), (spread, 9)):
            live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            assert len(live) == want
        # the spread job actually spread across the three DCs
        by_dc = {}
        for a in snap.allocs_by_job(spread.namespace, spread.id):
            node = snap.node_by_id(a.node_id)
            by_dc[node.datacenter] = by_dc.get(node.datacenter, 0) + 1
        assert sorted(by_dc.values()) == [3, 3, 3], by_dc

    def test_applier_fast_path_and_fence(self):
        """Coupled-batch plans skip the redundant AllocsFit re-check; a
        foreign placement-relevant write mid-chain breaks the fence and
        restores the full optimistic re-check (which refutes a plan the
        fast path would have waved through)."""
        from nomad_tpu.structs import Allocation, Plan

        s, jobs, _, _ = self._run(eval_batch=64, n_jobs=6, count=20,
                                  system_too=False)
        stats = s.plan_applier.stats
        assert stats["fast_path"] >= 5, stats

        # hand-drive a coupled chain against the applier
        snap = s.state.snapshot()
        node = snap.nodes()[0]
        job = jobs[0]

        def mkplan(cpu, bid, seq0):
            a = Allocation(namespace=job.namespace, job_id=job.id, job=job,
                           task_group=job.task_groups[0].name,
                           desired_status="run", client_status="pending")
            a.resources = job.task_groups[0].combined_resources().copy()
            a.resources.cpu = cpu
            a.node_id = node.id
            p = Plan(eval_id="manual", job=job,
                     coupled_batch=(bid, seq0))
            p.append_alloc(a)
            return p

        seq0 = s.state.placement_seq()
        r1 = s.plan_applier.evaluate_plan(
            mkplan(50, "bX", seq0), skip_fit=True)
        assert not r1.refuted_nodes

        # a foreign write to an UNRELATED node must NOT demote the fence
        # (per-node granularity — the whole point: disjoint workers never
        # poison each other's chains)
        s.register_node(mock.node(), now=NOW + 1)
        fp_before = s.plan_applier.stats["fast_path"]
        from nomad_tpu.core.plan_apply import PendingPlan
        ok_plan = mkplan(10, "bX", seq0)
        pending = PendingPlan(ok_plan)
        s.plan_applier.apply_one(pending)
        result, err = pending.wait(timeout=5)
        assert err is None and not result.refuted_nodes
        assert s.plan_applier.stats["fast_path"] == fp_before + 1

        # a plan that oversubscribes the node: a foreign write TO THE
        # PLAN'S NODE breaks its fence, so apply_one full-checks and
        # refutes it.  (The foreign write: an unfenced alloc commit on
        # that node.)
        from nomad_tpu.structs import Resources
        foreign = Allocation(namespace=job.namespace, job_id=job.id,
                             job=job, task_group=job.task_groups[0].name,
                             desired_status="run", client_status="pending",
                             node_id=node.id,
                             resources=Resources(cpu=1, memory_mb=1))
        s.state.upsert_allocs([foreign])
        big = mkplan(10 ** 9, "bX", seq0)
        pending = PendingPlan(big)
        s.plan_applier.apply_one(pending)
        result, err = pending.wait(timeout=5)
        assert err is None
        assert result.refuted_nodes == [node.id]

    def test_cross_batch_prefetch_chain(self):
        """Small eval_batch forces multiple coupled batches per drain:
        the worker prefetch-chains batch k+1 on batch k's device-side
        proposed usage.  Everything must still place exactly, without
        refutes, and with the applier fast path active across batches."""
        s = Server(dev_mode=True, eval_batch=4)
        s.establish_leadership()
        rng = random.Random(7)
        for i in range(30):
            n = mock.node()
            n.datacenter = f"dc{1 + i % 3}"
            n.resources.cpu = rng.choice([8000, 16000])
            n.resources.memory_mb = 16384
            s.register_node(n, now=NOW)
        jobs = []
        for _ in range(12):                      # 3 batches of 4
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.task_groups[0].count = 15
            job.task_groups[0].tasks[0].resources.cpu = 20
            job.task_groups[0].tasks[0].resources.memory_mb = 16
            s.register_job(job, now=NOW)
            jobs.append(job)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        for job in jobs:
            live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            assert len(live) == 15, (job.id, len(live))
        assert s.workers[0].stats["nacked"] == 0
        # chained batches share the fence: the fast path dominated
        stats = s.plan_applier.stats
        assert stats["fast_path"] >= 8, stats

    def test_chain_resyncs_after_node_table_change(self):
        """A node-table rebuild between chained batches remaps rows; the
        chained usage must be dropped (version guard) — placements stay
        valid."""
        s = Server(dev_mode=True, eval_batch=4)
        s.establish_leadership()
        nodes = []
        for _ in range(6):
            n = mock.node()
            n.resources.cpu = 4000
            n.resources.memory_mb = 8192
            s.register_node(n, now=NOW)
            nodes.append(n)
        # wave 1 fills some capacity
        first = []
        for _ in range(4):
            job = mock.batch_job()
            job.task_groups[0].count = 3
            job.task_groups[0].tasks[0].resources.cpu = 300
            s.register_job(job, now=NOW)
            first.append(job)
        s.process_all(now=NOW)
        # membership change rebuilds the node table (rows remap)
        s.register_node(mock.node(), now=NOW + 1)
        more = []
        for _ in range(4):
            job = mock.batch_job()
            job.task_groups[0].count = 3
            job.task_groups[0].tasks[0].resources.cpu = 300
            s.register_job(job, now=NOW + 1)
            more.append(job)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        # capacity accounting stayed exact through the resync
        for job in first + more:
            live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                    if not a.terminal_status()]
            assert len(live) == 3
        by_node = {}
        for job in first + more:
            for a in snap.allocs_by_job(job.namespace, job.id):
                if not a.terminal_status():
                    by_node[a.node_id] = (by_node.get(a.node_id, 0)
                                          + a.resources.cpu)
        for nid, cpu in by_node.items():
            node = snap.node_by_id(nid)
            usable = node.resources.cpu - node.reserved.cpu
            assert cpu <= usable, (nid, cpu, usable)

    def test_preemption_falls_back_to_solo(self):
        from nomad_tpu.structs import (PreemptionConfig,
                                       SchedulerConfiguration)
        s = Server(dev_mode=True, eval_batch=64)
        s.establish_leadership()
        s.state.set_scheduler_config(SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                service_scheduler_enabled=True,
                batch_scheduler_enabled=True)))
        for _ in range(5):
            n = mock.node()
            n.resources.cpu = 4000
            n.resources.memory_mb = 8192
            s.register_node(n, now=NOW)
        low = mock.batch_job()
        low.priority = 20
        low.task_groups[0].count = 5
        low.task_groups[0].tasks[0].resources.cpu = 3000
        s.register_job(low, now=NOW)
        s.process_all(now=NOW)
        # two high-pri jobs arrive together: each must preempt
        highs = []
        for _ in range(2):
            hi = mock.job()
            hi.priority = 80
            hi.task_groups[0].count = 2
            hi.task_groups[0].tasks[0].resources.cpu = 3000
            s.register_job(hi, now=NOW + 1)
            highs.append(hi)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        for hi in highs:
            live = [a for a in snap.allocs_by_job(hi.namespace, hi.id)
                    if not a.terminal_status()]
            assert len(live) == 2, (hi.id, len(live))
        evicted = [a for a in snap.allocs_by_job(low.namespace, low.id)
                   if a.desired_status == "evict"]
        assert len(evicted) == 4


def build_zoned_cluster(n_nodes=500, n_zones=5, seed=0):
    """Bench-shaped cluster: per-zone CSI volumes whose topologies pin
    jobs to provably-disjoint node sets (the compact laned kernel's
    activation condition)."""
    from nomad_tpu.structs import CSIVolume
    rng = random.Random(seed)
    h = Harness()
    nodes = []
    zone_nodes = {z: [] for z in range(n_zones)}
    for i in range(n_nodes):
        n = mock.node()
        n.datacenter = f"dc{1 + i % 3}"
        n.attributes["storage.topology"] = f"zone{i % n_zones}"
        n.csi_node_plugins["ebs0"] = True
        n.resources.cpu = rng.choice([4000, 8000, 16000])
        n.resources.memory_mb = rng.choice([8192, 16384, 32768])
        nodes.append(n)
        zone_nodes[i % n_zones].append(n.id)
    h.state.upsert_nodes(nodes)
    for z in range(n_zones):
        h.state.upsert_csi_volume(CSIVolume(
            id=f"vol-zone{z}", plugin_id="ebs0",
            access_mode="multi-node-multi-writer",
            topology_node_ids=tuple(zone_nodes[z])))
    return h, nodes


def zoned_items(h, n_items, count, n_zones=5):
    from nomad_tpu.structs import VolumeRequest
    items = []
    for i in range(n_items):
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = 10
        tg.tasks[0].resources.memory_mb = 10
        tg.volumes = {"data": VolumeRequest(
            name="data", type="csi", source=f"vol-zone{i % n_zones}",
            read_only=True)}
        h.state.upsert_job(job)
        items.append(BatchItem(job=job, tg=tg, count=count))
    return items


class TestSignatureDisjointness:
    """The structural disjointness prover gates lane parallelism: a
    FALSE POSITIVE would let two lanes water-fill the same node
    concurrently and oversubscribe it.  Conservative by construction —
    prove only what the lowered rows entail."""

    def _luts(self):
        # rows: 0 = {vocab 0,1}, 1 = {vocab 2,3}, 2 = {vocab 1,2}
        luts = np.zeros((3, 4), bool)
        luts[0, [0, 1]] = True
        luts[1, [2, 3]] = True
        luts[2, [1, 2]] = True
        return luts

    def test_proven_disjoint(self):
        from nomad_tpu.ops.engine import _sig_disjoint
        from nomad_tpu.pack.packer import DOP_EQ, DOP_LUT
        luts = self._luts()
        # EQ/EQ different values on one column
        assert _sig_disjoint([(5, DOP_EQ, 1)], [(5, DOP_EQ, 2)], luts)
        # LUT/LUT with empty intersection ({0,1} vs {2,3})
        assert _sig_disjoint([(7, DOP_LUT, 0)], [(7, DOP_LUT, 1)], luts)
        # EQ value outside the LUT's set (2 not in {0,1})
        assert _sig_disjoint([(7, DOP_LUT, 0)], [(7, DOP_EQ, 2)], luts)
        assert _sig_disjoint([(7, DOP_EQ, 2)], [(7, DOP_LUT, 0)], luts)

    def test_not_proven(self):
        from nomad_tpu.ops.engine import _sig_disjoint
        from nomad_tpu.pack.packer import (
            DOP_EQ, DOP_LUT, DOP_NEQ, DOP_TRUE)
        luts = self._luts()
        # same EQ value: same set
        assert not _sig_disjoint([(5, DOP_EQ, 1)], [(5, DOP_EQ, 1)], luts)
        # different COLUMNS never prove anything
        assert not _sig_disjoint([(5, DOP_EQ, 1)], [(6, DOP_EQ, 2)], luts)
        # overlapping LUTs ({0,1} vs {1,2})
        assert not _sig_disjoint([(7, DOP_LUT, 0)], [(7, DOP_LUT, 2)],
                                 luts)
        # EQ value inside the LUT's set
        assert not _sig_disjoint([(7, DOP_LUT, 0)], [(7, DOP_EQ, 1)],
                                 luts)
        # NEQ / padding rows are ignored (no false proofs from them)
        assert not _sig_disjoint([(5, DOP_NEQ, 1)], [(5, DOP_NEQ, 2)],
                                 luts)
        assert not _sig_disjoint([(0, DOP_TRUE, 0)], [(0, DOP_TRUE, 0)],
                                 luts)
        # empty signatures
        assert not _sig_disjoint([], [(5, DOP_EQ, 1)], luts)

    def test_overlapping_signatures_fall_back_to_flat(self):
        """Two jobs whose CSI topologies OVERLAP must not lane-split:
        build_multi_inputs has to keep the flat sequential schedule."""
        from nomad_tpu.structs import CSIVolume, VolumeRequest
        h = Harness()
        nodes = [mock.node() for _ in range(40)]
        for n in nodes:
            n.csi_node_plugins["ebs0"] = True
        h.state.upsert_nodes(nodes)
        ids = [n.id for n in nodes]
        h.state.upsert_csi_volume(CSIVolume(
            id="vol-a", plugin_id="ebs0",
            topology_node_ids=tuple(ids[:30])))      # overlaps vol-b
        h.state.upsert_csi_volume(CSIVolume(
            id="vol-b", plugin_id="ebs0",
            topology_node_ids=tuple(ids[20:])))
        items = []
        for src in ("vol-a", "vol-b"):
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 10
            tg.volumes = {"data": VolumeRequest(
                name="data", type="csi", source=src, read_only=True)}
            h.state.upsert_job(job)
            items.append(BatchItem(job=job, tg=tg, count=10))
        eng = PlacementEngine(mesh=False)
        built = eng.build_multi_inputs(h.state.snapshot(), items, seed=3)
        assert built["cand_rows"] is None     # no disjointness proof
        assert built["n_lanes"] == 1
        # and the batch still places correctly on the flat path
        d = eng.place_batch(h.state.snapshot(), items, seed=3)
        assert sum(int((x.picks >= 0).sum()) for x in d) == 20


class TestCompactLanedKernel:
    """The compact lane-parallel multi-eval kernel (round-5: signatures
    with provably-disjoint landscapes run as concurrent lanes over
    per-signature candidate frames) must be decision- and metric-exact
    vs the flat sequential schedule.  Single-device engines: the mesh
    path keeps the flat schedule."""

    def _flat(self, fn):
        import nomad_tpu.ops.engine as em
        old = em.MAX_LANES
        em.MAX_LANES = 1          # width-1 cliques -> flat fallback path
        try:
            return fn()
        finally:
            em.MAX_LANES = old

    def test_fast_path_engages_on_zoned_batch(self):
        h, _ = build_zoned_cluster()
        items = zoned_items(h, 10, 30)
        eng = PlacementEngine(mesh=False)
        built = eng.build_multi_inputs(h.state.snapshot(), items, seed=3)
        assert built["cand_rows"] is not None
        assert built["n_lanes"] == 5
        assert built["perm"] is not None

    def test_parity_binpack(self):
        h, _ = build_zoned_cluster()
        items = zoned_items(h, 13, 40)
        snap = h.state.snapshot()
        d_c = PlacementEngine(mesh=False).place_batch(snap, items, seed=7)
        d_f = self._flat(
            lambda: PlacementEngine(mesh=False).place_batch(
                snap, items, seed=7))
        for a, b in zip(d_c, d_f):
            assert np.array_equal(a.picks, b.picks)
            for ma, mb in zip(a.metrics, b.metrics):
                assert ma.nodes_filtered == mb.nodes_filtered
                assert ma.nodes_exhausted == mb.nodes_exhausted
                assert ma.dimension_exhausted == mb.dimension_exhausted
                assert ([s.node_id for s in ma.score_meta_data]
                        == [s.node_id for s in mb.score_meta_data])

    def test_parity_spread_overflow(self):
        """Spread algorithm fans a round over more distinct nodes than
        the FILL_K small-buffer prefix: the collect path must detect the
        overflow and fall back to the device-resident full fills."""
        from nomad_tpu.ops.select import FILL_K
        from nomad_tpu.structs import (
            SCHED_ALGO_SPREAD, SchedulerConfiguration)
        h, _ = build_zoned_cluster()
        h.state.set_scheduler_config(SchedulerConfiguration(
            scheduler_algorithm=SCHED_ALGO_SPREAD))
        snap = h.state.snapshot()
        items = zoned_items(h, 6, FILL_K + 26)
        d_c = PlacementEngine(mesh=False).place_batch(snap, items, seed=5)
        d_f = self._flat(
            lambda: PlacementEngine(mesh=False).place_batch(
                snap, items, seed=5))
        for a, b in zip(d_c, d_f):
            assert np.array_equal(a.picks, b.picks)
        # the spread cap really did fan past the small prefix
        distinct = {p for a in d_c for p in a.picks.tolist() if p >= 0}
        assert len(distinct) > FILL_K

    def test_mesh_compact_parity(self):
        """The laned fast path composes with node-axis sharding: the
        8-virtual-device mesh engine must take the compact path on a
        zoned batch and decide exactly like the single-device engine
        (sorted picks per item — the two-stage top-k resolves ties in
        mesh order, so pick ORDER may differ within a round)."""
        h, _ = build_zoned_cluster(512)     # mesh-multiple node count
        items = zoned_items(h, 10, 30)
        snap = h.state.snapshot()
        mesh_eng = PlacementEngine()        # auto-mesh (8 devices)
        assert mesh_eng.mesh is not None
        built = mesh_eng.build_multi_inputs(snap, items, seed=9)
        assert built["cand_rows"] is not None, "mesh compact not engaged"
        assert built["cand_rows"].ndim == 3      # [S, L, Nc_loc]
        d_mesh = mesh_eng.place_batch(snap, items, seed=9)
        d_one = PlacementEngine(mesh=False).place_batch(snap, items,
                                                        seed=9)
        for a, b in zip(d_mesh, d_one):
            assert np.array_equal(np.sort(a.picks), np.sort(b.picks))
            for ma, mb in zip(a.metrics, b.metrics):
                assert ma.nodes_filtered == mb.nodes_filtered
                assert ma.nodes_exhausted == mb.nodes_exhausted

    def test_single_eval_bulk_overflow_fallback(self):
        """The single-eval bulk kernel's compact output must survive a
        round filling more distinct nodes than the FILL_K prefix (tiny
        nodes force ~2 allocs each): the engine refetches the resident
        full fills and the picks match the full-layout run exactly."""
        import nomad_tpu.ops.engine as em
        from nomad_tpu.ops.select import FILL_K

        h = Harness()
        nodes = []
        for _ in range(FILL_K * 2):
            n = mock.node()
            # mock nodes reserve cpu=100/mem=256: usable = 200/200,
            # exactly 2 of the 100/100 asks
            n.resources.cpu = 300
            n.resources.memory_mb = 456
            nodes.append(n)
        h.state.upsert_nodes(nodes)
        job = mock.batch_job()
        tg = job.task_groups[0]
        count = FILL_K * 3                 # > FILL_K distinct fills
        tg.count = count
        tg.tasks[0].resources.cpu = 100
        tg.tasks[0].resources.memory_mb = 100
        h.state.upsert_job(job)
        snap = h.state.snapshot()

        bd = PlacementEngine(mesh=False).place(
            snap, job, job.task_groups, None, bulk_api=True, seed=5,
            block=(tg.name, count))
        old = em.FILL_K
        em.FILL_K = 4096                   # full prefix: no overflow
        try:
            bd_full = PlacementEngine(mesh=False).place(
                snap, job, job.task_groups, None, bulk_api=True, seed=5,
                block=(tg.name, count))
        finally:
            em.FILL_K = old
        assert np.array_equal(bd.picks, bd_full.picks)
        placed = bd.picks[bd.picks >= 0]
        assert len(placed) == count
        assert len(np.unique(placed)) > FILL_K     # really overflowed
        counts = np.bincount(placed)
        assert counts.max() <= 2                   # capacity respected

    def test_job_count_seeds_respected(self):
        """A job with live allocs placing again through the compact path
        must see its existing per-node counts (anti-affinity seeds) —
        the compact [J', Nc] seed table gathers them onto the frame."""
        h, nodes = build_zoned_cluster(60, n_zones=2)
        items = zoned_items(h, 2, 8, n_zones=2)
        snap = h.state.snapshot()
        eng = PlacementEngine(mesh=False)
        first = eng.place_batch(snap, items, seed=3)
        from nomad_tpu.structs import Resources
        allocs = []
        for bd, it in zip(first, items):
            for p in bd.picks.tolist():
                if p >= 0:
                    allocs.append(mock.alloc(
                        job=it.job, node_id=bd.node_ids[p],
                        task_group=it.tg.name,
                        resources=Resources(cpu=10, memory_mb=10),
                        client_status="running"))
        h.state.upsert_allocs(allocs)
        snap2 = h.state.snapshot()
        d_c = PlacementEngine(mesh=False).place_batch(snap2, items, seed=4)
        d_f = self._flat(
            lambda: PlacementEngine(mesh=False).place_batch(
                snap2, items, seed=4))
        for a, b in zip(d_c, d_f):
            assert np.array_equal(a.picks, b.picks)


class TestPortSafetyInBatch:
    """Port asks must never ride the coupled-batch skip-fit path: each
    batched scheduler assigns ports from a private NetworkIndex over the
    same shared snapshot, so two batch-mates on one node pick identical
    dynamic ports — only the applier's AllocsFit port check catches it
    (reference: plan_apply.go evaluateNodePlan)."""

    def test_prepare_batch_accepts_port_asks(self):
        """Round-5 verdict #6: networked groups RIDE the batch (the
        worker's shared NetworkIndex keeps batch-mates' ports disjoint;
        round 4 excluded them entirely)."""
        from nomad_tpu.scheduler.generic import GenericScheduler
        from nomad_tpu.structs import NetworkResource, Port

        h, _ = build_cluster(20)
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 8
        tg.tasks[0].resources.networks = [NetworkResource(
            dynamic_ports=[Port(label="http")])]
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id, type=job.type)
        h.state.upsert_evals([e])
        sched = GenericScheduler(h.state.snapshot(), h, is_batch=True,
                                 now=NOW)
        assert sched.prepare_batch(e) is not None

    def test_batched_networked_jobs_get_disjoint_ports(self):
        """Several networked evals share one batch on a TINY cluster so
        batch-mates pile onto the same nodes: every committed (node,
        port) pair must be unique — the shared per-batch NetworkIndex is
        what prevents the identical-pick collision the old exclusion
        guarded against."""
        from nomad_tpu.structs import NetworkResource, Port

        s = Server(dev_mode=True, eval_batch=64)
        s.establish_leadership()
        for _ in range(3):
            s.register_node(mock.node(), now=NOW)
        jobs = []
        for _ in range(4):
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 6
            tg.tasks[0].resources.cpu = 10
            tg.tasks[0].resources.memory_mb = 10
            tg.tasks[0].resources.networks = [NetworkResource(
                dynamic_ports=[Port(label="http"),
                               Port(label="admin")])]
            jobs.append(job)
            s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        seen = set()
        live = 0
        for job in jobs:
            for a in snap.allocs_by_job(job.namespace, job.id):
                if a.terminal_status():
                    continue
                live += 1
                for label, port in a.allocated_ports.items():
                    key = (a.node_id, port)
                    assert key not in seen, (
                        f"port collision on {key} ({label})")
                    seen.add(key)
        assert live == 24          # every placement committed
        assert len(seen) == 48     # two unique ports per alloc

    def test_skip_fit_still_refutes_port_collision(self):
        """Defense at the serialization point: even a fenced coupled plan
        whose allocs carry port assignments must run the fit check — a
        static-port collision behind an intact fence is refuted, not
        committed."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import (NetworkResource, Plan, Port,
                                       Resources)

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        node = mock.node()
        state.upsert_node(node)
        job = mock.job()
        state.upsert_job(job)

        def mkplan(eid, bid, seq0):
            a = mock.alloc(job=job, node_id=node.id)
            a.resources = Resources(
                cpu=50, memory_mb=32,
                networks=[NetworkResource(
                    reserved_ports=[Port(label="http", value=8080)])])
            a.allocated_ports = {"http": 8080}
            p = Plan(eval_id=eid, job=job, coupled_batch=(bid, seq0))
            p.append_alloc(a)
            return p

        seq0 = state.placement_seq()
        p1 = q.enqueue(mkplan("e1", "batch1", seq0))
        applier.apply_one(p1)
        r1, err1 = p1.wait(1)
        assert err1 is None and not r1.refuted_nodes

        # same static port, same node, same (still-intact) chain fence
        p2 = q.enqueue(mkplan("e2", "batch1", seq0))
        applier.apply_one(p2)
        r2, err2 = p2.wait(1)
        assert err2 is None
        assert r2.refuted_nodes == [node.id]
        # the collision never reached state
        ports = [a.allocated_ports for a in
                 state.snapshot().allocs_by_node(node.id)
                 if not a.terminal_status()]
        assert ports == [{"http": 8080}]

    def test_static_port_job_places_end_to_end(self):
        """A static-port alloc carries its port in BOTH allocated_ports
        and its resources ask; the applier must not read that as a
        self-collision (regression: allocs_fit double-counted it)."""
        from nomad_tpu.structs import NetworkResource, Port

        s = Server(dev_mode=True, eval_batch=64)
        s.establish_leadership()
        for _ in range(4):
            s.register_node(mock.node(), now=NOW)
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 3
        tg.tasks[0].resources.networks = [NetworkResource(
            reserved_ports=[Port(label="http", value=8080)])]
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 3
        # static port -> three distinct nodes, each alloc owns 8080
        assert len({a.node_id for a in live}) == 3
        assert all(a.allocated_ports.get("http") == 8080 for a in live)


class TestMultiWorkerSafety:
    """Per-node fencing, delivery-token gating, and partitioned dequeue —
    the machinery that lets num_schedulers-style concurrent workers
    coexist with the coupled-batch fast path (reference contrast:
    nomad/worker.go workers dequeue blindly and resolve every collision
    at plan apply; here disjoint workers never even collide)."""

    def test_per_node_fence_tolerates_own_chain(self):
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan

        state = StateStore()
        n1, n2 = mock.node(), mock.node()
        state.upsert_node(n1)
        state.upsert_node(n2)
        job = mock.job()
        state.upsert_job(job)
        seq0 = state.placement_seq()
        # chain A commits on n1
        a = mock.alloc(job=job, node_id=n1.id)
        plan = Plan(eval_id="e1", job=job, coupled_batch=("chainA", seq0))
        plan.append_alloc(a)
        from nomad_tpu.structs import PlanResult
        state.upsert_plan_results(plan, PlanResult(
            node_allocation=plan.node_allocation))
        # chain A's own write on n1 is tolerated; a foreign view is not
        assert state.nodes_unchanged_since([n1.id], seq0, "chainA")
        assert not state.nodes_unchanged_since([n1.id], seq0, "chainB")
        # n2 untouched: everyone passes
        assert state.nodes_unchanged_since([n2.id], seq0, "chainB")

    def test_stale_delivery_token_rejected_at_applier(self):
        """An eval redelivered while worker A sat in a device compile:
        worker A's plan must be rejected, not double-committed
        (reference: the EvalToken check at plan submission)."""
        s = Server(dev_mode=True)
        s.establish_leadership()
        s.register_node(mock.node(), now=NOW)
        job = mock.job()
        job.task_groups[0].count = 1
        ev = s.register_job(job, now=NOW)

        # worker A dequeues; then the delivery expires and B gets it
        e1, tok_a = s.eval_broker.dequeue(["service"], now=NOW)
        assert e1.id == ev.id
        s.eval_broker.tick(NOW + 10_000)          # expire A's delivery
        e2, tok_b = s.eval_broker.dequeue(["service"], now=NOW + 10_000)
        assert e2.id == ev.id and tok_b != tok_a

        from nomad_tpu.core.plan_apply import PendingPlan, StaleDeliveryError
        from nomad_tpu.structs import Plan
        stale = Plan(eval_id=ev.id, eval_token=tok_a, job=job)
        stale.append_alloc(mock.alloc(job=job,
                                      node_id=s.state.snapshot().nodes()[0].id))
        p = PendingPlan(stale)
        s.plan_applier.apply_one(p)
        result, err = p.wait(1)
        assert result is None and isinstance(err, StaleDeliveryError)
        assert s.plan_applier.stats["stale_token"] == 1
        # the CURRENT delivery's plan commits fine
        fresh = Plan(eval_id=ev.id, eval_token=tok_b, job=job)
        fresh.append_alloc(mock.alloc(job=job,
                                      node_id=s.state.snapshot().nodes()[0].id))
        p2 = PendingPlan(fresh)
        s.plan_applier.apply_one(p2)
        result2, err2 = p2.wait(1)
        assert err2 is None and not result2.refuted_nodes

    def test_partitioned_dequeue_single_key_batches(self):
        """With partition_of set (num_workers > 1), each batch carries a
        single placement-domain signature; other signatures stay queued
        for the next worker."""
        from nomad_tpu.structs import VolumeRequest

        s = Server(dev_mode=True, num_workers=2)
        s.establish_leadership()
        for _ in range(4):
            n = mock.node()
            n.csi_node_plugins["ebs0"] = True
            s.register_node(n, now=NOW)
        from nomad_tpu.structs import CSIVolume
        for z in ("a", "b"):
            s.state.upsert_csi_volume(CSIVolume(id=f"vol-{z}",
                                                plugin_id="ebs0"))
        jobs = []
        for i in range(6):
            job = mock.batch_job()
            job.task_groups[0].count = 1
            job.task_groups[0].volumes = {
                "d": VolumeRequest(name="d", type="csi",
                                   source=f"vol-{'a' if i % 2 else 'b'}",
                                   read_only=True)}
            s.register_job(job, now=NOW)
            jobs.append(job)
        batch1 = s.eval_broker.dequeue_batch(
            ["service", "batch"], 16, now=NOW)
        batch2 = s.eval_broker.dequeue_batch(
            ["service", "batch"], 16, now=NOW)
        assert len(batch1) == 3 and len(batch2) == 3
        key1 = {s._eval_partition(ev) for ev, _ in batch1}
        key2 = {s._eval_partition(ev) for ev, _ in batch2}
        assert len(key1) == 1 and len(key2) == 1 and key1 != key2
