"""Seeded traffic generator (chaos/traffic.py): determinism, workload
mix, fault pairing, capacity ledger, and the verified-idempotent retry
discipline."""

import json

import pytest

from nomad_tpu.chaos.traffic import (
    DEFAULT_SCENARIOS,
    FaultyCall,
    TrafficProfile,
    fleet,
    generate_schedule,
    retry_idempotent,
    stable_id,
)

KINDS = {"job.register", "job.deploy", "job.scale", "job.stop",
         "node.drain", "node.restore", "node.flap", "chaos"}


def _blob(events):
    return json.dumps(events, sort_keys=True).encode()


class TestDeterminism:
    def test_same_seed_byte_identical(self):
        p = TrafficProfile()
        assert _blob(generate_schedule(7, p)) == \
            _blob(generate_schedule(7, p))

    def test_different_seed_differs(self):
        p = TrafficProfile()
        assert _blob(generate_schedule(1, p)) != \
            _blob(generate_schedule(2, p))

    def test_fleet_stable(self):
        p = TrafficProfile(n_nodes=5, n_zones=2)
        a, b = fleet(3, p), fleet(3, p)
        assert a == b
        assert [s["datacenter"] for s in a] == \
            ["dc1", "dc2", "dc1", "dc2", "dc1"]
        assert len({s["id"] for s in a}) == 5

    def test_stable_id_is_not_positional_soup(self):
        assert stable_id("node", 1, 2) != stable_id("node", 12, "")
        assert len(stable_id("x")) == 32


class TestScheduleShape:
    def setup_method(self):
        self.p = TrafficProfile(hours=1.0)
        self.events = generate_schedule(11, self.p)

    def test_sorted_and_known_kinds(self):
        ats = [e["at"] for e in self.events]
        assert ats == sorted(ats)
        assert {e["kind"] for e in self.events} <= KINDS

    def test_mixed_workload_present(self):
        kinds = [e["kind"] for e in self.events]
        regs = [e for e in self.events if e["kind"] == "job.register"]
        assert {e["jtype"] for e in regs} == {"service", "batch",
                                              "system"}
        assert "node.drain" in kinds and "node.flap" in kinds

    def test_drains_paired_with_restores(self):
        drains = [e for e in self.events if e["kind"] == "node.drain"]
        restores = {(e["node"], e["at"])
                    for e in self.events if e["kind"] == "node.restore"}
        assert drains
        for d in drains:
            assert (d["node"], round(d["at"] + d["duration"], 3)) \
                in restores

    def test_chaos_interleaved_inside_active_window(self):
        chaos = [e for e in self.events if e["kind"] == "chaos"]
        assert [e["scenario"] for e in chaos] == list(DEFAULT_SCENARIOS)
        active_end = self.p.hours * 3600 * (1 - self.p.quiet_tail_frac)
        for e in chaos:
            assert 0 < e["at"] < active_end
            assert e["seed"] == 11 * 1000 + chaos.index(e)

    def test_faults_stay_clear_of_quiet_tail(self):
        active_end = self.p.hours * 3600 * (1 - self.p.quiet_tail_frac)
        for e in self.events:
            if e["kind"] in ("node.drain", "node.flap"):
                assert e["at"] + e["duration"] < active_end

    def test_batch_runtimes_clear_the_tail(self):
        active_end = self.p.hours * 3600 * (1 - self.p.quiet_tail_frac)
        for e in self.events:
            if e["kind"] == "job.register" and "runtime_s" in e \
                    and e["jtype"] == "batch" and \
                    e["job"].startswith("bat-"):
                assert e["at"] + e["runtime_s"] < active_end

    def test_capacity_ledger_bounds_standing_demand(self):
        """Replaying register/scale/stop events against a cpu ledger
        must never exceed the capacity fraction — that bound is what
        makes 'every surviving demand placed' a reachable target."""
        budget = (self.p.n_nodes * self.p.node_cpu
                  * self.p.capacity_fraction)
        booked = {}
        for e in self.events:
            if e["kind"] == "job.register" and e["jtype"] == "service":
                booked[e["job"]] = e["count"] * e["cpu"]
            elif e["kind"] == "job.scale":
                booked[e["job"]] = e["count"] * e["cpu"]
            elif e["kind"] == "job.stop":
                booked.pop(e["job"], None)
            assert sum(booked.values()) <= budget + 1e-9


class TestRetryIdempotent:
    def test_clean_call_single_attempt(self):
        result, n = retry_idempotent(lambda: 42, lambda: False)
        assert (result, n) == (42, 1)

    def test_landed_but_reply_lost_is_not_reissued(self):
        state = []
        op = FaultyCall(lambda: state.append("x"), fail_first=1)
        result, n = retry_idempotent(op, lambda: bool(state))
        assert result is None and n == 1
        assert state == ["x"]          # applied exactly once

    def test_not_landed_reissues_until_success(self):
        state = []
        calls = []

        def op():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("injected: request lost")
            state.append("x")
            return "ok"

        result, n = retry_idempotent(op, lambda: bool(state))
        assert (result, n) == ("ok", 3)
        assert state == ["x"]

    def test_budget_spent_raises_last_error(self):
        def op():
            raise ConnectionError("down")
        with pytest.raises(ConnectionError):
            retry_idempotent(op, lambda: False, attempts=3)
