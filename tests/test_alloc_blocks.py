"""Columnar alloc blocks (structs.block.AllocBlock): bulk placements
commit as picks + template, materialize lazily on read, and convert to
ordinary table rows the moment a member alloc is written.

No reference analog — this replaces stock's per-placement Allocation
materialization (scheduler/generic_sched.go computePlacements), which the
round-3 profile showed costing more than the device placement work.
"""

import numpy as np

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import AllocBlock, Allocation, Resources

NOW = 1.7e9


def run_bulk(count=100, n_nodes=20, eval_batch=0, cpu=100, mem=64):
    s = Server(dev_mode=True, eval_batch=eval_batch)
    s.establish_leadership()
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = 8000
        n.resources.memory_mb = 16384
        s.register_node(n, now=NOW)
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.cpu = cpu
    job.task_groups[0].tasks[0].resources.memory_mb = mem
    s.register_job(job, now=NOW)
    s.process_all(now=NOW)
    return s, job


class TestBlockCommit:
    def test_bulk_placement_commits_columnar(self):
        s, job = run_bulk(count=100)
        # the commit itself stayed columnar: a live block, no table rows
        assert s.state._alloc_blocks, "bulk placements should be a block"
        assert not s.state._allocs_by_job.get((job.namespace, job.id))
        # reads materialize lazily and see ordinary allocs
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 100
        names = {a.name for a in live}
        assert f"{job.id}.{job.task_groups[0].name}[0]" in names
        assert len({a.id for a in live}) == 100
        assert all(a.create_index > 0 for a in live)
        # per-node reads agree with per-job reads
        by_node_total = sum(
            len(snap.allocs_by_node(nid))
            for nid in {a.node_id for a in live})
        assert by_node_total == 100

    def test_alloc_by_id_reads_block_rows(self):
        s, job = run_bulk(count=80)
        snap = s.state.snapshot()
        some = snap.allocs_by_job(job.namespace, job.id)[5]
        assert snap.alloc_by_id(some.id).id == some.id
        assert s.state.alloc_by_id(some.id).id == some.id

    def test_member_write_materializes_block(self):
        s, job = run_bulk(count=80)
        assert s.state._alloc_blocks
        a = s.state.allocs_by_job(job.namespace, job.id)[0]
        upd = a.copy_skip_job()
        upd.client_status = "complete"
        s.state.update_allocs_from_client([upd])
        # representation flipped: block gone, all rows in tables
        assert not s.state._alloc_blocks
        bucket = s.state._allocs_by_job[(job.namespace, job.id)]
        assert len(bucket) == 80
        assert bucket[a.id].client_status == "complete"
        # non-updated rows keep their identity
        live = [x for x in s.state.allocs_by_job(job.namespace, job.id)
                if not x.terminal_status()]
        assert len(live) == 79

    def test_snapshot_isolation_across_materialization(self):
        s, job = run_bulk(count=80)
        snap_before = s.state.snapshot()
        a = s.state.allocs_by_job(job.namespace, job.id)[0]
        upd = a.copy_skip_job()
        upd.client_status = "failed"
        s.state.update_allocs_from_client([upd])
        snap_after = s.state.snapshot()
        # both views count every alloc exactly once
        before = snap_before.allocs_by_job(job.namespace, job.id)
        after = snap_after.allocs_by_job(job.namespace, job.id)
        assert len(before) == len(after) == 80
        assert len({x.id for x in before}) == 80
        # the old snapshot must not see the update
        assert all(x.client_status == "pending" for x in before)
        assert sum(x.client_status == "failed" for x in after) == 1

    def test_usage_tracked_through_block_lifecycle(self):
        s, job = run_bulk(count=100, cpu=50, mem=32)
        packer = s.engine.packer
        t = packer.update(s.state.snapshot())
        assert int(t.used[:, 0].sum()) == 100 * 50
        assert int(t.used[:, 1].sum()) == 100 * 32
        # a member going terminal releases exactly its usage
        a = s.state.allocs_by_job(job.namespace, job.id)[0]
        upd = a.copy_skip_job()
        upd.client_status = "complete"
        s.state.update_allocs_from_client([upd])
        t = packer.update(s.state.snapshot())
        assert int(t.used[:, 0].sum()) == 99 * 50
        assert int(t.used[:, 1].sum()) == 99 * 32

    def test_snapshot_save_restore_flattens_blocks(self):
        s, job = run_bulk(count=80)
        assert s.state._alloc_blocks
        doc = s.state.snapshot_save()
        from nomad_tpu.state import StateStore
        fresh = StateStore()
        fresh.snapshot_restore(doc)
        live = [a for a in fresh.allocs_by_job(job.namespace, job.id)
                if not a.terminal_status()]
        assert len(live) == 80
        assert not fresh._alloc_blocks

    def test_same_id_stop_through_plan_materializes(self):
        """A later plan stopping a block member (job update path) sees it
        as its predecessor."""
        s, job = run_bulk(count=80)
        a = s.state.allocs_by_job(job.namespace, job.id)[0]
        from nomad_tpu.structs import Plan, PlanResult
        stop = a.copy_skip_job()
        plan = Plan(eval_id="stop", job=job)
        plan.append_stopped_alloc(stop, "test stop")
        result = PlanResult(node_update=plan.node_update)
        s.state.upsert_plan_results(plan, result)
        got = s.state.alloc_by_id(a.id)
        assert got.desired_status == "stop"
        assert got.create_index == a.create_index   # predecessor seen
        live = [x for x in s.state.allocs_by_job(job.namespace, job.id)
                if not x.terminal_status() and x.desired_status == "run"]
        assert len(live) == 79


class TestBlockApplier:
    def test_broken_fence_expands_blocks(self):
        """With a foreign write between snapshot and apply, block plans
        take the full per-node path (and still commit correctly)."""
        s, job = run_bulk(count=100, eval_batch=64)
        stats = s.plan_applier.stats
        assert stats["fast_path"] >= 1
        # now force full checks: concurrent foreign writes each round
        job2 = mock.batch_job()
        job2.task_groups[0].count = 100
        job2.task_groups[0].tasks[0].resources.cpu = 10
        job2.task_groups[0].tasks[0].resources.memory_mb = 10
        s.register_job(job2, now=NOW + 1)
        # break the fence mid-flight: a node write after the snapshot
        s.register_node(mock.node(), now=NOW + 1)
        s.process_all(now=NOW + 1)
        snap = s.state.snapshot()
        live = [a for a in snap.allocs_by_job(job2.namespace, job2.id)
                if not a.terminal_status()]
        assert len(live) == 100

    def test_down_node_in_block_refutes_only_that_node(self):
        """Whole-block admission fails when a picked node is down; the
        expansion path refutes that node's rows and commits the rest."""
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import Plan

        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        applier = PlanApplier(state, q)
        n1, n2 = mock.node(), mock.node()
        state.upsert_node(n1)
        state.upsert_node(n2)
        job = mock.batch_job()
        state.upsert_job(job)
        tg = job.task_groups[0]
        tmpl = Allocation(namespace=job.namespace, job_id=job.id, job=job,
                          task_group=tg.name, desired_status="run",
                          client_status="pending",
                          resources=Resources(cpu=10, memory_mb=10))
        from nomad_tpu.structs import new_ids
        ids = new_ids(10)
        block = AllocBlock(id="blk1", template=tmpl, ids=ids,
                           name_prefix=f"{job.id}.{tg.name}[",
                           indexes=list(range(10)),
                           picks=np.array([0, 1] * 5, np.int32),
                           node_table=[n1.id, n2.id])
        seq0 = state.placement_seq()
        state.update_node_status(n2.id, "down")
        plan = Plan(eval_id="e1", job=job, coupled_batch=("b1", seq0))
        plan.alloc_blocks = [block]
        p = q.enqueue(plan)
        applier.apply_one(p)
        result, err = p.wait(1)
        assert err is None
        assert result.refuted_nodes == [n2.id]
        snap = state.snapshot()
        assert len(snap.allocs_by_node(n1.id)) == 5
        assert len(snap.allocs_by_node(n2.id)) == 0
