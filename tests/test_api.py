"""HTTP API + SDK + event stream + CLI (reference: command/agent/http.go,
api/, nomad/stream/)."""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent
from nomad_tpu.api.client import APIClient, APIException
from nomad_tpu.structs import codec


@pytest.fixture(scope="module")
def agent():
    ag = Agent(num_clients=2, num_workers=1, heartbeat_ttl=3600)
    ag.start()
    yield ag
    ag.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(address=agent.address)


def _wire_batch_job(count=2, run_for=300):
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].config = {"run_for_s": run_for}
    return codec.encode(job), job


def _wait(fn, timeout=60, period=0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    return fn()


class TestJobsAPI:
    def test_register_status_allocs_stop(self, api):
        wire, job = _wire_batch_job()
        resp = api.jobs.register(wire)
        assert resp["EvalID"]

        stubs = api.jobs.list()
        assert any(s["ID"] == job.id for s in stubs)

        info = api.jobs.info(job.id)
        assert info["ID"] == job.id and info["Type"] == "batch"

        allocs = _wait(lambda: api.jobs.allocations(job.id))
        assert len(allocs) == 2
        assert all(a["JobID"] == job.id for a in allocs)

        evals = api.jobs.evaluations(job.id)
        assert evals and evals[0]["JobID"] == job.id

        resp = api.jobs.deregister(job.id)
        stopped = _wait(lambda: api.jobs.info(job.id).get("Stop"))
        assert stopped

    def test_job_plan_dry_run(self, api):
        wire, job = _wire_batch_job(count=3)
        out = api.jobs.plan(wire, diff=True)
        assert out["CreatedAllocs"] == 3
        assert out["FailedTGAllocs"] == {}
        # plan is a dry run: nothing registered
        with pytest.raises(APIException):
            api.jobs.info(job.id)

    def test_dispatch_and_periodic(self, api):
        job = mock.batch_job()
        job.parameterized = None
        from nomad_tpu.structs import ParameterizedJobConfig
        job.parameterized = ParameterizedJobConfig(meta_required=["k"])
        api.jobs.register(codec.encode(job))
        resp = api.jobs.dispatch(job.id, b"payload", {"k": "v"})
        assert resp["DispatchedJobID"].startswith(job.id + "/dispatch-")
        with pytest.raises(APIException) as e:
            api.jobs.dispatch(job.id, b"", {})
        assert "missing required meta" in str(e.value)

    def test_node_endpoints(self, api, agent):
        nodes = api.nodes.list()
        assert len(nodes) == 2
        info = api.nodes.info(nodes[0]["ID"])
        assert info["ID"] == nodes[0]["ID"]

        api.nodes.eligibility(nodes[0]["ID"], False)
        assert _wait(lambda: api.nodes.info(
            nodes[0]["ID"])["SchedulingEligibility"] == "ineligible")
        api.nodes.eligibility(nodes[0]["ID"], True)

    def test_operator_scheduler_config(self, api):
        cfg = api.operator.scheduler_config()["SchedulerConfig"]
        assert cfg["SchedulerAlgorithm"] in ("binpack", "spread")
        cfg["SchedulerAlgorithm"] = "spread"
        api.operator.set_scheduler_config(cfg)
        cfg2 = api.operator.scheduler_config()["SchedulerConfig"]
        assert cfg2["SchedulerAlgorithm"] == "spread"
        cfg2["SchedulerAlgorithm"] = "binpack"
        api.operator.set_scheduler_config(cfg2)

    def test_agent_and_metrics(self, api):
        self_ = api.agent.self()
        assert self_["config"]["Server"]["Enabled"]
        m = api.agent.metrics()
        assert "nomad.state.nodes" in m

    def test_system_gc(self, api):
        api.system.gc()   # must not error

    def test_search(self, api, agent):
        wire, job = _wire_batch_job()
        api.jobs.register(wire)
        out = api.request("PUT", "/v1/search",
                          body={"Prefix": job.id[:10], "Context": "jobs"})
        assert job.id in out["Matches"]["jobs"]


class TestEventStream:
    def test_stream_delivers_job_events(self, api, agent):
        wire, job = _wire_batch_job()
        got = []
        done = threading.Event()

        def consume():
            # replay may deliver earlier jobs' events first; wait for OURS
            for batch in api.events.stream(topics=["Job:*"]):
                got.extend(batch["Events"])
                if any(e["Topic"] == "Job" and e["Key"] == job.id
                       for e in got):
                    done.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        api.jobs.register(wire)
        assert done.wait(10), "no Job event for the registered job"
        ev = next(e for e in got if e["Key"] == job.id)
        assert ev["Payload"]["ID"] == job.id


class TestBlockingQueries:
    def test_jobs_list_blocks_until_index(self, api, agent):
        idx = agent.server.state.latest_index()

        result = {}

        def blocked():
            result["jobs"] = api.request(
                "GET", "/v1/jobs", params={"index": idx, "wait": 10})

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        time.sleep(0.2)
        wire, job = _wire_batch_job()
        api.jobs.register(wire)
        t.join(timeout=10)
        assert not t.is_alive()
        assert any(s["ID"] == job.id for s in result["jobs"])


class TestCLI:
    def test_cli_against_live_agent(self, agent, tmp_path, capsys):
        from nomad_tpu.cli import main
        addr = agent.address

        spec = tmp_path / "cli-job.hcl"
        spec.write_text('''
job "cli-demo" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    count = 1
    task "t" {
      driver = "mock"
      config { run_for_s = 300 }
      resources { cpu = 100 memory = 64 }
    }
  }
}
''')
        assert main(["-address", addr, "job", "run", str(spec)]) == 0
        out = capsys.readouterr().out
        assert "registered" in out

        assert main(["-address", addr, "job", "status"]) == 0
        assert "cli-demo" in capsys.readouterr().out

        assert main(["-address", addr, "node", "status"]) == 0
        assert main(["-address", addr, "eval", "list"]) == 0
        assert main(["-address", addr, "operator", "scheduler",
                     "get-config"]) == 0
        assert main(["-address", addr, "job", "stop", "cli-demo"]) == 0
        capsys.readouterr()


class TestAgentConfig:
    def test_parse_and_merge(self, tmp_path):
        from nomad_tpu.agent_config import load_agent_config
        base = tmp_path / "base.hcl"
        base.write_text('''
bind_addr = "0.0.0.0"
server { num_schedulers = 4 heartbeat_ttl = "45s" }
client { count = 3 meta { rack = "r9" } }
''')
        override = tmp_path / "override.hcl"
        override.write_text('ports { http = 5555 }\nacl { enabled = true }')
        cfg = load_agent_config([str(base), str(override)])
        assert cfg.bind_addr == "0.0.0.0"
        assert cfg.num_workers == 4
        assert cfg.heartbeat_ttl == 45.0
        assert cfg.client_count == 3
        assert cfg.client_meta == {"rack": "r9"}
        assert cfg.http_port == 5555
        assert cfg.acl_enabled

    def test_example_config_parses(self):
        from pathlib import Path
        from nomad_tpu.agent_config import load_agent_config
        example = (Path(__file__).parent.parent / "examples"
                   / "agent.hcl")
        cfg = load_agent_config([str(example)])
        assert cfg.num_workers == 2 and cfg.heartbeat_ttl == 60.0
        assert cfg.node_class == "compute"

    def test_unknown_setting_rejected(self):
        import pytest as _pytest
        from nomad_tpu.agent_config import parse_agent_config
        with _pytest.raises(ValueError):
            parse_agent_config("data_dir_typo = \"/x\"")


class TestScaleAndVolumes:
    def test_job_scale(self, api, agent):
        wire, job = _wire_batch_job(count=1)
        api.jobs.register(wire)
        _wait(lambda: api.jobs.allocations(job.id))
        api.jobs.scale(job.id, "worker", 3)
        allocs = _wait(lambda: len([
            a for a in api.jobs.allocations(job.id)
            if a["DesiredStatus"] == "run"]) == 3 or None)
        assert allocs
        info = api.jobs.info(job.id)
        assert info["TaskGroups"][0]["Count"] == 3
        with pytest.raises(APIException):
            api.jobs.scale(job.id, "nope", 2)

    def test_csi_volume_lifecycle_and_claims(self, api, agent):
        from nomad_tpu.structs import VolumeRequest, compute_class
        api.volumes.register("vol-data", "ebs-plugin",
                             AccessMode="multi-node-multi-writer")
        vols = api.volumes.list()
        assert any(v["ID"] == "vol-data" for v in vols)

        # node advertising the plugin; job claiming the volume
        s = agent.server
        from nomad_tpu import mock
        n = mock.node()
        n.csi_node_plugins = {"ebs-plugin": True}
        n.computed_class = compute_class(n)
        s.register_node(n)
        job = mock.batch_job()
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].config = {"run_for_s": 300}
        job.task_groups[0].volumes = {
            "data": VolumeRequest(name="data", type="csi",
                                  source="vol-data")}
        api.jobs.register(codec.encode(job))
        allocs = _wait(lambda: api.jobs.allocations(job.id))
        assert allocs and allocs[0]["NodeID"] == n.id, \
            "csi job must land on the plugin node"
        vol = _wait(lambda: (api.volumes.info("vol-data")
                             if api.volumes.info("vol-data")["WriteAllocs"]
                             else None))
        assert allocs[0]["ID"] in vol["WriteAllocs"]

        # claimed volume cannot be deregistered
        with pytest.raises(APIException):
            api.volumes.deregister("vol-data")

        # terminal alloc releases the claim
        api.jobs.deregister(job.id, purge=True)
        released = _wait(lambda: not api.volumes.info(
            "vol-data")["WriteAllocs"] or None)
        assert released
        api.volumes.deregister("vol-data")
        with pytest.raises(APIException):
            api.volumes.info("vol-data")

    def test_single_writer_volume_refuses_second_claim(self, api, agent):
        from nomad_tpu import mock
        from nomad_tpu.structs import VolumeRequest, compute_class
        api.volumes.register("vol-sw", "ebs-plugin",
                             AccessMode="single-node-writer")
        s = agent.server
        n = mock.node()
        n.csi_node_plugins = {"ebs-plugin": True}
        n.computed_class = compute_class(n)
        s.register_node(n)

        def vol_job():
            j = mock.batch_job()
            j.task_groups[0].count = 1
            j.task_groups[0].tasks[0].config = {"run_for_s": 300}
            j.task_groups[0].volumes = {
                "d": VolumeRequest(name="d", type="csi", source="vol-sw")}
            return j

        j1 = vol_job()
        api.jobs.register(codec.encode(j1))
        assert _wait(lambda: api.jobs.allocations(j1.id))
        assert _wait(lambda: api.volumes.info("vol-sw")["WriteAllocs"]
                     or None)

        j2 = vol_job()
        api.jobs.register(codec.encode(j2))
        # second writer is refuted at plan apply: eval fails or blocks,
        # no alloc commits
        time.sleep(3)
        assert not [a for a in api.jobs.allocations(j2.id)
                    if a["DesiredStatus"] == "run"], \
            "single-writer volume accepted a second writer"
        api.jobs.deregister(j1.id, purge=True)
        api.jobs.deregister(j2.id, purge=True)
        _wait(lambda: not api.volumes.info("vol-sw")["WriteAllocs"]
              or None)
        api.volumes.deregister("vol-sw")


class TestUISurfaces:
    def test_ui_serves_exec_and_diff_views(self, agent):
        """The SPA ships the exec-terminal and version-diff views
        (VERDICT r3 #8) and they are wired into the hash router."""
        import urllib.request
        with urllib.request.urlopen(agent.address + "/ui/") as r:
            html = r.read().decode()
        for needle in ("viewExec", "viewDiff", "p[0] === 'exec'",
                       "p[0] === 'diff'", "termcmd", "PAUSE_REFRESH"):
            assert needle in html, needle

    def test_exec_surface_the_terminal_drives(self, api, agent):
        """The terminal's POST /v1/client/allocation/:id/exec round-trip
        against a running mock-driver task."""
        import base64

        wire, job = _wire_batch_job(count=1)
        api.jobs.register(wire)
        allocs = _wait(lambda: [
            a for a in api.jobs.allocations(job.id)
            if a["ClientStatus"] == "running"])
        assert allocs
        out = api.request(
            "POST", f"/v1/client/allocation/{allocs[0]['ID']}/exec",
            body={"Cmd": ["/bin/sh", "-c", "echo terminal-ping"]})
        assert out["ExitCode"] == 0
        assert "terminal-ping" in base64.b64decode(
            out["Output"]).decode()

    def test_interactive_exec_streams_both_ways(self, api, agent):
        """Round-5 verdict #8 done-criterion: an INTERACTIVE shell
        session against a mock-driver task with streaming both ways —
        open a session, read the streamed prompt, send stdin, read the
        echoed response, exit cleanly."""
        import base64

        wire, job = _wire_batch_job(count=1)
        api.jobs.register(wire)
        allocs = _wait(lambda: [
            a for a in api.jobs.allocations(job.id)
            if a["ClientStatus"] == "running"])
        assert allocs
        base = f"/v1/client/allocation/{allocs[0]['ID']}/exec"
        sid = api.request("POST", base, body={
            "Cmd": ["/bin/sh"], "Interactive": True})["SessionId"]

        def read_until(needle: bytes, offset: int) -> tuple:
            buf = b""
            for _ in range(20):
                out = api.request(
                    "GET", f"{base}/{sid}/stream",
                    params={"offset": offset, "timeout": 2})
                buf += base64.b64decode(out.get("Data") or "")
                offset = out["Offset"]
                if needle in buf or out.get("Exited"):
                    return buf, offset, out
            raise AssertionError(f"never saw {needle!r} in {buf!r}")

        # output direction: the fake shell's prompt streams first
        buf, off, _ = read_until(b"mock-shell$", 0)
        # stdin direction: a line goes in, its echo streams back
        api.request("POST", f"{base}/{sid}/stdin", body={
            "Data": base64.b64encode(b"hello there\n").decode()})
        buf, off, _ = read_until(b"you said: hello there", off)
        # second round trip on the SAME session (it's a session, not
        # one-shot)
        api.request("POST", f"{base}/{sid}/stdin", body={
            "Data": base64.b64encode(b"second line\n").decode()})
        buf, off, _ = read_until(b"you said: second line", off)
        # clean exit
        api.request("POST", f"{base}/{sid}/stdin", body={
            "Data": base64.b64encode(b"exit\n").decode()})
        _, _, out = read_until(b"\xff\xff", off)   # drain to exit
        assert out["Exited"] and out["ExitCode"] == 0
        api.request("DELETE", f"{base}/{sid}")
        # the session is gone
        with pytest.raises(APIException):
            api.request("GET", f"{base}/{sid}/stream",
                        params={"offset": 0, "timeout": 1})

    def test_version_diff_data(self, api, agent):
        """The diff view's data source: two versions with a visible
        count change."""
        wire, job = _wire_batch_job(count=1)
        api.jobs.register(wire)
        wire2 = dict(wire)
        wire2["TaskGroups"] = [dict(wire["TaskGroups"][0], Count=3)]
        api.jobs.register(wire2)
        vs = api.request(
            "GET", f"/v1/job/{job.id}/versions")["Versions"]
        assert [v["Version"] for v in vs][:2] == [1, 0]
        assert vs[0]["TaskGroups"][0]["Count"] == 3
        assert vs[1]["TaskGroups"][0]["Count"] == 1
