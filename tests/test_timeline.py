"""Retrospective timeline plane (core/timeline.py, ISSUE 15):
clock-aligned columnar history + cross-plane annotations.

Determinism is the load-bearing property: a VirtualClock soak must
replay a byte-identical CANONICAL dump for the same seed (the same
gate the trace digest already passes), eviction must be counted and
never silent, query aggregation must be exact on known inputs, the
post-mortem must attribute a seeded flap-storm's breach to the storm's
own annotation, and pool-child deltas must merge losslessly under
`col@origin` names."""

import json

import pytest

from nomad_tpu.chaos.clock import VirtualClock
from nomad_tpu.chaos.soak import run_soak
from nomad_tpu.chaos.traffic import TrafficProfile
from nomad_tpu.core.telemetry import MetricsRegistry
from nomad_tpu.core.timeline import (CANONICAL_SERIES, REPORT_SCHEMA,
                                     SCHEMA, Timeline, build_report,
                                     render_report_md, sparkline)

# no drains: drain batch pacing is sweep-ordering shaped (like the
# flight event ring, it is deliberately outside the byte-identity
# gate); flap storms stay in — heartbeat expiry lands on quiesced
# virtual-time boundaries so misses ARE canonical
STORMY = dict(hours=0.05, n_nodes=4, n_zones=2, service_per_hour=40,
              batch_per_hour=40, drains_per_hour=0.0,
              flap_storms_per_hour=20.0, flap_storm_nodes=2,
              preempt_storms_per_hour=0.0, chaos_scenarios=())


def _mini(step_s=1.0, max_points=8192, max_annotations=4096):
    """An isolated Timeline over its own registry + VirtualClock —
    no interference with the process singleton."""
    clock = VirtualClock(start=1000.0)
    reg = MetricsRegistry(clock=clock)
    tl = Timeline(clock=clock, registry=reg, step_s=step_s,
                  max_points=max_points,
                  max_annotations=max_annotations)
    tl.reset()
    return tl, reg, clock


class TestSoakByteIdentity:
    def test_same_seed_same_canonical_dump(self):
        p = TrafficProfile(**STORMY)
        a = run_soak(seed=7, profile=p)
        b = run_soak(seed=7, profile=p)
        assert a.ok and b.ok, (a.violations, b.violations)
        ja = json.dumps(a.timeline_canonical, sort_keys=True)
        jb = json.dumps(b.timeline_canonical, sort_keys=True)
        assert ja == jb
        assert (a.summary["timeline_digest"]
                == b.summary["timeline_digest"])
        # the dump actually carries history, not a vacuous match
        assert a.timeline_canonical["Schema"] == SCHEMA
        assert len(a.timeline_canonical["Buckets"]) > 10
        assert set(a.timeline_canonical["Series"]) \
            == set(CANONICAL_SERIES)
        kinds = {x["Kind"] for x in a.timeline_canonical["Annotations"]}
        assert "traffic.node.flap" in kinds
        assert "leadership.established" in kinds

    def test_summary_carries_timeline_keys_within_budget(self):
        r = run_soak(seed=5, profile=TrafficProfile(**STORMY))
        s = r.summary
        assert s["timeline_points"] > 10
        assert s["timeline_annotations"] > 0
        assert s["timeline_evictions"] == 0
        # the 2% budget is gated at bench scale (scripts/perfcheck.py)
        # and measured over the standard soak in PERF.md §18; a ~4s
        # quick soak amortizes nothing, so only gross blowups fail here
        assert 0.0 <= s["timeline_overhead_fraction"] <= 0.05
        assert len(s["timeline_digest"]) == 64
        int(s["timeline_digest"], 16)
        # the full query doc + report ride the result
        assert r.timeline["Schema"] == SCHEMA
        assert r.report["Schema"] == REPORT_SCHEMA


class TestRings:
    def test_point_eviction_is_counted_never_silent(self):
        tl, reg, clock = _mini(max_points=4)
        for i in range(10):
            tl.sample(now=float(i))
        assert len(tl.query()["Series"]["nodes_in_use"]) <= 4
        st = tl.snapshot_stats()
        assert st["points"] == 4
        assert st["point_evictions"] == 6
        assert st["samples"] == 10
        # oldest buckets went first
        assert tl.window() == [6.0, 10.0]

    def test_settled_row_survives_racy_resample(self):
        tl, reg, clock = _mini()
        reg.set_gauge("nomad.quality.nodes_in_use", 3.0)
        tl.sample(now=5.2, settled=True)
        reg.set_gauge("nomad.quality.nodes_in_use", 99.0)
        tl.sample(now=5.8)                       # same bucket, unsettled
        pts = tl.query(series=["nodes_in_use"])["Series"]["nodes_in_use"]
        assert [p["Last"] for p in pts] == [3.0]
        # a later settled sample MAY replace a settled row
        reg.set_gauge("nomad.quality.nodes_in_use", 4.0)
        tl.sample(now=5.9, settled=True)
        pts = tl.query(series=["nodes_in_use"])["Series"]["nodes_in_use"]
        assert [p["Last"] for p in pts] == [4.0]

    def test_annotation_rings_are_partitioned(self):
        """A storm of volatile annotations (executor invalidations)
        must never evict the canonical stream."""
        tl, reg, clock = _mini(max_annotations=3)
        tl.annotate("chaos.begin", now=1.0, scenario="x")
        tl.annotate("health.breach", now=2.0, rule="r")
        for i in range(50):
            tl.annotate("executor.invalidation", now=3.0 + i,
                        reason="chain")
        anns = tl.query()["Annotations"] if tl.window() else []
        st = tl.snapshot_stats()
        assert st["volatile_evictions"] == 47
        assert st["annotation_evictions"] == 0
        dump = tl.canonical_dump()
        kinds = [a["Kind"] for a in dump["Annotations"]]
        assert kinds == ["chaos.begin", "health.breach"]
        assert all(a["Kind"] != "executor.invalidation"
                   for a in dump["Annotations"])
        del anns

    def test_disabled_timeline_records_nothing(self):
        tl, reg, clock = _mini()
        tl.enabled = False
        tl.sample(now=1.0)
        tl.annotate("chaos.begin", now=1.0)
        assert tl.window() is None
        assert tl.canonical_dump()["Annotations"] == []


class TestQuery:
    def test_rejects_unknown_series_and_bad_ranges(self):
        tl, reg, clock = _mini()
        tl.sample(now=1.0)
        with pytest.raises(ValueError, match="unknown timeline series"):
            tl.query(series=["nope"])
        with pytest.raises(ValueError, match="step"):
            tl.query(step=0)
        with pytest.raises(ValueError, match="step"):
            tl.query(step=-1.0)
        with pytest.raises(ValueError, match="end"):
            tl.query(start=10.0, end=1.0)

    def test_empty_timeline_queries_clean(self):
        tl, reg, clock = _mini()
        doc = tl.query()
        assert doc["Points"] == 0
        assert all(v == [] for v in doc["Series"].values())
        assert doc["Annotations"] == []
        assert tl.window() is None

    def test_step_aggregation_min_max_avg_last(self):
        """Exact aggregation over known raw values: merged `col@origin`
        columns pass raw numbers through `_native`, so the arithmetic
        is checkable to the digit."""
        tl, reg, clock = _mini()
        samples = [[t, {"acked": v}] for t, v in
                   [(0, 1.0), (1, 3.0), (2, 5.0), (3, 7.0)]]
        tl.merge_delta({"Seq": 4, "StepS": 1.0, "Samples": samples,
                        "Annotations": []}, origin="w1")
        doc = tl.query(series=["acked@w1"], step=2.0)
        pts = doc["Series"]["acked@w1"]
        assert [p["T"] for p in pts] == [0.0, 2.0]
        assert pts[0] == {"T": 0.0, "Min": 1.0, "Max": 3.0, "Avg": 2.0,
                          "Last": 3.0, "Count": 2}
        assert pts[1] == {"T": 2.0, "Min": 5.0, "Max": 7.0, "Avg": 6.0,
                          "Last": 7.0, "Count": 2}
        # half-open range [start, end): t=2 excluded
        doc = tl.query(series=["acked@w1"], step=1.0, start=0.0,
                       end=2.0)
        assert [p["T"] for p in doc["Series"]["acked@w1"]] == [0.0, 1.0]

    def test_first_bucket_rates_are_none_not_zero(self):
        """A rate needs the previous bucket; the first one is unknowable
        and must be absent from aggregation, never fabricated as 0."""
        tl, reg, clock = _mini()
        reg.inc("nomad.broker.acked", 10)
        tl.sample(now=0.5)
        reg.inc("nomad.broker.acked", 4)
        tl.sample(now=1.5)
        pts = tl.query(series=["evals_per_s"])["Series"]["evals_per_s"]
        # only the second bucket has a derivable rate: 4 acks / 1s
        assert [p["T"] for p in pts] == [1.0]
        assert pts[0]["Last"] == 4.0

    def test_run_relative_counters_rebase_on_reset(self):
        tl, reg, clock = _mini()
        reg.inc("nomad.broker.acked", 1000)    # pre-run residue
        tl.reset()
        reg.inc("nomad.broker.acked", 2)
        tl.sample(now=0.0, settled=True)
        reg.inc("nomad.broker.acked", 2)
        tl.sample(now=1.0, settled=True)
        dump = tl.canonical_dump()
        # cum column stores raw minus the reset() base, so two same-seed
        # runs in one process agree regardless of prior traffic
        i = dump["Buckets"].index(0)
        pts = tl.query(series=["evals_per_s"])["Series"]["evals_per_s"]
        assert pts[0]["Last"] == 2.0
        assert i == 0


class TestReport:
    def _dump(self):
        anns = [
            {"T": 95.0, "Kind": "traffic.node.flap", "node": "n1"},
            {"T": 100.0, "Kind": "health.breach",
             "rule": "heartbeat_misses", "observed": 3.0,
             "threshold": 0.0},
            {"T": 170.0, "Kind": "health.recover",
             "rule": "heartbeat_misses"},
            {"T": 400.0, "Kind": "traffic.job.deploy", "job": "svc-1"},
        ]
        pts = [{"T": float(t), "Min": 1.0, "Max": 1.0, "Avg": 1.0,
                "Last": 1.0, "Count": 1} for t in range(90, 110)]
        return {"Schema": SCHEMA, "Start": 90.0, "End": 110.0,
                "Step": 1.0, "Points": 20,
                "Series": {"nodes_in_use": pts}, "Annotations": anns}

    def test_breach_attributed_to_nearest_annotation(self):
        rep = build_report(self._dump())
        assert rep["Schema"] == REPORT_SCHEMA
        breaches = [i for i in rep["Incidents"] if i["Kind"] == "breach"]
        assert len(breaches) == 1
        inc = breaches[0]
        assert inc["Rule"] == "heartbeat_misses"
        attr = inc["Attribution"]
        assert attr, "breach must be attributed"
        # nearest-in-time wins; health.* kinds never self-attribute
        assert attr[0]["Kind"] == "traffic.node.flap"
        assert attr[0]["DtS"] == -5.0
        assert all(not a["Kind"].startswith("health.") for a in attr)
        # the deploy at t=400 is outside the 60s window
        assert all(a["Kind"] != "traffic.job.deploy" for a in attr)

    def test_spike_needs_positive_baseline(self):
        """An idle-most-of-the-window series (median 0) must not flag
        every blip as an infinite-ratio spike."""
        pts = [{"T": float(t), "Min": 0.0, "Max": 0.0, "Avg": 0.0,
                "Last": 0.0, "Count": 1} for t in range(20)]
        pts[10] = {"T": 10.0, "Min": 4.0, "Max": 4.0, "Avg": 4.0,
                   "Last": 4.0, "Count": 1}
        doc = {"Start": 0.0, "End": 20.0, "Points": 20,
               "Series": {"evals_per_s": pts}, "Annotations": []}
        assert build_report(doc)["Incidents"] == []

    def test_flap_storm_soak_attributes_heartbeat_breach(self):
        """The acceptance scenario: a seeded flap-storm soak run with a
        zero-tolerance heartbeat SLO must produce a breach the report
        pins on the storm's own traffic annotation."""
        r = run_soak(seed=7, profile=TrafficProfile(**STORMY),
                     slo={"heartbeat_misses": 0.0})
        rep = build_report(r.timeline)
        breaches = [i for i in rep["Incidents"]
                    if i["Kind"] == "breach"
                    and i["Rule"] == "heartbeat_misses"]
        assert breaches, rep["AnnotationKinds"]
        attributed = [a for i in breaches for a in i["Attribution"]]
        assert any(a["Kind"].startswith("traffic.node.")
                   for a in attributed), attributed
        # and the Markdown face names the storm
        md = render_report_md(rep)
        assert "heartbeat_misses" in md
        assert "traffic.node." in md

    def test_render_helpers(self):
        assert len(sparkline([1.0, 2.0, 3.0], width=8)) == 3
        assert len(sparkline(list(map(float, range(100))), width=8)) == 8
        assert sparkline([None, 1.0], width=4) == "·▁"
        md = render_report_md(build_report(self._dump()))
        assert md.startswith("# Timeline retrospective")


class TestDeltaMerge:
    def test_child_delta_merges_under_origin_names(self):
        child, creg, _ = _mini()
        creg.inc("nomad.broker.acked", 3)
        child.sample(now=2.0)
        child.annotate("pool.respawn", now=2.5, worker=1, respawn=1)
        delta = child.export_delta(since_seq=0)
        assert delta["Samples"] and delta["Annotations"]

        parent, preg, _ = _mini()
        parent.sample(now=2.2)
        parent.merge_delta(delta, origin="pool-1")
        doc = parent.query(series=["acked@pool-1"])
        pts = doc["Series"]["acked@pool-1"]
        assert [p["Last"] for p in pts] == [3.0]
        anns = doc["Annotations"]
        assert any(a["Kind"] == "pool.respawn"
                   and a.get("Origin") == "pool-1" for a in anns)
        st = parent.snapshot_stats()
        assert st["merges"] == 1
        assert st["merged_points"] == 1
        assert st["merged_annotations"] == 1
        # merged (origin-tagged) annotations stay out of the canonical
        # stream — child timing is not replayable
        assert parent.canonical_dump()["Annotations"] == []

    def test_export_delta_high_water_mark(self):
        tl, reg, _ = _mini()
        tl.sample(now=1.0)
        d1 = tl.export_delta(since_seq=0)
        assert len(d1["Samples"]) == 1
        # nothing new since d1 -> empty delta
        d2 = tl.export_delta(since_seq=d1["Seq"])
        assert d2["Samples"] == [] and d2["Annotations"] == []
        tl.sample(now=2.0)
        tl.annotate("drain.begin", now=2.1, node="n1")
        d3 = tl.export_delta(since_seq=d1["Seq"])
        assert len(d3["Samples"]) == 1
        assert [a["Kind"] for a in d3["Annotations"]] == ["drain.begin"]

    def test_merge_rebuckets_foreign_step(self):
        parent, _, _ = _mini(step_s=2.0)
        delta = {"Seq": 1, "StepS": 1.0,
                 "Samples": [[5, {"acked": 9.0}]],  # child t=5s
                 "Annotations": []}
        parent.merge_delta(delta, origin="w")
        pts = parent.query(series=["acked@w"])["Series"]["acked@w"]
        assert [p["T"] for p in pts] == [4.0]      # bucket 2 @ step 2s
