"""Wire codec + authenticated framing tests (reference trust model:
nomad msgpack-RPC with optional encryption — the wire is DATA ONLY and,
with a cluster key set, unauthenticated frames are dropped)."""

import socket
import struct

import pytest

from nomad_tpu import mock
from nomad_tpu.core import wire

try:                                  # the image may lack the optional
    import cryptography  # noqa: F401 - AEAD/RSA dep (gated, not assumed)
    HAS_CRYPTO = True
except ModuleNotFoundError:
    HAS_CRYPTO = False

requires_crypto = pytest.mark.skipif(
    not HAS_CRYPTO, reason="cryptography not installed in this image")


@pytest.fixture(autouse=True)
def _reset_key():
    yield
    wire.set_key(None)


class TestCodec:
    def test_scalar_roundtrip(self):
        msg = {"type": "append", "term": 3, "entries": [(1, 2, b"x")],
               "ok": True, "none": None, "f": 1.5}
        out = wire.unpackb(wire.packb(msg))
        assert out["term"] == 3
        assert out["entries"][0][2] == b"x"
        assert out["none"] is None

    def test_dataclass_roundtrip(self):
        job = mock.job()
        out = wire.unpackb(wire.packb({"args": (job,)}))
        job2 = out["args"][0]
        assert type(job2).__name__ == "Job"
        assert job2.id == job.id
        assert job2.task_groups[0].tasks[0].name == \
            job.task_groups[0].tasks[0].name

    def test_node_roundtrip(self):
        node = mock.node()
        node2 = wire.unpackb(wire.packb(node))
        assert node2.id == node.id
        assert node2.resources.cpu == node.resources.cpu

    def test_set_roundtrip(self):
        assert wire.unpackb(wire.packb({"s": {3, 1, 2}}))["s"] == {1, 2, 3}

    def test_unregistered_class_rejected(self):
        class Sneaky:
            pass

        with pytest.raises(TypeError):
            wire.packb(Sneaky())

    def test_unknown_dataclass_name_rejected_on_decode(self):
        import msgpack

        # hand-craft an ext frame claiming a class outside the registry
        evil = msgpack.packb(
            {"x": msgpack.ExtType(1, wire.packb(["PosixPath", {}]))})
        with pytest.raises(ValueError):
            wire.unpackb(evil)


@requires_crypto
class TestFrameAuth:
    def test_encrypted_roundtrip(self):
        wire.set_key("cluster-secret")
        frame = wire.encode_frame({"a": 1})
        assert wire.decode_body(frame[4:]) == {"a": 1}

    def test_replay_rejected(self):
        wire.set_key("cluster-secret")
        body = wire.encode_frame({"op": "deregister"})[4:]
        assert wire.decode_body(body) == {"op": "deregister"}
        with pytest.raises(ValueError):   # byte-identical resend
            wire.decode_body(body)

    def test_tampered_frame_rejected(self):
        wire.set_key("cluster-secret")
        body = bytearray(wire.encode_frame({"a": 1})[4:])
        body[-1] ^= 1
        with pytest.raises(ValueError):
            wire.decode_body(bytes(body))

    def test_plaintext_frame_rejected_when_keyed(self):
        import msgpack

        wire.set_key("cluster-secret")
        for payload in ({"a": 1}, {"pad": "x" * 64}):
            with pytest.raises(ValueError):
                wire.decode_body(msgpack.packb(payload))

    def test_wrong_key_rejected(self):
        wire.set_key("key-a")
        frame = wire.encode_frame({"a": 1})
        wire.set_key("key-b", force=True)
        with pytest.raises(ValueError):
            wire.decode_body(frame[4:])

    def test_conflicting_key_raises(self):
        """The key is process-global: silently swapping clusters is a
        bug, not a feature (one cluster per process)."""
        wire.set_key("key-a")
        with pytest.raises(ValueError):
            wire.set_key("key-b")
        # same key: idempotent AND keeps the replay cache (a second
        # same-key Agent must not reopen the replay window)
        body = wire.encode_frame({"x": 1})[4:]
        wire.decode_body(body)
        wire.set_key("key-a")
        with pytest.raises(ValueError):
            wire.decode_body(body)     # still a replay after re-set
        wire.set_key(None)             # explicit reset allowed
        wire.set_key("key-b")          # fresh install after reset

    def test_plaintext_agent_in_keyed_process_rejected(self):
        from nomad_tpu.agent import Agent

        wire.set_key("cluster-secret")
        with pytest.raises(ValueError):
            Agent(client_enabled=False)   # default encrypt="" must not
                                          # silently strip the key

    def test_channel_binding(self):
        """A frame bound to one (channel, direction, listener) must not
        authenticate anywhere else — no cross-plane or reflected replay."""
        wire.set_key("cluster-secret")
        addr_a = ("127.0.0.1", 4646)
        addr_b = ("127.0.0.1", 4647)
        tag = wire.channel_tag("raft", "req", addr_a)
        body = wire.encode_frame({"type": "append"}, tag=tag)[4:]
        # wrong plane, wrong listener, reflected direction: all rejected
        for bad in (wire.channel_tag("serf", "req", addr_a),
                    wire.channel_tag("raft", "req", addr_b),
                    wire.channel_tag("raft", "rep", addr_a),
                    b""):
            with pytest.raises(ValueError):
                wire.decode_body(body, tag=bad)
        assert wire.decode_body(body, tag=tag) == {"type": "append"}

    def test_forged_flood_does_not_grow_replay_cache(self):
        """Unauthenticated frames must neither grow _seen_nonces nor
        pre-poison a legitimate frame's nonce (nonce registration happens
        only after the GCM tag verifies)."""
        import os
        import struct as _struct
        import time as _time

        wire.set_key("cluster-secret")
        real = wire.encode_frame({"op": "x"})[4:]
        ts, nonce = real[:8], real[8:20]
        with wire._seen_lock:
            base = len(wire._seen_nonces)
        # flood: garbage ciphertexts with fresh timestamps + the REAL
        # frame's ts+nonce with a forged body (the pre-poison attack)
        for _ in range(50):
            forged = (_struct.pack(">d", _time.time()) + os.urandom(12)
                      + os.urandom(48))
            with pytest.raises(ValueError):
                wire.decode_body(forged)
        with pytest.raises(ValueError):
            wire.decode_body(ts + nonce + os.urandom(48))
        with wire._seen_lock:
            assert len(wire._seen_nonces) == base   # nothing registered
        # the authentic frame still decodes (nonce was never poisoned)
        assert wire.decode_body(real) == {"op": "x"}
        # ... and only now is its nonce live
        with pytest.raises(ValueError):
            wire.decode_body(real)

    def test_replay_cache_hard_cap_fails_closed(self, monkeypatch):
        """Overflow while the oldest nonce is UNEXPIRED rejects the new
        frame instead of evicting (an evicted fresh nonce would let a
        captured frame replay inside its freshness window — ADVICE r3)."""
        wire.set_key("cluster-secret")
        monkeypatch.setattr(wire, "MAX_SEEN_NONCES", 64)
        accepted = 0
        rejected = 0
        for i in range(200):
            try:
                wire.decode_body(wire.encode_frame({"i": i})[4:])
                accepted += 1
            except ValueError:
                rejected += 1
        # the cap holds, overflow traffic is rejected (not silently
        # weakening replay protection), and the cache never exceeds the
        # cap by more than the in-flight frame
        assert accepted >= 64
        assert rejected == 200 - accepted
        with wire._seen_lock:
            assert len(wire._seen_nonces) <= 65

    def test_confidentiality(self):
        wire.set_key("cluster-secret")
        frame = wire.encode_frame({"secret": "hunter2-hunter2"})
        assert b"hunter2" not in frame

    def test_stale_frame_rejected(self, monkeypatch):
        # a sender whose injected clock (wire.set_clock) runs far behind
        # stamps frames outside the freshness window — the receiver on
        # the real clock drops them
        wire.set_key("cluster-secret")

        class Skewed(wire.SystemClock):
            def time(self):
                return super().time() - 2 * wire.REPLAY_WINDOW_S

        monkeypatch.setattr(wire, "_CLOCK", Skewed())
        body = wire.encode_frame({"a": 1})[4:]
        monkeypatch.setattr(wire, "_CLOCK", wire.SystemClock())
        with pytest.raises(ValueError):
            wire.decode_body(body)

    def test_no_key_plain_frames(self):
        frame = wire.encode_frame({"a": 1})
        assert wire.decode_body(frame[4:]) == {"a": 1}


class TestRPCAllowlist:
    def test_endpoint_rejects_non_rpc_methods(self):
        """A reachable RPC port must not dispatch arbitrary attributes."""
        from nomad_tpu.core.cluster import ClusterServer
        from nomad_tpu.core.raft import send_msg

        s = ClusterServer("s-allow", bootstrap_expect=1,
                          heartbeat_interval=0.04,
                          election_timeout=(0.15, 0.3))
        s.start(tick_interval=0.2)
        try:
            import time
            deadline = time.time() + 8
            while not s.is_leader() and time.time() < deadline:
                time.sleep(0.05)
            assert s.is_leader()
            for method in ("shutdown", "rpc_call", "_fsm_apply",
                           "establish_leadership", "__init__"):
                r = send_msg(s.rpc.addr, {"method": method, "args": (),
                                          "kwargs": {}}, timeout=2.0)
                assert r is not None
                assert not r.get("ok"), f"{method} was dispatched!"
            # a legitimate method still works
            r = send_msg(s.rpc.addr,
                         {"method": "register_node",
                          "args": (mock.node(),), "kwargs": {}},
                         timeout=2.0)
            assert r is not None and r.get("ok"), r
        finally:
            s.shutdown()

    @requires_crypto
    def test_unauthenticated_peer_rejected(self):
        """With a cluster key set, a keyless frame gets no reply."""
        from nomad_tpu.core.membership import Gossip

        wire.set_key("secret")
        g = Gossip("auth-a", ("127.0.0.1", 0))
        g.start()
        try:
            # raw unauthenticated (plain msgpack) frame
            import msgpack
            body = msgpack.packb({"type": "sync", "members": []})
            with socket.create_connection(g.addr, timeout=2.0) as s:
                s.sendall(struct.pack(">I", len(body)) + body)
                s.settimeout(0.5)
                try:
                    data = s.recv(4)
                except (socket.timeout, OSError):
                    data = b""
            assert data == b""
        finally:
            g.stop()
