"""Periodic + parameterized dispatch (reference: nomad/periodic.go,
Job.Dispatch)."""

import calendar
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core import Server
from nomad_tpu.core.periodic import CronSpec
from nomad_tpu.structs import ParameterizedJobConfig, PeriodicConfig

NOW = calendar.timegm((2026, 7, 1, 12, 0, 0))   # Wed Jul 1 2026 12:00 UTC


class TestCronSpec:
    def test_every_minute(self):
        assert CronSpec("* * * * *").next(NOW) == NOW + 60

    def test_specific_minute(self):
        # next :30 after 12:00 is 12:30
        assert CronSpec("30 * * * *").next(NOW) == NOW + 30 * 60

    def test_step(self):
        assert CronSpec("*/15 * * * *").next(NOW) == NOW + 15 * 60

    def test_daily_shortcut(self):
        nxt = CronSpec("@daily").next(NOW)
        tm = time.gmtime(nxt)
        assert (tm.tm_hour, tm.tm_min, tm.tm_mday) == (0, 0, 2)

    def test_dow(self):
        # next Sunday (dow 0) after Wed Jul 1 2026 is Jul 5
        nxt = CronSpec("0 0 * * 0").next(NOW)
        tm = time.gmtime(nxt)
        assert tm.tm_mday == 5 and tm.tm_wday == 6   # Python Sunday=6

    def test_bad_spec(self):
        with pytest.raises(ValueError):
            CronSpec("* * *")


class TestPeriodicDispatch:
    def _server(self):
        s = Server(dev_mode=True, heartbeat_ttl=10**9)
        s.establish_leadership()
        for _ in range(3):
            s.register_node(mock.node(), now=NOW)
        return s

    def test_parent_not_scheduled_child_launched(self):
        s = self._server()
        job = mock.batch_job()
        job.periodic = PeriodicConfig(spec="*/5 * * * *")
        ev = s.register_job(job, now=NOW)
        assert ev is None, "periodic parent gets no eval"
        s.process_all(now=NOW)
        assert s.state.allocs_by_job(job.namespace, job.id) == []

        s.tick(now=NOW + 5 * 60 + 1)
        children = [j for j in s.state.snapshot().jobs()
                    if j.parent_id == job.id]
        assert len(children) == 1
        assert children[0].id == f"{job.id}/periodic-{NOW + 5 * 60}"
        assert children[0].periodic is None
        s.process_all(now=NOW + 5 * 60 + 1)
        assert s.state.allocs_by_job(job.namespace, children[0].id)

    def test_prohibit_overlap(self):
        s = self._server()
        job = mock.batch_job()
        job.periodic = PeriodicConfig(spec="* * * * *",
                                      prohibit_overlap=True)
        s.register_job(job, now=NOW)
        s.tick(now=NOW + 61)
        s.process_all(now=NOW + 61)
        # first child is still running (allocs pending)
        s.tick(now=NOW + 121)
        children = [j for j in s.state.snapshot().jobs()
                    if j.parent_id == job.id]
        assert len(children) == 1, "overlapping launch suppressed"

    def test_force_run(self):
        s = self._server()
        job = mock.batch_job()
        job.periodic = PeriodicConfig(spec="0 0 1 1 *")   # yearly
        s.register_job(job, now=NOW)
        child = s.periodic.force_run(job.namespace, job.id, now=NOW + 1)
        assert child is not None and child.parent_id == job.id

    def test_leadership_restores_tracking(self):
        s = self._server()
        job = mock.batch_job()
        job.periodic = PeriodicConfig(spec="*/5 * * * *")
        s.register_job(job, now=NOW)
        s2_tracker = s.periodic._tracked
        assert job.ns_id() in s2_tracker
        # a fresh leadership pass (e.g. leader flap) re-tracks from state
        s.periodic._tracked.clear()
        s.periodic._next.clear()
        s.establish_leadership()
        assert job.ns_id() in s.periodic._tracked


class TestDispatch:
    def _server(self):
        s = Server(dev_mode=True, heartbeat_ttl=10**9)
        s.establish_leadership()
        for _ in range(3):
            s.register_node(mock.node(), now=NOW)
        return s

    def _param_job(self, **cfg):
        job = mock.batch_job()
        job.parameterized = ParameterizedJobConfig(**cfg)
        return job

    def test_dispatch_creates_running_child(self):
        s = self._server()
        job = self._param_job(payload="optional",
                              meta_required=["input"],
                              meta_optional=["verbose"])
        assert s.register_job(job, now=NOW) is None
        child, err = s.dispatch_job(job.namespace, job.id,
                                    payload=b"data",
                                    meta={"input": "x"}, now=NOW + 1)
        assert err == "" and child is not None
        assert child.dispatched and child.payload == b"data"
        assert child.meta["input"] == "x"
        assert child.parameterized is None
        s.process_all(now=NOW + 1)
        assert s.state.allocs_by_job(job.namespace, child.id)

    def test_dispatch_validation(self):
        s = self._server()
        job = self._param_job(payload="required", meta_required=["k"])
        s.register_job(job, now=NOW)
        _, err = s.dispatch_job(job.namespace, job.id, meta={"k": "v"})
        assert "payload is required" in err
        _, err = s.dispatch_job(job.namespace, job.id, payload=b"x")
        assert "missing required meta" in err
        _, err = s.dispatch_job(job.namespace, job.id, payload=b"x",
                                meta={"k": "v", "zzz": "1"})
        assert "unexpected meta" in err
        _, err = s.dispatch_job(job.namespace, "nope")
        assert err == "job not found"
        plain = mock.batch_job()
        s.register_job(plain, now=NOW)
        _, err = s.dispatch_job(plain.namespace, plain.id)
        assert "not parameterized" in err
