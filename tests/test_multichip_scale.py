"""Sharded scale soak (ISSUE 7): the quality gauges must hold their
50k-node envelope when the SAME zoned workload runs mesh-sharded at
>=200k virtual nodes — scale must buy throughput, not quality drift.

The cluster is the bench's north-star shape (3 DCs, 5 storage zones,
zone-pinned CSI volumes) shrunk to a soak-sized placement count.  Two
gauges, two sources:

  - per-STORAGE-zone nodes-used balance (bench.py's
    quality_zone_balance_max_over_min; 1.0 at 50k in BENCH_r05) must
    stay <= 1.05 at 200k — density never collapses a volume zone;
  - the live state-store aggregates behind
    nomad.quality.{zone_balance_max_over_min,binpack_fill} (PR 5, zone
    = datacenter there) must not DRIFT from what the identical
    workload measures at 50k.

Tier-1 excludes this (slow marker); the CI multichip stage runs it.
"""

import time

import jax
import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import CSIVolume, VolumeRequest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(jax.device_count() < 2,
                       reason="needs the virtual multi-device mesh"),
]

N_EVALS = 20
PER_EVAL = 800


def _zoned_nodes(n):
    import random
    rng = random.Random(0)
    nodes = []
    zone_nodes = {z: [] for z in range(5)}
    for i in range(n):
        nd = mock.node()
        nd.datacenter = f"dc{1 + i % 3}"
        nd.attributes["storage.topology"] = f"zone{i % 5}"
        nd.csi_node_plugins["ebs0"] = True
        nd.resources.cpu = rng.choice([4000, 8000, 16000])
        nd.resources.memory_mb = rng.choice([8192, 16384, 32768])
        nodes.append(nd)
        zone_nodes[i % 5].append(nd.id)
    vols = [CSIVolume(id=f"vol-zone{z}", plugin_id="ebs0",
                      access_mode="multi-node-multi-writer",
                      topology_node_ids=tuple(zone_nodes[z]))
            for z in range(5)]
    return nodes, vols


def _run_workload(n_nodes):
    """The north-star workload shape at `n_nodes`; returns (live
    quality_summary, per-storage-zone nodes-used balance)."""
    s = Server(dev_mode=False, num_workers=1, eval_batch=N_EVALS,
               heartbeat_ttl=1e9, nack_timeout=600.0)
    assert s.engine.mesh is not None
    assert s.engine.n_devices >= 2
    s.establish_leadership()
    nodes, vols = _zoned_nodes(n_nodes)
    s.state.upsert_nodes(nodes)
    for v in vols:
        s.state.upsert_csi_volume(v)

    evals, jobs = [], []
    for i in range(N_EVALS):
        job = mock.batch_job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = PER_EVAL
        tg.tasks[0].resources.cpu = 10
        tg.tasks[0].resources.memory_mb = 10
        tg.volumes = {"data": VolumeRequest(
            name="data", type="csi", source=f"vol-zone{i % 5}",
            read_only=True)}
        evals.append(s.register_job(job, now=time.time()))
        jobs.append(job)

    s.start_scheduling()
    deadline = time.time() + 900
    pending = {e.id for e in evals}
    while pending and time.time() < deadline:
        done = set()
        for eid in pending:
            ev = s.state.eval_by_id(eid)
            if ev is not None and ev.status in ("complete", "failed",
                                                "canceled"):
                done.add(eid)
        pending -= done
        if pending:
            time.sleep(0.1)
    s.stop_scheduling()
    assert not pending, f"{len(pending)} evals never finished"

    snap = s.state.snapshot()
    placed = sum(1 for job in jobs
                 for a in snap.allocs_by_job(job.namespace, job.id)
                 if not a.terminal_status())
    assert placed == N_EVALS * PER_EVAL, placed
    assert s.plan_applier.stats["plans_refuted"] == 0

    # bench.py's quality axis: nodes-used per STORAGE zone (density
    # must not collapse a volume zone)
    zone_of = {nd.id: nd.attributes["storage.topology"] for nd in nodes}
    used = {a.node_id
            for job in jobs
            for a in snap.allocs_by_job(job.namespace, job.id)
            if not a.terminal_status()}
    per_zone = {f"zone{z}": 0 for z in range(5)}
    for nid in used:
        per_zone[zone_of[nid]] += 1
    counts = sorted(per_zone.values())
    assert counts[0] > 0, per_zone
    zone_nodes_balance = counts[-1] / counts[0]

    q = s.state.quality_summary()
    s.shutdown()
    return q, zone_nodes_balance


def test_quality_gauges_hold_at_200k_sharded():
    q_50k, znb_50k = _run_workload(50_000)       # the envelope
    q_200k, znb_200k = _run_workload(200_000)    # the scaled run

    # density never collapses a volume zone, at either scale (the 50k
    # bench envelope: 1.0 in BENCH_r05; <= 1.05 is the ISSUE 7 gate)
    assert znb_50k <= 1.05, znb_50k
    assert znb_200k <= 1.05, znb_200k

    # the live gauges hold the 50k envelope: the per-DC alloc-balance
    # gauge must not drift (zone-pinned binpack legitimately skews DCs
    # a little — the gate is "no WORSE sharded at 4x the nodes"), and
    # bin-pack fill stays dense
    assert q_200k["zone_balance_max_over_min"] <= \
        q_50k["zone_balance_max_over_min"] * 1.05, (q_50k, q_200k)
    assert q_200k["nodes_in_use"] > 0
    assert q_200k["fill_cpu"] >= q_50k["fill_cpu"] - 0.15, (q_50k, q_200k)
    assert q_200k["fill_memory"] >= q_50k["fill_memory"] - 0.15, \
        (q_50k, q_200k)
