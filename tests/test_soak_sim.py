"""Virtual-time production soak (chaos/soak.py): the full agent driven
through the real HTTP API on a VirtualClock, gated on chaos invariants
AND the live health plane.

Fast tests run a shrunk profile (a few virtual minutes, ~3s wall); the
default 2h-virtual profile with chaos scenarios interleaved is
@pytest.mark.slow and runs in the dedicated CI soak stage."""

import pytest

from nomad_tpu.chaos.soak import coarse_fingerprint, run_soak
from nomad_tpu.chaos.traffic import TrafficProfile

TINY = dict(hours=0.05, n_nodes=4, n_zones=2, service_per_hour=40,
            batch_per_hour=40, drains_per_hour=10,
            flap_storms_per_hour=0, preempt_storms_per_hour=0,
            chaos_scenarios=())

CHURNY = dict(hours=0.1, n_nodes=4, n_zones=2, service_per_hour=30,
              batch_per_hour=30, drains_per_hour=10,
              flap_storms_per_hour=10, flap_storm_nodes=2,
              preempt_storms_per_hour=10, chaos_scenarios=())

SUMMARY_KEYS = {"seed", "soak_virtual_hours", "soak_evals",
                "soak_breaches", "converged_fingerprint",
                "trace_digest", "schedule_events", "wall_s",
                "compression_x", "p99_plan_queue_ms", "quality", "ok",
                "timeline_points", "timeline_annotations",
                "timeline_overhead_fraction", "timeline_evictions",
                "timeline_digest", "rss_bytes", "rss_peak_bytes",
                "journal_bytes", "journal_entries",
                "journal_compactions", "journal_bytes_reclaimed",
                "journal_floor_fallbacks", "ring_evictions",
                "mem_scrape_us", "mem_overhead_fraction"}


def test_tiny_soak_green_and_summarized():
    r = run_soak(seed=1, profile=TrafficProfile(**TINY))
    assert r.ok, r.violations
    assert r.summary["soak_breaches"] == 0
    assert r.summary["soak_evals"] > 0
    assert r.summary["soak_virtual_hours"] >= 0.05
    assert set(r.summary) == SUMMARY_KEYS
    assert r.summary["converged_fingerprint"] == r.fingerprint
    assert r.summary["quality"]["nodes_in_use"] > 0


def test_same_seed_byte_identical_replay():
    p = TrafficProfile(**CHURNY)
    a = run_soak(seed=3, profile=p)
    b = run_soak(seed=3, profile=p)
    assert a.ok and b.ok, (a.violations, b.violations)
    assert a.digest == b.digest
    assert a.fingerprint == b.fingerprint
    assert a.trace.canonical_bytes() == b.trace.canonical_bytes()


def test_different_seed_different_life():
    p = TrafficProfile(**TINY)
    a = run_soak(seed=1, profile=p)
    b = run_soak(seed=2, profile=p)
    assert a.ok and b.ok, (a.violations, b.violations)
    assert a.digest != b.digest


def test_churny_soak_survives_flaps_and_preemption():
    """Flap storms knock heartbeats out (allocs go lost, nodes go down
    and come back), preemption storms evict low-priority work — the
    converged state must still place every surviving demand, with zero
    watchdog breaches."""
    r = run_soak(seed=3, profile=TrafficProfile(**CHURNY))
    assert r.ok, r.violations
    assert r.summary["soak_breaches"] == 0
    # the canonical trace carries the verdict record
    lines = r.trace.canonical_lines()
    assert any(l.startswith("verdict ") for l in lines)
    assert any(l.startswith("slo ") for l in lines)


def test_coarse_fingerprint_ignores_placement_details():
    """Two snapshots that differ only in WHICH node hosts a replica
    must fingerprint identically (placement is thread-timing shaped);
    a different live count must not."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore

    def build(node_for_alloc, n_allocs=2):
        s = StateStore()
        nodes = []
        for i in range(2):
            n = mock.node(name=f"fp-n{i}")
            nodes.append(n)
            s.upsert_node(n)
        job = mock.job()
        job.id = "fp-job"
        s.upsert_job(job)
        for k in range(n_allocs):
            a = mock.alloc()
            a.job_id = job.id
            a.namespace = job.namespace
            a.task_group = job.task_groups[0].name
            a.node_id = nodes[node_for_alloc(k)].id
            a.client_status = "running"
            s.upsert_allocs([a])
        return s.snapshot()

    fp_a = coarse_fingerprint(build(lambda k: 0))
    fp_b = coarse_fingerprint(build(lambda k: k % 2))
    fp_c = coarse_fingerprint(build(lambda k: 0, n_allocs=3))
    assert fp_a == fp_b
    assert fp_a != fp_c


@pytest.mark.slow
def test_default_profile_two_virtual_hours():
    """The acceptance run: the full default profile — 2h of virtual
    cluster life, chaos scenarios interleaved — replayed green in
    bounded wall time."""
    r = run_soak(seed=0)
    assert r.ok, r.violations
    assert r.summary["soak_virtual_hours"] >= 2.0
    assert r.summary["soak_breaches"] == 0
    assert r.summary["wall_s"] < 90.0
    assert r.summary["compression_x"] > 50.0
    chaos_lines = [l for l in r.trace.canonical_lines()
                   if l.startswith("chaos_result ")]
    assert len(chaos_lines) == 2
