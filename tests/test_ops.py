"""Kernel parity tests: device ops vs independently-written host oracles
(reference semantics: scheduler/feasible.go, rank.go, spread.go)."""

import re

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.ops import PlacementEngine, PlacementRequest
from nomad_tpu.ops.feasibility import feasible_mask
from nomad_tpu.ops.scoring import binpack_score
from nomad_tpu.pack import ClusterPacker
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    Constraint,
    Resources,
    Spread,
    SpreadTarget,
    score_fit_binpack,
    score_fit_spread,
)

import jax.numpy as jnp


def host_check(props: dict, c: Constraint) -> bool:
    """Independent re-derivation of checkConstraint for single node."""
    key = c.ltarget.strip("${}")
    if not key.startswith(("attr.", "meta.", "node.", "driver.")):
        key = "attr." + key
    val = props.get(key)
    op, rt = c.operand, c.rtarget
    if op in ("=", "==", "is"):
        return val is not None and val == rt
    if op in ("!=", "not"):
        return val != rt
    if op == "is_set":
        return val is not None
    if op == "is_not_set":
        return val is None
    if val is None:
        return False
    if op == "regexp":
        return re.search(rt, val) is not None
    if op == "set_contains":
        return set(x.strip() for x in rt.split(",")) <= set(
            x.strip() for x in val.split(","))
    if op == "set_contains_any":
        return bool(set(x.strip() for x in rt.split(",")) & set(
            x.strip() for x in val.split(",")))
    if op == "version":
        from nomad_tpu.utils.version import check_constraint
        return check_constraint(val, rt)
    if op in ("<", "<=", ">", ">="):
        try:
            l, r = float(val), float(rt)
        except ValueError:
            l, r = val, rt
        return {"<": l < r, "<=": l <= r, ">": l > r, ">=": l >= r}[op]
    raise AssertionError(f"op {op}")


def build_cluster(specs):
    """specs: list of dicts of extra attributes."""
    h = Harness()
    nodes = []
    for extra in specs:
        n = mock.node()
        n.attributes = {**n.attributes, **extra}
        from nomad_tpu.structs import compute_class
        n.computed_class = compute_class(n)
        h.state.upsert_node(n)
        nodes.append(n)
    return h, nodes


CONSTRAINT_CASES = [
    Constraint("${attr.kernel.name}", "=", "linux"),
    Constraint("${attr.kernel.name}", "=", "windows"),
    Constraint("${attr.kernel.name}", "!=", "windows"),
    Constraint("${attr.missing.key}", "!=", "anything"),
    Constraint("${attr.missing.key}", "=", "anything"),
    Constraint("${attr.os.version}", ">", "21"),
    Constraint("${attr.os.version}", "<=", "22.04"),
    Constraint("${attr.os.name}", "regexp", "^ubu"),
    Constraint("${attr.os.name}", "regexp", "centos|rhel"),
    Constraint("${attr.nomad.version}", "version", ">= 1.5"),
    Constraint("${attr.nomad.version}", "version", "< 1.0"),
    Constraint("${attr.tags}", "set_contains", "web,fast"),
    Constraint("${attr.tags}", "set_contains_any", "gpu,fast"),
    Constraint("${attr.rack}", "is_set", ""),
    Constraint("${attr.rack}", "is_not_set", ""),
    Constraint("${node.datacenter}", "=", "dc1"),
]


class TestFeasibilityParity:
    def test_all_operators_match_oracle(self):
        specs = [
            {},
            {"os.version": "20.10", "tags": "web,fast,ssd", "rack": "r1"},
            {"os.name": "centos", "nomad.version": "0.9.1"},
            {"tags": "gpu", "os.version": "23.10"},
        ]
        h, nodes = build_cluster(specs)
        snap = h.snapshot()
        packer = ClusterPacker()
        t = packer.build(snap)

        job = mock.job()
        for c in CONSTRAINT_CASES:
            job.constraints = [c]
            job.task_groups[0].tasks[0].constraints = []
            tgt = packer.lower_task_groups(job, job.task_groups)
            ctx = packer.job_context(job, snap, t)
            mask = np.asarray(feasible_mask(
                jnp.asarray(t.attrs), jnp.asarray(t.elig),
                jnp.asarray(ctx.dc_mask), jnp.asarray(ctx.pool_mask),
                jnp.asarray(tgt.con), jnp.asarray(tgt.luts)))[0]
            from nomad_tpu.pack.packer import node_property_map
            for i, nd in enumerate(nodes):
                props = node_property_map(nd)
                want = (host_check(props, c)
                        and props.get("driver.exec") == "1"
                        and nd.datacenter == "dc1")
                assert mask[t.id_to_row[nd.id]] == want, (
                    f"constraint {c} node {i}: dev={mask[t.id_to_row[nd.id]]} "
                    f"oracle={want}")


class TestBinpackParity:
    def test_matches_struct_oracle(self):
        rng = np.random.default_rng(0)
        cap = rng.integers(100, 10000, size=(64, 3)).astype(np.float32)
        used = (cap * rng.uniform(0, 1.2, size=(64, 3))).astype(np.float32)
        req = np.zeros(3, np.float32)
        dev = np.asarray(binpack_score(jnp.asarray(cap), jnp.asarray(used),
                                       jnp.asarray(req)))
        for i in range(64):
            want = score_fit_binpack(cap[i, 0], cap[i, 1], used[i, 0], used[i, 1])
            assert dev[i] == pytest.approx(want, abs=1e-4)

    def test_spread_algo_matches(self):
        cap = np.array([[4000, 8192, 1000]], np.float32)
        used = np.array([[1000, 2048, 0]], np.float32)
        dev = np.asarray(binpack_score(jnp.asarray(cap), jnp.asarray(used),
                                       jnp.zeros(3), spread_algo=True))
        want = score_fit_spread(4000, 8192, 1000, 2048)
        assert dev[0] == pytest.approx(want, abs=1e-4)


class TestPlacementEngine:
    def test_capacity_consumed_sequentially(self):
        # 2 nodes, each fits exactly 2 allocs of 1000MHz: 4 placements must
        # split 2/2; a 5th must fail.
        h = Harness()
        nodes = []
        for _ in range(2):
            n = mock.node()
            n.resources.cpu = 2100
            n.reserved.cpu = 0
            n.resources.memory_mb = 99999
            n.reserved.memory_mb = 0
            h.state.upsert_node(n)
            nodes.append(n)
        job = mock.batch_job()
        job.task_groups[0].tasks[0].resources = Resources(cpu=1000, memory_mb=10)
        job.task_groups[0].count = 5
        h.state.upsert_job(job)

        eng = PlacementEngine()
        reqs = [PlacementRequest(tg_name="worker") for _ in range(5)]
        snap = h.snapshot()
        decisions = eng.place(snap, job, job.task_groups, reqs)
        placed = [d for d in decisions if d.node_id]
        failed = [d for d in decisions if not d.node_id]
        assert len(placed) == 4 and len(failed) == 1
        from collections import Counter
        counts = Counter(d.node_id for d in placed)
        assert sorted(counts.values()) == [2, 2]
        # exhaustion metric must name the dimension
        assert failed[0].metric.dimension_exhausted.get("cpu", 0) > 0

    def test_anti_affinity_spreads_same_job(self):
        # plenty of capacity on both nodes: anti-affinity should still
        # split a 2-count service group across nodes
        h = Harness()
        for _ in range(2):
            h.state.upsert_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        h.state.upsert_job(job)
        eng = PlacementEngine()
        decisions = eng.place(h.snapshot(), job, job.task_groups,
                              [PlacementRequest(tg_name="web")] * 2)
        assert decisions[0].node_id != decisions[1].node_id

    def test_reschedule_penalty_avoids_prev_node(self):
        h = Harness()
        nodes = [mock.node() for _ in range(2)]
        for n in nodes:
            h.state.upsert_node(n)
        job = mock.job()
        h.state.upsert_job(job)
        eng = PlacementEngine()
        d = eng.place(h.snapshot(), job, job.task_groups,
                      [PlacementRequest(tg_name="web",
                                        prev_node_id=nodes[0].id)])
        assert d[0].node_id == nodes[1].id

    def test_spread_targets_respected(self):
        h = Harness()
        for dc, cnt in (("dc1", 4), ("dc2", 4), ("dc3", 4)):
            for _ in range(cnt):
                h.state.upsert_node(mock.node(datacenter=dc))
        job = mock.job()
        job.datacenters = ["dc1", "dc2", "dc3"]
        job.spreads = [Spread(attribute="${node.datacenter}", weight=100,
                              targets=(SpreadTarget("dc1", 50),
                                       SpreadTarget("dc2", 30),
                                       SpreadTarget("dc3", 20)))]
        job.task_groups[0].count = 10
        h.state.upsert_job(job)
        eng = PlacementEngine()
        decisions = eng.place(h.snapshot(), job, job.task_groups,
                              [PlacementRequest(tg_name="web")] * 10)
        snap = h.snapshot()
        from collections import Counter
        dcs = Counter(snap.node_by_id(d.node_id).datacenter
                      for d in decisions if d.node_id)
        assert dcs["dc1"] == 5 and dcs["dc2"] == 3 and dcs["dc3"] == 2

    def test_distinct_hosts(self):
        h = Harness()
        for _ in range(3):
            h.state.upsert_node(mock.node())
        job = mock.job()
        job.constraints.append(Constraint("", "distinct_hosts", ""))
        job.task_groups[0].count = 4
        h.state.upsert_job(job)
        eng = PlacementEngine()
        decisions = eng.place(h.snapshot(), job, job.task_groups,
                              [PlacementRequest(tg_name="web")] * 4)
        placed = [d.node_id for d in decisions if d.node_id]
        assert len(placed) == 3 and len(set(placed)) == 3
        assert decisions[3].node_id is None

    def test_metrics_shape(self):
        h = Harness()
        h.state.upsert_node(mock.node())
        h.state.upsert_node(mock.node(datacenter="dc2"))
        job = mock.job()
        h.state.upsert_job(job)
        eng = PlacementEngine()
        d = eng.place(h.snapshot(), job, job.task_groups,
                      [PlacementRequest(tg_name="web")])[0]
        m = d.metric
        assert m.nodes_evaluated == 2
        assert m.nodes_filtered == 1          # dc2 node
        assert m.nodes_available == {"dc1": 1, "dc2": 1}
        assert len(m.score_meta_data) >= 1
        assert m.score_meta_data[0].node_id == d.node_id


class TestReviewRegressions:
    """Regression tests for review findings on the pack/ops layer."""

    def test_engine_sees_committed_allocs(self):
        # A reused engine must not serve stale device tensors: after allocs
        # are committed to state, the next place() must see reduced capacity.
        h = Harness()
        n = mock.node()
        n.resources.cpu = 2100
        n.reserved.cpu = 0
        n.resources.memory_mb = 99999
        n.reserved.memory_mb = 0
        h.state.upsert_node(n)
        job = mock.batch_job()
        job.task_groups[0].tasks[0].resources = Resources(cpu=1000, memory_mb=10)
        h.state.upsert_job(job)
        eng = PlacementEngine()

        for _ in range(2):
            d = eng.place(h.snapshot(), job, job.task_groups,
                          [PlacementRequest(tg_name="worker")])[0]
            assert d.node_id == n.id
            a = mock.alloc(job=job, node_id=n.id)
            a.resources = Resources(cpu=1000, memory_mb=10)
            h.state.upsert_allocs([a])

        # third must fail: 2x1000 committed on a 2100 node
        d = eng.place(h.snapshot(), job, job.task_groups,
                      [PlacementRequest(tg_name="worker")])[0]
        assert d.node_id is None
        assert d.metric.dimension_exhausted.get("cpu", 0) > 0

    def test_tiebreak_seed_diversifies_equal_nodes(self):
        # Equal-score nodes must be picked differently by different eval
        # seeds (the reference's shuffled-node-order analog) or concurrent
        # workers collide on identical nodes and refute each other's plans.
        h = Harness()
        for _ in range(32):
            h.state.upsert_node(mock.node())
        job = mock.batch_job()
        h.state.upsert_job(job)
        snap = h.snapshot()
        eng = PlacementEngine()
        tg = job.task_groups[0]
        reqs = [PlacementRequest(tg_name=tg.name)]
        picks = {eng.place(snap, job, [tg], reqs, seed=s)[0].node_id
                 for s in (1, 2, 3, 4, 5, 6)}
        assert len(picks) > 1, "seeds did not diversify tie-break"
        # seed 0 stays deterministic
        a = eng.place(snap, job, [tg], reqs, seed=0)[0].node_id
        b = eng.place(snap, job, [tg], reqs, seed=0)[0].node_id
        assert a == b

    def test_used_delta_replay_concurrent_with_alloc_events(self):
        # Applier-thread alloc events racing a worker's device `used` sync
        # must neither skip nor double-apply deltas: the engine holds the
        # packer lock across read-version -> fetch-deltas -> commit.
        import threading

        h = Harness()
        nodes = [mock.node() for _ in range(16)]
        for n in nodes:
            h.state.upsert_node(n)
        eng = PlacementEngine()
        eng.packer.attach(h.state)
        eng.packer.update(h.snapshot())
        job = mock.job()
        errors = []

        def writer():
            try:
                # > the 256-entry replay window, so the trimmed-window
                # full-re-upload path races too
                for i in range(300):
                    a = mock.alloc(job=job, node_id=nodes[i % 16].id)
                    h.state.upsert_allocs([a])
            except Exception as e:  # pragma: no cover - fail loudly below
                errors.append(e)

        th = threading.Thread(target=writer)
        th.start()
        while th.is_alive():
            eng._used_device(eng.packer._tensors)
        th.join()
        assert not errors
        t = eng.packer._tensors
        dev = np.asarray(eng._used_device(t))
        assert (dev == t.used).all()

    def test_distinct_property_enforced(self):
        # 4 nodes in 2 racks; distinct_property on meta.rack with limit 1
        # must place at most one alloc per rack.
        h = Harness()
        for rack in ("r1", "r1", "r2", "r2"):
            n = mock.node()
            n.meta = {"rack": rack}
            from nomad_tpu.structs import compute_class
            n.computed_class = compute_class(n)
            h.state.upsert_node(n)
        job = mock.job()
        job.constraints.append(
            Constraint("${meta.rack}", "distinct_property", "1"))
        job.task_groups[0].count = 3
        h.state.upsert_job(job)
        eng = PlacementEngine()
        ds = eng.place(h.snapshot(), job, job.task_groups,
                       [PlacementRequest(tg_name="web")] * 3)
        placed = [d.node_id for d in ds if d.node_id]
        assert len(placed) == 2
        snap = h.snapshot()
        racks = {snap.node_by_id(nid).meta["rack"] for nid in placed}
        assert racks == {"r1", "r2"}
        assert ds[2].node_id is None

    def test_distinct_property_counts_existing_allocs(self):
        h = Harness()
        nodes = []
        for rack in ("r1", "r2"):
            n = mock.node()
            n.meta = {"rack": rack}
            from nomad_tpu.structs import compute_class
            n.computed_class = compute_class(n)
            h.state.upsert_node(n)
            nodes.append(n)
        job = mock.job()
        job.constraints.append(
            Constraint("${meta.rack}", "distinct_property", "1"))
        h.state.upsert_job(job)
        # existing alloc in r1
        h.state.upsert_allocs([mock.alloc(job=job, node_id=nodes[0].id)])
        eng = PlacementEngine()
        d = eng.place(h.snapshot(), job, job.task_groups,
                      [PlacementRequest(tg_name="web")])[0]
        assert d.node_id == nodes[1].id

    def test_lut_rows_do_not_grow_per_eval(self):
        packer = ClusterPacker()
        h = Harness()
        h.state.upsert_node(mock.node())
        job = mock.job()
        job.constraints = [Constraint("${attr.os.name}", "regexp", "^ubu")]
        packer.build(h.snapshot())
        packer.lower_task_groups(job, job.task_groups)
        luts_before = len(packer._luts)
        for i in range(5):
            # grow the vocab each round, then re-lower the same predicate
            packer.interner.intern(f"brand-new-value-{i}")
            packer.lower_task_groups(job, job.task_groups)
        assert len(packer._luts) == luts_before
        # extended rows must cover the full vocab
        assert packer.lut_matrix().shape[1] == len(packer.interner)

    def test_bulk_kernel_rejects_over_capacity_unrequested_dim(self):
        # A node over capacity in a dimension the task group does NOT
        # request (e.g. disk after a shrunk re-registration) must be
        # infeasible in the bulk rounds kernel, matching capacity_fit's
        # all-dims check in the exact scan kernel.
        import jax.numpy as jnp
        from nomad_tpu.ops.select import (PlacementInputs, place_bulk_jit,
                                          place_jit)

        n, p = 8, 64
        attrs = np.zeros((n, 4), np.int32)
        cap = np.tile(np.array([[4000, 8192, 1000]], np.int32), (n, 1))
        used = np.zeros((n, 3), np.int32)
        used[0, 2] = 1100            # node 0 over disk capacity
        inp = PlacementInputs(
            attrs=jnp.asarray(attrs), cap=jnp.asarray(cap),
            used0=jnp.asarray(used), elig=jnp.ones(n, bool),
            dc_mask=jnp.ones(n, bool), pool_mask=jnp.ones(n, bool),
            luts=jnp.ones((1, 4), bool),
            con=jnp.zeros((1, 0, 3), jnp.int32),
            aff=jnp.zeros((1, 0, 4), jnp.int32),
            req=jnp.asarray(np.array([[100, 10, 0]], np.int32)),  # no disk ask
            desired=jnp.asarray(np.array([p], np.int32)),
            dh_limit=jnp.zeros(1, jnp.int32),
            sp_nodeval=jnp.full((1, n), -1, jnp.int32),
            sp_weight=jnp.zeros(1, jnp.float32),
            sp_expected=jnp.zeros((1, 1), jnp.float32),
            sp_counts0=jnp.zeros((1, 1), jnp.float32),
            pd_nodeval=jnp.full((1, n), -1, jnp.int32),
            pd_limit=jnp.zeros(1, jnp.int32),
            pd_apply=jnp.zeros((1, 1), bool),
            pd_counts0=jnp.zeros((1, 1), jnp.int32),
            tg_idx=jnp.zeros(p, jnp.int32),
            prev_row=jnp.full(p, -1, jnp.int32),
            active=jnp.ones(p, bool),
            job_count0=jnp.zeros(n, jnp.int32),
            spread_algo=jnp.asarray(False),
        )
        for picks in (np.asarray(place_jit(inp).picks),
                      np.asarray(place_bulk_jit(inp, 32).picks)):
            assert (picks != 0).all(), picks
