"""Batched columnar port assignment (ISSUE 8).

Networked task groups ride the columnar block path: dynamic ports are
carved per node in one batched pass (scheduler/generic._carve_ports_batch)
and commit as port columns on the AllocBlock, with the sequential
per-alloc NetworkIndex loop surviving as the static-port / multi-network
fallback AND the parity oracle.  This suite covers:

  - NetworkIndex free-cursor semantics: bit-for-bit the linear first-fit
    scan it replaced, O(1) amortized, failed assignments never burn pool
    positions
  - the bulk APIs (claim_dynamic_block / assign_ports_batch) equal n
    sequential assign+commit calls exactly
  - batched == sequential end-to-end parity (the bench gate's pytest twin)
  - edge cases: dynamic-pool exhaustion -> blocked eval naming the
    exhaustion dimension, static-port conflict vs an in-flight batch
    mate, preemption-victim ports counted free, port reuse after
    terminal-alloc GC
  - churn soak: place -> kill -> replace across >= 3 waves with zero
    (node, port) collisions among live allocs and no leaked reservations
"""

import pathlib
import sys

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import (
    MAX_DYNAMIC_PORT,
    MIN_DYNAMIC_PORT,
    NetworkIndex,
    NetworkResource,
    Port,
)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

NOW = 1.7e9


def _linear_pick(used, newly):
    """The pre-cursor reference implementation: O(pool) first-fit."""
    for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
        if port not in used and port not in newly:
            return port
    return None


class TestNetworkIndexCursor:
    def test_cursor_matches_linear_scan(self):
        import random
        rnd = random.Random(7)
        ni = NetworkIndex()
        ni.used_ports.update(rnd.sample(
            range(MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT + 200), 120))
        for _ in range(150):
            want = _linear_pick(ni.used_ports, set())
            got, err = ni.assign_ports(
                [NetworkResource(dynamic_ports=[Port(label="p")])])
            assert err == "" and got == {"p": want}
            ni.commit(got)

    def test_failed_assign_does_not_burn_pool_positions(self):
        ni = NetworkIndex()
        # first pick succeeds transiently, then the reserved ask collides
        # -> whole assignment fails, nothing committed
        got, err = ni.assign_ports([NetworkResource(
            dynamic_ports=[Port(label="p")],
            reserved_ports=[Port(label="r", value=MIN_DYNAMIC_PORT)]),
            NetworkResource(
                reserved_ports=[Port(label="r2",
                                     value=MIN_DYNAMIC_PORT)])])
        assert got is None and "collision" in err
        # the next assignment still gets the linear scan's answer:
        # NOTHING from the failed call was committed, so first-fit
        # starts from the bottom of the pool again
        assert _linear_pick(ni.used_ports, set()) == MIN_DYNAMIC_PORT
        got, err = ni.assign_ports(
            [NetworkResource(dynamic_ports=[Port(label="p")])])
        assert got == {"p": MIN_DYNAMIC_PORT}, got

    def test_pick_dynamic_exhaustion(self):
        ni = NetworkIndex()
        ni.used_ports.update(range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1))
        got, err = ni.assign_ports(
            [NetworkResource(dynamic_ports=[Port(label="p")])])
        assert got is None
        assert err == "network: dynamic port exhaustion"

    def test_claim_dynamic_block(self):
        ni = NetworkIndex()
        ni.used_ports.update({MIN_DYNAMIC_PORT + 1, MIN_DYNAMIC_PORT + 3})
        got = ni.claim_dynamic_block(3)
        assert got == [MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT + 2,
                       MIN_DYNAMIC_PORT + 4]
        assert set(got) <= ni.used_ports          # committed
        # all-or-nothing on shortfall: nothing claimed
        free_before = ni.dyn_free_count()
        assert ni.claim_dynamic_block(free_before + 1) is None
        assert ni.dyn_free_count() == free_before

    def test_assign_ports_batch_matches_sequential(self):
        import copy
        ask = [NetworkResource(dynamic_ports=[Port(label="http"),
                                              Port(label="admin")])]
        a = NetworkIndex()
        a.used_ports.update({MIN_DYNAMIC_PORT + 2, MIN_DYNAMIC_PORT + 5})
        b = copy.deepcopy(a)
        batch, err = a.assign_ports_batch(ask, 5)
        assert err == "" and len(batch) == 5
        seq = []
        for _ in range(5):
            got, err = b.assign_ports(ask)
            assert err == ""
            b.commit(got)
            seq.append(got)
        assert batch == seq
        assert a.used_ports == b.used_ports

    def test_assign_ports_batch_static_falls_back(self):
        ni = NetworkIndex()
        got, err = ni.assign_ports_batch(
            [NetworkResource(reserved_ports=[Port(label="r", value=80)])],
            2)
        assert got is None and "sequential" in err

    def test_dyn_free_count(self):
        ni = NetworkIndex()
        pool = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
        assert ni.dyn_free_count() == pool
        ni.used_ports.add(MIN_DYNAMIC_PORT)
        ni.used_ports.add(80)                      # outside the pool
        assert ni.dyn_free_count() == pool - 1
        ni.used_ports.update(range(MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT + 10))
        assert ni.dyn_free_count() == pool - 10


class TestBatchedSequentialParity:
    def test_port_parity_gate(self):
        """The bench gate's pytest twin: the same seeded networked
        workload through the batched carve and the sequential oracle
        commits bit-for-bit identical (job, name) -> (node, ports)."""
        import bench
        assert bench._port_parity_gate(seed=31) > 0


def _networked_server(n_nodes=4, eval_batch=0, node_cpu=100000):
    s = Server(dev_mode=True, eval_batch=eval_batch)
    s.establish_leadership()
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = node_cpu
        n.resources.memory_mb = 100000
        s.register_node(n, now=NOW)
        nodes.append(n)
    return s, nodes


def _networked_job(count, labels=("http",), cpu=10, mem=10, static=None):
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    net = NetworkResource(
        dynamic_ports=[Port(label=lb) for lb in labels])
    if static is not None:
        net.reserved_ports.append(Port(label="static", value=static))
    tg.tasks[0].resources.networks = [net]
    return job


def _live_ports(state, jobs):
    """{(node, port), ...} over live allocs; asserts uniqueness."""
    seen = set()
    live = 0
    snap = state.snapshot()
    for job in jobs:
        for a in snap.allocs_by_job(job.namespace, job.id):
            if a.terminal_status():
                continue
            live += 1
            for port in a.allocated_ports.values():
                key = (a.node_id, port)
                assert key not in seen, f"(node, port) collision {key}"
                seen.add(key)
    return seen, live


class TestColumnarNetworkedPath:
    def test_block_path_carries_ports(self):
        """A block-sized networked eval commits COLUMNAR — a live
        AllocBlock with port columns, no per-alloc table rows — and
        every materialized row carries a unique (node, port)."""
        s, _ = _networked_server()
        job = _networked_job(96, labels=("http", "admin"))
        s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        assert s.state._alloc_blocks, "networked placements should block"
        blk = next(iter(s.state._alloc_blocks.values()))
        assert blk.port_labels == ["http", "admin"]
        assert blk.ports is not None and blk.ports.shape == (96, 2)
        assert not s.state._allocs_by_job.get((job.namespace, job.id))
        seen, live = _live_ports(s.state, [job])
        assert live == 96 and len(seen) == 192
        s.shutdown()

    def test_exhaustion_blocks_eval_with_dimension(self):
        """Dynamic-pool exhaustion: the carve bails to the sequential
        oracle, which places what fits and parks the rest in a blocked
        eval whose metric names the exhaustion dimension (the `eval
        explain` surface)."""
        from nomad_tpu.core.explain import blocked_cause

        s = Server(dev_mode=True)
        s.establish_leadership()
        n = mock.node()
        n.resources.cpu = 100000
        n.resources.memory_mb = 100000
        # all but 10 dynamic ports pre-reserved on the node
        n.reserved.reserved_ports = list(
            range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT - 9))
        s.register_node(n, now=NOW)
        job = _networked_job(66, labels=("http", "admin"))  # wants 132
        ev = s.register_job(job, now=NOW)
        s.process_all(now=NOW)
        seen, live = _live_ports(s.state, [job])
        assert live == 5                      # 10 free ports / 2 per alloc
        assert len(seen) == 10
        done = s.state.eval_by_id(ev.id)
        assert done.status == "complete"
        metric = done.failed_tg_allocs[job.task_groups[0].name]
        assert metric.dimension_exhausted.get(
            "network: dynamic port exhaustion"), metric.dimension_exhausted
        cause = blocked_cause(done.failed_tg_allocs)
        assert "dynamic port exhaustion" in cause, cause
        # a blocked eval carries the unplaced remainder
        assert done.blocked_eval, "expected a blocked eval"
        s.shutdown()

    def test_static_port_conflict_vs_in_flight_batch_mate(self):
        """Two batch-mates asking the same static port on a one-node
        cluster: the shared per-batch NetworkIndex hands the port to the
        first mate and refuses the second — one winner, no double
        commit, loser blocked on the collision dimension."""
        s = Server(dev_mode=True, eval_batch=8)
        s.establish_leadership()
        n = mock.node()
        n.resources.cpu = 100000
        n.resources.memory_mb = 100000
        s.register_node(n, now=NOW)
        jobs = [_networked_job(1, labels=("http",), static=8080)
                for _ in range(2)]
        evs = [s.register_job(j, now=NOW) for j in jobs]
        s.process_all(now=NOW)
        snap = s.state.snapshot()
        holders = [a for j in jobs
                   for a in snap.allocs_by_job(j.namespace, j.id)
                   if not a.terminal_status()]
        assert len(holders) == 1, [h.allocated_ports for h in holders]
        assert holders[0].allocated_ports["static"] == 8080
        loser = next(e for e, j in zip(evs, jobs)
                     if j.id != holders[0].job_id)
        done = s.state.eval_by_id(loser.id)
        exhausted = done.failed_tg_allocs[
            jobs[0].task_groups[0].name].dimension_exhausted
        assert any("reserved port collision" in d for d in exhausted), \
            exhausted
        s.shutdown()

    def test_preemption_victim_ports_counted_free(self):
        """_net_index victim exclusion: a preemption victim's ports do
        not block the preemptor's assignment on the same node."""
        from nomad_tpu.scheduler import Harness
        from nomad_tpu.scheduler.generic import GenericScheduler

        h = Harness()
        n = mock.node()
        h.state.upsert_node(n)
        job = mock.job()
        h.state.upsert_job(job)
        victim = mock.alloc(job=job, node_id=n.id)
        victim.allocated_ports = {"http": MIN_DYNAMIC_PORT}
        h.state.upsert_allocs([victim])
        sched = GenericScheduler(h.state.snapshot(), h, now=NOW)
        cache = {}
        with_victim = sched._net_index(n.id, cache, {victim.id})
        assert MIN_DYNAMIC_PORT not in with_victim.used_ports
        without = sched._net_index(n.id, {}, set())
        assert MIN_DYNAMIC_PORT in without.used_ports

    def test_port_reuse_after_terminal_gc(self):
        """Ports freed by terminal allocs are reclaimed by the next
        wave: first-fit restarts from the bottom of the pool, so the
        replacement allocs reuse the exact freed values — reservations
        do not leak across alloc lifecycles.  ONE node, so the
        wave-to-wave pick distribution cannot shift the per-node port
        sequences (eval ids seed the kernel's tie-break noise)."""
        s, _ = _networked_server(n_nodes=1)
        job1 = _networked_job(70)
        s.register_job(job1, now=NOW)
        s.process_all(now=NOW)
        first_ports, live = _live_ports(s.state, [job1])
        assert live == 70
        # kill wave 1 (client reports every alloc complete)
        for a in list(s.state.allocs_by_job(job1.namespace, job1.id)):
            upd = a.copy_skip_job()
            upd.client_status = "complete"
            s.state.update_allocs_from_client([upd])
        job2 = _networked_job(70)
        s.register_job(job2, now=NOW)
        s.process_all(now=NOW)
        second_ports, live2 = _live_ports(s.state, [job2])
        assert live2 == 70
        # freed (node, port) pairs are reused, not leaked: the second
        # wave's claims sit in the same bottom-of-pool range
        assert second_ports == first_ports


class TestApplierColumnarPortAudit:
    """The commit-time safety net (plan_apply._eval_blocks): port-
    carrying blocks stay COLUMNAR through the full re-check, with a
    per-node used-port set built on the same alloc walk as the capacity
    sums — colliding nodes refute by masking rows out of the block."""

    @staticmethod
    def _applier():
        from nomad_tpu.core import PlanApplier, PlanQueue
        from nomad_tpu.state import StateStore
        state = StateStore()
        q = PlanQueue()
        q.set_enabled(True)
        return state, q, PlanApplier(state, q)

    @staticmethod
    def _port_block(job, nodes, ports):
        import numpy as np
        from nomad_tpu.structs import AllocBlock, Allocation, new_ids
        tmpl = Allocation(
            namespace=job.namespace, job_id=job.id, job=job,
            task_group=job.task_groups[0].name, desired_status="run",
            client_status="pending",
            resources=job.task_groups[0].combined_resources())
        uniq = sorted(set(nodes))
        row = {nid: i for i, nid in enumerate(uniq)}
        n = len(nodes)
        return AllocBlock(
            id="blk-test", template=tmpl, ids=new_ids(n),
            name_prefix=f"{job.id}.{job.task_groups[0].name}[",
            indexes=list(range(n)),
            picks=np.array([row[nid] for nid in nodes], np.int32),
            node_table=uniq, metrics=[], round_size=max(n, 1),
            port_labels=["http"],
            ports=np.array([[p] for p in ports], np.int32))

    def test_collision_with_existing_alloc_refutes_columnar(self):
        from nomad_tpu.structs import Plan
        state, q, applier = self._applier()
        n1, n2 = mock.node(), mock.node()
        for n in (n1, n2):
            n.resources.cpu = 100000
            n.resources.memory_mb = 100000
            state.upsert_node(n)
        job = _networked_job(2)
        state.upsert_job(job)
        holder = mock.alloc(job=job, node_id=n1.id)
        holder.allocated_ports = {"http": MIN_DYNAMIC_PORT}
        state.upsert_allocs([holder])
        # a stale scheduler assigned n1's already-held port
        plan = Plan(eval_id="e1", job=job)
        plan.alloc_blocks.append(self._port_block(
            job, [n1.id, n2.id], [MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT]))
        pending = q.enqueue(plan)
        applier.apply_one(pending)
        result, err = pending.wait(1)
        assert err is None
        assert result.refuted_nodes == [n1.id]
        # the surviving row committed COLUMNAR on n2 with its port
        assert result.alloc_blocks and len(result.alloc_blocks[0].ids) == 1
        live = [a for a in state.snapshot().allocs_by_node(n2.id)
                if not a.terminal_status()]
        assert len(live) == 1
        assert live[0].allocated_ports == {"http": MIN_DYNAMIC_PORT}

    def test_within_plan_duplicate_refutes_node(self):
        from nomad_tpu.structs import Plan
        state, q, applier = self._applier()
        n1 = mock.node()
        n1.resources.cpu = 100000
        n1.resources.memory_mb = 100000
        state.upsert_node(n1)
        job = _networked_job(2)
        state.upsert_job(job)
        plan = Plan(eval_id="e1", job=job)
        plan.alloc_blocks.append(self._port_block(
            job, [n1.id, n1.id], [MIN_DYNAMIC_PORT, MIN_DYNAMIC_PORT]))
        pending = q.enqueue(plan)
        applier.apply_one(pending)
        result, err = pending.wait(1)
        assert err is None
        assert result.refuted_nodes == [n1.id]
        assert not result.alloc_blocks
        assert not [a for a in state.snapshot().allocs_by_node(n1.id)
                    if not a.terminal_status()]


class TestPortChurnSoak:
    def test_churn_three_waves_no_collisions_no_leaks(self):
        """place -> kill -> replace across >= 3 waves on a small cluster
        (mates pile onto the same nodes): after every wave, zero
        (node, port) collisions among LIVE allocs; after the churn, the
        per-node live port count exactly matches the live asks (no
        leaked reservations holding pool positions)."""
        s, nodes = _networked_server(n_nodes=3, eval_batch=16)
        all_jobs = []
        prev_jobs = []
        for wave in range(4):
            jobs = [_networked_job(66, labels=("http", "admin"))
                    for _ in range(2)]
            for j in jobs:
                s.register_job(j, now=NOW + wave)
            s.process_all(now=NOW + wave)
            all_jobs.extend(jobs)
            # live-set audit over EVERY job ever placed
            seen, live = _live_ports(s.state, all_jobs)
            want_live = 132 * (1 + bool(prev_jobs))
            assert live == want_live, (wave, live)
            assert len(seen) == 2 * live
            # kill the previous wave (replace pattern: the wave before
            # stays live so two waves' ports always coexist)
            for j in prev_jobs:
                for a in list(s.state.allocs_by_job(j.namespace, j.id)):
                    if a.terminal_status():
                        continue
                    upd = a.copy_skip_job()
                    upd.client_status = "complete"
                    s.state.update_allocs_from_client([upd])
            prev_jobs = jobs
        # no leaked reservations: a fresh NetworkIndex built per node
        # from live state claims exactly the live allocs' ports
        snap = s.state.snapshot()
        seen, live = _live_ports(s.state, all_jobs)
        assert live == 132                    # only the last wave lives
        for node in nodes:
            ni = NetworkIndex()
            ni.set_node(node)
            ni.add_allocs(snap.allocs_by_node(node.id))
            node_live = {p for (nid, p) in seen if nid == node.id}
            assert ni.used_ports == node_live, node.id
        s.shutdown()
