"""State store tests (reference semantics: nomad/state/state_store.go)."""

import threading

from nomad_tpu import mock
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Plan,
    PlanResult,
)


def test_upsert_node_and_snapshot_isolation():
    s = StateStore()
    n = mock.node()
    idx = s.upsert_node(n)
    snap = s.snapshot()
    assert snap.index == idx
    assert snap.node_by_id(n.id).id == n.id
    # later writes must not show in existing snapshot
    n2 = mock.node()
    s.upsert_node(n2)
    assert snap.node_by_id(n2.id) is None
    assert s.snapshot().node_by_id(n2.id) is not None


def test_index_monotonic_and_modify_index():
    s = StateStore()
    n = mock.node()
    i1 = s.upsert_node(n)
    j = mock.job()
    i2 = s.upsert_job(j)
    assert i2 == i1 + 1
    stored = s.snapshot().job_by_id(j.namespace, j.id)
    assert stored.modify_index == i2 and stored.create_index == i2
    i3 = s.upsert_job(j.copy())
    stored2 = s.snapshot().job_by_id(j.namespace, j.id)
    assert stored2.create_index == i2 and stored2.modify_index == i3
    assert stored2.version == stored.version + 1


def test_snapshot_immune_to_caller_mutation():
    # The store copies on insert: mutating the caller's object after upsert
    # must not alter what snapshots see.
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    snap = s.snapshot()
    n.status = "down"
    assert snap.node_by_id(n.id).status == "ready"


def test_computed_class_recomputed_on_upsert():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    before = s.snapshot().node_by_id(n.id).computed_class
    n2 = s.snapshot().node_by_id(n.id).copy()
    n2.attributes = {**n2.attributes, "os.name": "debian"}
    s.upsert_node(n2)
    after = s.snapshot().node_by_id(n.id).computed_class
    assert before != after


def test_unknown_node_update_is_noop():
    s = StateStore()
    idx = s.latest_index()
    assert s.update_node_status("nope", "down") == idx


def test_listener_sees_committed_state_and_cannot_abort():
    s = StateStore()
    seen = []

    def listener(topic, index, payload):
        if topic == "Evaluation":
            # re-entrant read must see the committed eval
            seen.append(s.eval_by_id(payload.id) is not None)
        raise RuntimeError("listener bug must not abort the commit")

    s.subscribe(listener)
    e = mock.eval()
    s.upsert_evals([e])
    assert seen == [True]
    assert s.eval_by_id(e.id) is not None


def test_allocs_by_node_and_job_indexes():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    j = mock.job()
    s.upsert_job(j)
    a = mock.alloc(job=j, node_id=n.id)
    s.upsert_allocs([a])
    snap = s.snapshot()
    assert [x.id for x in snap.allocs_by_node(n.id)] == [a.id]
    assert [x.id for x in snap.allocs_by_job(j.namespace, j.id)] == [a.id]
    assert snap.allocs_by_node_terminal(n.id, terminal=False)[0].id == a.id
    assert snap.allocs_by_node_terminal(n.id, terminal=True) == []


def test_ready_nodes_filters():
    s = StateStore()
    ready = mock.node()
    down = mock.node(status="down")
    inel = mock.node(scheduling_eligibility="ineligible")
    other_dc = mock.node(datacenter="dc9")
    for n in (ready, down, inel, other_dc):
        s.upsert_node(n)
    snap = s.snapshot()
    got = {n.id for n in snap.ready_nodes_in_pool(["dc1"])}
    assert got == {ready.id}


def test_upsert_plan_results_applies_stops_and_places():
    s = StateStore()
    n = mock.node()
    s.upsert_node(n)
    j = mock.job()
    s.upsert_job(j)
    old = mock.alloc(job=j, node_id=n.id)
    s.upsert_allocs([old])

    stopped = old.copy_skip_job()
    stopped.desired_status = "stop"
    new = mock.alloc(job=j, node_id=n.id)
    plan = Plan(eval_id="e1", job=j)
    result = PlanResult(node_update={n.id: [stopped]},
                        node_allocation={n.id: [new]})
    s.upsert_plan_results(plan, result)
    snap = s.snapshot()
    assert snap.alloc_by_id(old.id).desired_status == "stop"
    assert snap.alloc_by_id(new.id) is not None
    live = snap.allocs_by_node_terminal(n.id, terminal=False)
    assert {a.id for a in live} == {new.id}


def test_client_status_merge():
    s = StateStore()
    j = mock.job()
    s.upsert_job(j)
    a = mock.alloc(job=j, node_id="n1")
    s.upsert_allocs([a])
    upd = a.copy_skip_job()
    upd.client_status = "running"
    s.update_allocs_from_client([upd])
    assert s.snapshot().alloc_by_id(a.id).client_status == "running"


def test_wait_for_index():
    s = StateStore()
    target = s.latest_index() + 1

    def later():
        s.upsert_node(mock.node())

    t = threading.Timer(0.05, later)
    t.start()
    assert s.wait_for_index(target, timeout=2.0)
    t.join()


def test_job_versions():
    s = StateStore()
    j = mock.job()
    s.upsert_job(j)
    j2 = j.copy()
    j2.priority = 70
    s.upsert_job(j2)
    snap = s.snapshot()
    cur = snap.job_by_id(j.namespace, j.id)
    assert cur.version == 1 and cur.priority == 70
    # version history must be immutable: v0 keeps the old priority
    assert snap.job_by_id_and_version(j.namespace, j.id, 0).priority == 50
    assert snap.job_by_id_and_version(j.namespace, j.id, 1).priority == 70
