"""Continuous-profiling plane (core/profiling.py): stack
classification, role mapping, the GIL-wait split, folded-stack export,
the compile ledger, on-demand capture bundles, and — the satellite-3
contract — sampler NEUTRALITY: a seeded virtual-time soak must replay
byte-identical with the sampler on or off, because the sampler reads
the real clock and writes to none of the deterministic surfaces."""

import sys
import threading
import time

from nomad_tpu.core import profiling
from nomad_tpu.core.profiling import (
    BUCKETS, SCHEMA, CompileLedger, SamplingProfiler, activity,
    classify_stack, current_activity, role_of, role_window,
)

# ------------------------------------------------------- classification


def test_role_of_prefix_table():
    assert role_of("worker-3") == "worker"
    assert role_of("plan-applier") == "applier"
    assert role_of("raft-follower-2") == "raft"
    assert role_of("heartbeat-watcher") == "raft"
    assert role_of("server-tick") == "broker"
    assert role_of("http-api-9") == "http"
    assert role_of("client-node-1") == "client"
    assert role_of("chaos-partition") == "chaos"
    assert role_of("MainThread") == "other"


def _frame_named(name):
    # a real frame whose innermost co_name is `name` — classify_stack
    # only looks at code objects, so a renamed local works
    src = f"def {name}():\n    import sys\n    return sys._getframe()\n"
    ns = {}
    exec(compile(src, __file__, "exec"), ns)
    return ns[name]()


def test_classify_device_wait_by_func_name():
    assert classify_stack(_frame_named("block_until_ready")) \
        == "device-wait"
    assert classify_stack(_frame_named("fetch")) == "device-wait"


def test_classify_wire_and_idle_by_filename():
    ns = {}
    exec(compile("import sys\nf = sys._getframe()",
                 "/x/core/wire.py", "exec"), ns)
    assert classify_stack(ns["f"]) == "wire"
    ns = {}
    exec(compile("import sys\nf = sys._getframe()",
                 "/x/chaos/clock.py", "exec"), ns)
    assert classify_stack(ns["f"]) == "idle"


def test_classify_host_residual():
    assert classify_stack(sys._getframe()) == "host"


def test_classify_parked_event_wait_is_idle():
    """A thread parked in Event.wait shows threading.py:wait innermost;
    that is idle (no work queued), not lock contention."""
    ev = threading.Event()
    ready = threading.Event()

    def park():
        try:
            ready.set()
            ev.wait(5.0)
        except Exception:
            pass

    t = threading.Thread(target=park, name="park-test", daemon=True)
    t.start()
    ready.wait(2.0)
    time.sleep(0.02)
    frame = sys._current_frames().get(t.ident)
    try:
        assert frame is not None
        assert classify_stack(frame) == "idle"
    finally:
        ev.set()
        t.join(2.0)


def test_classify_semaphore_acquire_is_lock_wait():
    """Semaphore.acquire is a Python frame in threading.py named
    `acquire` — the lock-wait signature."""
    sem = threading.Semaphore(0)
    ready = threading.Event()

    def contend():
        try:
            ready.set()
            sem.acquire(timeout=5.0)
        except Exception:
            pass

    t = threading.Thread(target=contend, name="sem-test", daemon=True)
    t.start()
    ready.wait(2.0)
    time.sleep(0.02)
    frame = sys._current_frames().get(t.ident)
    try:
        assert frame is not None
        assert classify_stack(frame) == "lock-wait"
    finally:
        sem.release()
        t.join(2.0)


# ------------------------------------------------------ activity markers


def test_activity_marker_nesting_and_cross_thread_publish():
    ident = threading.get_ident()
    assert current_activity() is None
    assert ident not in profiling._MARKS
    with activity("device-wait"):
        assert current_activity() == "device-wait"
        assert profiling._MARKS[ident] == "device-wait"
        with activity("wire"):
            assert current_activity() == "wire"
            assert profiling._MARKS[ident] == "wire"
        assert current_activity() == "device-wait"
        assert profiling._MARKS[ident] == "device-wait"
    assert current_activity() is None
    assert ident not in profiling._MARKS


# -------------------------------------------------------------- sampler


def _burn(stop):
    # pure-Python spin: classified `host`, keeps the GIL busy
    x = 0
    while not stop.is_set():
        x += 1
    return x


def test_sampler_buckets_roles_and_gil_split():
    """Two runnable worker threads spinning Python: with one GIL, each
    runnable sample splits 1/N own-bucket + (N-1)/N gil-wait — the
    measurement ROADMAP item 5 is scoped from."""
    p = SamplingProfiler(hz=97.0)
    stop = threading.Event()
    threads = [threading.Thread(target=_burn, args=(stop,),
                                name=f"worker-{i}", daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        assert p.start()
        assert p.running
        time.sleep(0.6)
    finally:
        stop.set()
        p.stop()
        for t in threads:
            t.join(2.0)
    snap = p.snapshot()
    assert snap["samples"] > 10
    assert snap["thread_samples"] >= snap["samples"]
    assert not snap["running"]
    assert set(snap["buckets"]) == set(BUCKETS)
    # every sample lands in a named bucket by construction
    assert snap["attributed_fraction"] >= 0.90
    worker = snap["roles"]["worker"]
    assert worker.get("gil-wait", 0.0) > 0.0
    assert snap["gil_wait_fraction"] > 0.0
    assert snap["gil_wait_fraction_by_role"]["worker"] == \
        snap["gil_wait_fraction"]
    # two always-runnable spinners: each carries ~1/2 gil-wait
    assert 0.2 <= snap["gil_wait_fraction"] <= 0.8
    folded = p.folded()
    assert folded
    assert any(line.startswith("worker;") and line.rsplit(" ", 1)[1]
               .isdigit() for line in folded.splitlines())
    assert p.folded(role="worker")
    assert "worker;" not in p.folded(role="broker")


def test_sampler_marker_beats_stack_heuristics():
    """A `with activity("device-wait")` around a pure-Python spin must
    classify as device-wait even though the frames say host."""
    p = SamplingProfiler(hz=97.0)
    stop = threading.Event()

    def marked():
        try:
            with activity("device-wait"):
                _burn(stop)
        except Exception:
            pass

    t = threading.Thread(target=marked, name="worker-marked",
                         daemon=True)
    t.start()
    try:
        p.start()
        time.sleep(0.4)
    finally:
        stop.set()
        p.stop()
        t.join(2.0)
    snap = p.snapshot()
    assert snap["roles"]["worker"].get("device-wait", 0.0) > 0.0


def test_sampler_idle_thread_classified_idle():
    p = SamplingProfiler(hz=97.0)
    ev = threading.Event()
    t = threading.Thread(target=lambda: ev.wait(10.0) and None,
                         name="worker-parked", daemon=True)
    t.start()
    try:
        p.start()
        time.sleep(0.4)
    finally:
        p.stop()
        ev.set()
        t.join(2.0)
    snap = p.snapshot()
    assert snap["roles"]["worker"].get("idle", 0.0) > 0.0


def test_sampler_reset_and_hz_retune():
    p = SamplingProfiler(hz=97.0)
    p.start()
    time.sleep(0.15)
    assert p.start(hz=53.0)   # re-tune while running: idempotent
    assert p.hz == 53.0
    p.stop()
    assert p.snapshot()["samples"] > 0
    p.reset()
    snap = p.snapshot()
    assert snap["samples"] == 0
    assert snap["buckets"] == {b: 0.0 for b in BUCKETS}
    assert p.folded() == ""
    assert not p.start(hz=0)  # hz<=0 is the off switch
    assert not p.running


# ------------------------------------------------------- compile ledger


def test_compile_ledger_accounting_and_hit_rate():
    led = CompileLedger()
    led.note_miss("engine.multi/8x4", compile_s=0.5)
    led.note_hit("engine.multi/8x4")
    led.note_hit("engine.multi/8x4")
    led.note_steady("engine.multi/8x4", 0.01)
    snap = led.snapshot()
    assert snap["hits"] == 2 and snap["misses"] == 1
    assert abs(snap["hit_rate"] - 2 / 3) < 1e-9
    assert snap["first_launch_s"] == 0.5
    site = snap["sites"]["engine.multi/8x4"]
    assert site["steady_calls"] == 1 and site["steady_s"] == 0.01
    led.reset()
    assert led.snapshot()["sites"] == {}
    assert led.snapshot()["hit_rate"] == 0.0


def test_compile_ledger_wrap_times_first_call_only():
    led = CompileLedger()
    calls = []
    wrapped = led.wrap("site/a", lambda x: calls.append(x) or x * 2)
    assert wrapped(3) == 6 and wrapped(4) == 8 and wrapped(5) == 10
    assert calls == [3, 4, 5]
    snap = led.snapshot()
    # only the FIRST call is a miss (jit compiles at first invocation)
    assert snap["sites"]["site/a"]["misses"] == 1
    assert snap["sites"]["site/a"]["first_launch_s"] >= 0.0


# -------------------------------------------------------------- capture


def test_capture_bundle_schema_providers_and_ring():
    p = SamplingProfiler(hz=97.0)
    p.device_ledger_provider = lambda: {"backend": "test",
                                        "hbm_resident_bytes": 7}
    p.flight_provider = lambda: {"rings": []}
    b = p.capture(duration_s=0.05)
    assert b["schema"] == SCHEMA
    assert b["id"] == "prof-0001"
    assert b["duration_s"] == 0.05
    assert not b["sampler_was_running"]   # one-shot start/stop
    assert not p.running                  # restored after capture
    assert set(b["buckets"]) == set(BUCKETS)
    assert 0.0 <= b["attributed_fraction"] <= 1.0
    assert b["device_ledger"] == {"backend": "test",
                                  "hbm_resident_bytes": 7}
    assert b["flight_recorder"] == {"rings": []}
    assert "hits" in b["compile_ledger"]
    assert b["jax_trace"] is None
    assert isinstance(b["folded"], list)
    assert p.get_capture("prof-0001") is b
    assert p.get_capture("prof-9999") is None
    for _ in range(9):
        p.capture(duration_s=0.05)
    caps = p.captures()
    assert len(caps) == profiling._CAPTURE_CAP
    assert caps[-1]["id"] == "prof-0010"   # seq keeps counting
    assert p.get_capture("prof-0001") is None  # evicted from the ring


def test_capture_provider_failure_is_contained():
    def boom():
        raise RuntimeError("server closing")

    p = SamplingProfiler(hz=97.0)
    p.device_ledger_provider = boom
    b = p.capture(duration_s=0.05)
    assert b["device_ledger"] == {"error": "server closing"}


def test_capture_clamps_duration():
    p = SamplingProfiler(hz=97.0)
    assert p.capture(duration_s=-5)["duration_s"] == 0.05


# ---------------------------------------------------------- role_window


def test_role_window_deltas_drop_zero_and_new_roles_appear():
    base = {"roles": {"worker": {"host": 4.0, "idle": 2.0}}}
    cur = {"roles": {"worker": {"host": 7.0, "idle": 2.0,
                                "gil-wait": 1.5},
                     "http": {"wire": 3.0}}}
    w = role_window(base, cur)
    assert w == {"worker": {"host": 3.0, "gil-wait": 1.5},
                 "http": {"wire": 3.0}}
    assert SamplingProfiler._gil_fraction(w, "worker") == 1.5 / 4.5
    assert SamplingProfiler._gil_fraction(w, "absent") == 0.0
    assert role_window(cur, cur) == {}


# ----------------------------------------------------- brief + configure


def test_brief_points_at_capture_surface():
    p = SamplingProfiler(hz=97.0)
    doc = p.brief()
    assert doc["capture_endpoint"] == "/v1/operator/profile"
    assert doc["captures"] == []
    assert set(doc["buckets"]) == set(BUCKETS)


def test_configure_global_start_stop_round_trip():
    was_hz = profiling.PROFILER.hz
    was_running = profiling.PROFILER.running
    try:
        prof = profiling.configure(hz=61.0)
        assert prof is profiling.PROFILER
        assert prof.running and prof.hz == 61.0
        profiling.configure(enabled=False)
        assert not prof.running
        profiling.configure(hz=0)
        assert not prof.running and prof.hz == 0
    finally:
        profiling.PROFILER.hz = was_hz
        if was_running:
            profiling.PROFILER.start()
        else:
            profiling.PROFILER.stop()


# -------------------------------------------- satellite 3: neutrality


def test_soak_replay_identical_with_sampler_on_and_off():
    """The neutrality contract: the always-on sampler observes a
    virtual-time soak but must never participate in its timeline — the
    canonical trace and converged fingerprint stay byte-identical
    whether it runs (at an aggressive hz) or not."""
    from nomad_tpu.chaos.soak import run_soak
    from nomad_tpu.chaos.traffic import TrafficProfile

    profile = TrafficProfile(
        hours=0.05, n_nodes=4, n_zones=2, service_per_hour=40,
        batch_per_hour=40, drains_per_hour=10, flap_storms_per_hour=0,
        preempt_storms_per_hour=0, chaos_scenarios=())
    was_hz = profiling.PROFILER.hz
    was_running = profiling.PROFILER.running
    try:
        profiling.configure(enabled=False)
        off = run_soak(seed=11, profile=profile)
        profiling.configure(hz=211.0)   # aggressive: ~5ms period
        assert profiling.PROFILER.running
        on = run_soak(seed=11, profile=profile)
    finally:
        profiling.PROFILER.stop()
        profiling.PROFILER.hz = was_hz
        if was_running and was_hz > 0:
            profiling.PROFILER.start()
    assert off.ok and on.ok, (off.violations, on.violations)
    assert on.digest == off.digest
    assert on.fingerprint == off.fingerprint
    assert on.trace.canonical_bytes() == off.trace.canonical_bytes()
