"""Device scheduling tests (reference scenarios: scheduler/device_test.go,
scheduler/feasible_test.go TestDeviceChecker, plan_apply device re-check)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.device import (
    InUseIndex,
    assign_devices,
    group_affinity_score,
    id_matches,
    node_feasible,
)
from nomad_tpu.structs import (
    AllocatedDeviceResource,
    Affinity,
    Allocation,
    Constraint,
    NodeDeviceResource,
    RequestedDevice,
    Resources,
    allocs_fit,
)

NOW = 1_700_000_000.0


def gpu_group(vendor="nvidia", typ="gpu", name="1080ti", count=2, **attrs):
    return NodeDeviceResource(
        vendor=vendor, type=typ, name=name,
        instance_ids=[f"{name}-{i}" for i in range(count)],
        attributes={k: str(v) for k, v in attrs.items()})


def gpu_node(groups=None, **overrides):
    n = mock.node(**overrides)
    n.resources.devices = groups if groups is not None \
        else [gpu_group()]
    return n


def gpu_job(name="gpu", count=1, dev_count=1, constraints=(),
            affinities=(), **overrides):
    j = mock.job(**overrides)
    j.task_groups[0].count = count
    j.task_groups[0].tasks[0].resources.devices = [RequestedDevice(
        name=name, count=dev_count,
        constraints=list(constraints), affinities=list(affinities))]
    return j


class TestMatching:
    def test_id_matches_hierarchy(self):
        d = gpu_group()
        assert id_matches("gpu", d)
        assert id_matches("nvidia/gpu", d)
        assert id_matches("nvidia/gpu/1080ti", d)
        assert not id_matches("fpga", d)
        assert not id_matches("amd/gpu", d)
        assert not id_matches("nvidia/gpu/2080", d)

    def test_node_feasible_counts(self):
        n = gpu_node([gpu_group(count=2)])
        tg = gpu_job(dev_count=2).task_groups[0]
        assert node_feasible(n, tg, InUseIndex())
        idx = InUseIndex()
        idx.add(n.id, "nvidia/gpu/1080ti", ["1080ti-0"])
        assert not node_feasible(n, tg, idx)

    def test_constraint_on_device_attr(self):
        small = gpu_group(name="k80", memory="8192")
        big = gpu_group(name="a100", memory="40960")
        tg = gpu_job(constraints=[
            Constraint("${device.attr.memory}", ">=", "16000")]
        ).task_groups[0]
        assert not node_feasible(gpu_node([small]), tg, InUseIndex())
        assert node_feasible(gpu_node([big]), tg, InUseIndex())

    def test_affinity_prefers_group(self):
        req = RequestedDevice(name="gpu", count=1, affinities=[
            Affinity("${device.model}", "=", "a100", weight=50)])
        assert group_affinity_score(gpu_group(name="a100"), req) == 1.0
        assert group_affinity_score(gpu_group(name="k80"), req) == 0.0

    def test_assign_picks_best_group(self):
        n = gpu_node([gpu_group(name="k80"), gpu_group(name="a100")])
        j = gpu_job(affinities=[
            Affinity("${device.model}", "=", "a100", weight=50)])
        assigned, why = assign_devices(n, j.task_groups[0], InUseIndex())
        assert why == ""
        assert assigned[0].name == "a100"
        assert len(assigned[0].device_ids) == 1

    def test_assign_consumes_instances(self):
        n = gpu_node([gpu_group(count=2)])
        tg = gpu_job().task_groups[0]
        idx = InUseIndex()
        a1, _ = assign_devices(n, tg, idx)
        a2, _ = assign_devices(n, tg, idx)
        assert a1[0].device_ids != a2[0].device_ids
        a3, why = assign_devices(n, tg, idx)
        assert a3 is None and "devices" in why


class TestSchedulerIntegration:
    def _harness(self, nodes):
        h = Harness()
        for n in nodes:
            h.state.upsert_node(n)
        return h

    def _placed(self, h):
        return [a for allocs in h.plans[-1].node_allocation.values()
                for a in allocs]

    def test_filters_deviceless_nodes(self):
        plain = [mock.node() for _ in range(4)]
        gn = gpu_node()
        h = self._harness(plain + [gn])
        job = gpu_job(count=2)
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id)
        assert h.process("service", e, now=NOW) is None
        placed = self._placed(h)
        assert len(placed) == 2
        assert all(a.node_id == gn.id for a in placed)
        ids = [tuple(a.allocated_devices[0].device_ids) for a in placed]
        assert len(set(ids)) == 2      # distinct instances
        assert all(a.allocated_devices[0].vendor == "nvidia" for a in placed)

    def test_spills_to_second_node_when_exhausted(self):
        g1, g2 = gpu_node(), gpu_node()
        h = self._harness([g1, g2] + [mock.node() for _ in range(3)])
        job = gpu_job(count=4)       # 4 allocs x 1 instance, 2 per node max
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id)
        assert h.process("service", e, now=NOW) is None
        placed = self._placed(h)
        assert len(placed) == 4
        by_node = {}
        seen = set()
        for a in placed:
            by_node[a.node_id] = by_node.get(a.node_id, 0) + 1
            key = (a.node_id, tuple(a.allocated_devices[0].device_ids))
            assert key not in seen
            seen.add(key)
        assert by_node == {g1.id: 2, g2.id: 2}

    def test_exhaustion_reports_devices_dimension(self):
        gn = gpu_node([gpu_group(count=1)])
        h = self._harness([gn, mock.node()])
        job = gpu_job(count=2)
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id)
        assert h.process("service", e, now=NOW) is None
        placed = self._placed(h)
        assert len(placed) == 1
        # second placement failed on devices; blocked eval created
        ev = h.evals[-1]
        assert ev.failed_tg_allocs
        m = ev.failed_tg_allocs["web"]
        assert m.nodes_exhausted >= 1

    def test_existing_allocs_block_instances(self):
        gn = gpu_node([gpu_group(count=2)])
        h = self._harness([gn])
        prior = mock.alloc(node_id=gn.id)
        prior.allocated_devices = [AllocatedDeviceResource(
            task="web", vendor="nvidia", type="gpu", name="1080ti",
            device_ids=["1080ti-0"])]
        h.state.upsert_allocs([prior])
        job = gpu_job(count=1)
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id)
        assert h.process("service", e, now=NOW) is None
        placed = self._placed(h)
        assert len(placed) == 1
        assert placed[0].allocated_devices[0].device_ids == ["1080ti-1"]

    def test_system_scheduler_assigns_devices(self):
        gn = gpu_node()
        plain = mock.node()
        h = self._harness([gn, plain])
        job = mock.system_job()
        job.task_groups[0].tasks[0].resources.devices = [
            RequestedDevice(name="gpu", count=1)]
        h.state.upsert_job(job)
        e = mock.eval(job_id=job.id, type="system")
        assert h.process("system", e, now=NOW) is None
        placed = self._placed(h)
        assert [a.node_id for a in placed] == [gn.id]
        assert placed[0].allocated_devices[0].device_ids


class TestAllocsFitDevices:
    def test_double_booking_refused(self):
        n = gpu_node([gpu_group(count=2)])
        mk = lambda iid: Allocation(
            resources=Resources(cpu=100, memory_mb=64),
            allocated_devices=[AllocatedDeviceResource(
                vendor="nvidia", type="gpu", name="1080ti",
                device_ids=[iid])])
        ok, why, _ = allocs_fit(
            n, [mk("1080ti-0"), mk("1080ti-0")], check_devices=True)
        assert not ok and "oversubscribed" in why
        ok, _, _ = allocs_fit(
            n, [mk("1080ti-0"), mk("1080ti-1")], check_devices=True)
        assert ok

    def test_unknown_instance_refused(self):
        n = gpu_node([gpu_group(count=1)])
        a = Allocation(
            resources=Resources(cpu=100, memory_mb=64),
            allocated_devices=[AllocatedDeviceResource(
                vendor="nvidia", type="gpu", name="1080ti",
                device_ids=["bogus"])])
        ok, why, _ = allocs_fit(n, [a], check_devices=True)
        assert not ok and "unknown instance" in why


class TestTaskEnv:
    def test_device_env_exposed(self):
        from nomad_tpu.client.taskenv import build_task_env
        job = gpu_job()
        alloc = mock.alloc(job=job, task_group="web")
        alloc.allocated_devices = [AllocatedDeviceResource(
            task="web", vendor="nvidia", type="gpu", name="1080ti",
            device_ids=["1080ti-1"])]
        env = build_task_env(alloc, job.task_groups[0].tasks[0],
                             mock.node())
        assert env["NOMAD_DEVICE_NVIDIA_GPU_1080TI"] == "1080ti-1"
