"""Multi-wave soak: the whole pipeline under sustained churn.

Two tests: mixed-churn global invariants (I1-I5 below), and a
wave-scale TIMING guard — per-wave materialize/commit host time must
stay flat as committed state accumulates (a COW/snapshot-isolation
regression that re-copies ever-growing tables per write shows up here
as superlinear growth long before it shows up as a wrong answer).

The per-feature suites pin individual behaviors; this drives the REAL
server loop (broker → batched workers → plan queue → serialized
applier) through several waves of mixed work — zoned CSI jobs riding
the compact laned kernel, networked jobs riding the shared-port batch
path, drains forcing migrations, job stops releasing claims — and
re-checks GLOBAL invariants after every wave:

  I1  no node oversubscribed (sum of live alloc asks ≤ usable capacity)
  I2  no (node, port) pair claimed twice
  I3  every CSI claim belongs to a live alloc (no leaked claims)
  I4  every eval reached a terminal status (nothing wedged)
  I5  drained nodes hold no live allocs

The reference's equivalent confidence comes from its e2e cluster suite
(e2e/, environment-impossible here — SURVEY §5) plus soak clusters;
this is the in-process analog at a size CI can afford.
"""

import random

from nomad_tpu import mock
from nomad_tpu.core.server import Server
from nomad_tpu.structs import (
    CSIVolume,
    DrainStrategy,
    NetworkResource,
    Port,
    VolumeRequest,
)

NOW = 1.7e9


def _usable(node):
    r = node.reserved
    return (node.resources.cpu - r.cpu,
            node.resources.memory_mb - r.memory_mb,
            node.resources.disk_mb - r.disk_mb)


def check_invariants(s, drained_ids):
    snap = s.state.snapshot()
    nodes = {n.id: n for n in snap.nodes()}
    live_by_node = {}
    live_ids = set()
    for n_id in nodes:
        for a in snap.allocs_by_node(n_id):
            if a.terminal_status():
                continue
            live_by_node.setdefault(n_id, []).append(a)
            live_ids.add(a.id)
    # I1: capacity
    for n_id, allocs in live_by_node.items():
        cpu = sum(a.resources.cpu for a in allocs)
        mem = sum(a.resources.memory_mb for a in allocs)
        u_cpu, u_mem, _ = _usable(nodes[n_id])
        assert cpu <= u_cpu, (n_id, cpu, u_cpu)
        assert mem <= u_mem, (n_id, mem, u_mem)
    # I2: port uniqueness
    for n_id, allocs in live_by_node.items():
        seen = set()
        for a in allocs:
            for port in (a.allocated_ports or {}).values():
                assert (n_id, port) not in seen, (n_id, port)
                seen.add((n_id, port))
    # I3: claims ⊆ live allocs (block claims expand to their member ids)
    for vol in snap.csi_volumes():
        claim_ids = (list(vol.read_allocs) + list(vol.write_allocs)
                     + [aid for b in vol.read_blocks.values()
                        for aid in b.ids])
        for aid in claim_ids:
            assert aid in live_ids, (vol.id, aid)
        # block claims must reference live blocks
        for bid in vol.read_blocks:
            assert bid in snap._alloc_blocks, (vol.id, bid)
    # I4: evals terminal
    for ev in snap.evals():
        assert ev.status in ("complete", "failed", "canceled",
                             "blocked"), (ev.id, ev.status)
    # I5: drained nodes empty
    for n_id in drained_ids:
        assert not live_by_node.get(n_id), n_id


def test_soak_mixed_churn():
    rng = random.Random(7)
    s = Server(dev_mode=True, eval_batch=64, heartbeat_ttl=1e9)
    s.establish_leadership()
    nodes = []
    zone_nodes = {z: [] for z in range(3)}
    for i in range(90):
        n = mock.node()
        n.datacenter = f"dc{1 + i % 3}"
        n.attributes["storage.topology"] = f"zone{i % 3}"
        n.csi_node_plugins["ebs0"] = True
        n.resources.cpu = rng.choice([4000, 8000])
        n.resources.memory_mb = 8192
        s.register_node(n, now=NOW)
        nodes.append(n)
        zone_nodes[i % 3].append(n.id)
    for z in range(3):
        s.state.upsert_csi_volume(CSIVolume(
            id=f"vol-z{z}", plugin_id="ebs0",
            access_mode="multi-node-multi-writer",
            topology_node_ids=tuple(zone_nodes[z])))

    drained: set = set()
    jobs = []
    now = NOW
    for cycle in range(4):
        now += 10
        # a wave of zoned CSI jobs (compact laned path)
        for i in range(4):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = 12
            tg.tasks[0].resources.cpu = 50
            tg.tasks[0].resources.memory_mb = 64
            tg.volumes = {"data": VolumeRequest(
                name="data", type="csi", source=f"vol-z{i % 3}",
                read_only=(i % 2 == 0))}
            s.register_job(job, now=now)
            jobs.append(job)
        # a networked job (shared-port batch path)
        net = mock.batch_job()
        net.task_groups[0].count = 8
        net.task_groups[0].tasks[0].resources.cpu = 20
        net.task_groups[0].tasks[0].resources.memory_mb = 32
        net.task_groups[0].tasks[0].resources.networks = [
            NetworkResource(dynamic_ports=[Port(label="http")])]
        s.register_job(net, now=now)
        jobs.append(net)
        s.process_all(now=now)
        check_invariants(s, drained)

        # churn: drain one node (migrations), stop one early job
        # (claim + port release)
        now += 10
        candidates = [n for n in nodes if n.id not in drained]
        victim = candidates[cycle * 7 % len(candidates)]
        drained.add(victim.id)
        s.drain_node(victim.id, DrainStrategy(deadline_s=5), now=now)
        if cycle and jobs:
            dead = jobs.pop(0)
            s.deregister_job(dead.namespace, dead.id, now=now)
        # settle: tick the drainer past its deadline until the drained
        # nodes are empty (bounded — migration completion is a
        # multi-step dance of drainer evals + placements)
        for step in range(8):
            now += 10
            s.drainer.tick(now=now)
            s.process_all(now=now)
            snap = s.state.snapshot()
            if all(all(a.terminal_status()
                       for a in snap.allocs_by_node(nid))
                   for nid in drained):
                break
        check_invariants(s, drained)

    # final: everything still consistent, and the store agrees with the
    # packer's incremental view (rebuild == incremental)
    t = s.engine.packer.update(s.state.snapshot())
    from nomad_tpu.pack.packer import ClusterPacker
    fresh = ClusterPacker()
    t2 = fresh.update(s.state.snapshot())
    import numpy as np
    by_id = {nid: i for i, nid in enumerate(t2.node_ids)}
    order = [by_id[nid] for nid in t.node_ids]
    assert np.array_equal(t.used, t2.used[order])
    s.shutdown()


def test_soak_wave_timing_stays_flat():
    """N identical waves through the real batched pipeline; the host
    materialize+commit time of the LAST waves must stay within 2x of
    the FIRST waves (VERDICT next-round #8: per-wave cost must not grow
    with accumulated cluster state).  Medians over 3-wave windows so a
    single scheduler hiccup on a shared host cannot flip the verdict;
    a small absolute floor keeps sub-millisecond noise out of the
    ratio."""
    import statistics
    import time

    rng = random.Random(11)
    s = Server(dev_mode=True, eval_batch=64, heartbeat_ttl=1e9)
    s.establish_leadership()
    for i in range(80):
        n = mock.node()
        n.datacenter = f"dc{1 + i % 3}"
        n.resources.cpu = rng.choice([8000, 16000])
        n.resources.memory_mb = 32768
        s.register_node(n, now=NOW)

    def wave(now, cpu):
        # several jobs at once so the broker batches them and the
        # pipeline's materialize stage (not the single-eval path) runs
        for _ in range(6):
            job = mock.batch_job()
            job.datacenters = ["dc1", "dc2", "dc3"]
            tg = job.task_groups[0]
            tg.count = 40
            tg.tasks[0].resources.cpu = cpu
            tg.tasks[0].resources.memory_mb = 4
            s.register_job(job, now=now)
        s.stage_timers.reset()
        t0 = time.perf_counter()
        s.process_all(now=now)
        wall = time.perf_counter() - t0
        totals = s.stage_timers.totals()
        host = totals.get("materialize", 0.0) + totals.get("commit", 0.0)
        assert totals.get("materialize", 0.0) > 0.0, totals
        assert totals.get("commit", 0.0) > 0.0, totals
        return host, wall

    n_waves = 9
    now = NOW
    wave(now, cpu=1)                       # warmup: compiles excluded
    host_times = []
    for w in range(n_waves):
        now += 10
        host_times.append(wave(now, cpu=1)[0])
    first = statistics.median(host_times[:3])
    last = statistics.median(host_times[-3:])
    # flat within 2x, with a 10ms absolute floor for timer noise
    assert last <= max(2.0 * first, first + 0.010), (
        f"per-wave materialize/commit grew {first:.4f}s -> {last:.4f}s "
        f"over {n_waves} waves: {[round(t, 4) for t in host_times]}")
    s.shutdown()
