"""External task-driver plugins
(reference: plugins/drivers/ DriverPlugin gRPC service + client shim).

Two halves:

  * `serve_driver(driver)` — plugin-process side: wraps any object
    implementing the `client.drivers.base.Driver` contract and serves it
    over the plugin protocol (the analog of drivers.Serve).
  * `ExternalDriver` — host side: implements the same `Driver` contract
    backed by a PluginClient, so the client's task runners use external
    plugin drivers exactly like built-ins (the analog of the
    drivers.driverPluginClient shim).

Wire mapping: Task objects cross the boundary as their API-JSON wire form
(structs.codec), TaskHandle/TaskResult as flat dicts.
"""

from __future__ import annotations

from typing import Dict, Optional

from nomad_tpu.client.drivers.base import (
    Driver,
    DriverCapabilities,
    DriverError,
    TaskHandle,
    TaskResult,
)
from nomad_tpu.structs import Task, codec

from .base import PluginClient, serve


def _handle_to_wire(h: TaskHandle) -> Dict:
    return {"task_id": h.task_id, "driver": h.driver, "pid": h.pid,
            "started_at": h.started_at, "driver_state": h.driver_state}


def _handle_from_wire(d: Dict) -> TaskHandle:
    return TaskHandle(task_id=d["task_id"], driver=d["driver"],
                      pid=d.get("pid", 0),
                      started_at=d.get("started_at", 0.0),
                      driver_state=d.get("driver_state") or {})


def _result_to_wire(r: Optional[TaskResult]) -> Optional[Dict]:
    if r is None:
        return None
    return {"exit_code": r.exit_code, "signal": r.signal,
            "oom_killed": r.oom_killed, "err": r.err}


def _result_from_wire(d: Optional[Dict]) -> Optional[TaskResult]:
    if d is None:
        return None
    return TaskResult(exit_code=d.get("exit_code", 0),
                      signal=d.get("signal", 0),
                      oom_killed=d.get("oom_killed", False),
                      err=d.get("err"))


def serve_driver(driver: Driver) -> None:
    """Plugin-process entry point: serve `driver` over the protocol."""

    def start_task(task_id: str, task: Dict, env: Dict, task_dir: str):
        t = codec.decode(Task, task)
        return _handle_to_wire(driver.start_task(task_id, t, env, task_dir))

    def wait_task(handle: Dict, timeout_s: Optional[float] = None):
        return _result_to_wire(
            driver.wait_task(_handle_from_wire(handle), timeout_s))

    handlers = {
        "fingerprint": lambda: driver.fingerprint(),
        "capabilities": lambda: {
            "send_signals": driver.capabilities().send_signals,
            "exec": driver.capabilities().exec_,
            "fs_isolation": driver.capabilities().fs_isolation,
        },
        "start_task": start_task,
        "wait_task": wait_task,
        "stop_task": lambda handle, kill_timeout=5.0: driver.stop_task(
            _handle_from_wire(handle), kill_timeout),
        "destroy_task": lambda handle: driver.destroy_task(
            _handle_from_wire(handle)),
        "inspect_task": lambda handle: driver.inspect_task(
            _handle_from_wire(handle)),
        "signal_task": lambda handle, signal_num: driver.signal_task(
            _handle_from_wire(handle), signal_num),
        "recover_task": lambda handle: driver.recover_task(
            _handle_from_wire(handle)),
    }
    serve(handlers, {"type": "driver", "name": driver.name, "version": "1"})


class ExternalDriver(Driver):
    """Host-side Driver backed by a plugin process."""

    def __init__(self, client: PluginClient) -> None:
        self.client = client
        self.name = client.info.get("name", "external")

    def _call(self, method: str, timeout="__default__", **params):
        """timeout omitted -> the client's 60s default; timeout=None ->
        block until the plugin answers (wait_task only)."""
        try:
            return self.client.call(method, timeout=timeout, **params)
        except Exception as e:  # noqa: BLE001 - uniform driver errors
            raise DriverError(f"plugin driver {self.name}: {e}") from e

    def fingerprint(self) -> Dict[str, str]:
        if not self.client.alive():
            return {}
        try:
            fp = self._call("fingerprint", timeout=5.0)
        except DriverError:
            return {}
        return {str(k): str(v) for k, v in (fp or {}).items()}

    def capabilities(self) -> DriverCapabilities:
        c = self._call("capabilities", timeout=5.0) or {}
        return DriverCapabilities(
            send_signals=c.get("send_signals", False),
            exec_=c.get("exec", False),
            fs_isolation=c.get("fs_isolation", "none"))

    def start_task(self, task_id: str, task, env: Dict[str, str],
                   task_dir: str) -> TaskHandle:
        wire = codec.encode(task)
        return _handle_from_wire(self._call(
            "start_task", task_id=task_id, task=wire, env=env,
            task_dir=task_dir))

    def wait_task(self, handle: TaskHandle,
                  timeout: Optional[float] = None) -> Optional[TaskResult]:
        budget = None if timeout is None else timeout + 5.0
        return _result_from_wire(self._call(
            "wait_task", timeout=budget,
            handle=_handle_to_wire(handle), **(
                {"timeout_s": timeout} if timeout is not None else {})))

    def stop_task(self, handle: TaskHandle,
                  kill_timeout: float = 5.0) -> None:
        self._call("stop_task", timeout=kill_timeout + 5.0,
                   handle=_handle_to_wire(handle),
                   kill_timeout=kill_timeout)

    def destroy_task(self, handle: TaskHandle) -> None:
        self._call("destroy_task", handle=_handle_to_wire(handle))

    def inspect_task(self, handle: TaskHandle) -> Dict:
        return self._call("inspect_task", handle=_handle_to_wire(handle))

    def signal_task(self, handle: TaskHandle, signal_num: int) -> None:
        self._call("signal_task", handle=_handle_to_wire(handle),
                   signal_num=signal_num)

    def recover_task(self, handle: TaskHandle) -> bool:
        return bool(self._call("recover_task",
                               handle=_handle_to_wire(handle)))
