"""External plugin protocol — the go-plugin analog
(reference: plugins/base/, hashicorp/go-plugin handshake + gRPC broker).

The reference launches plugin binaries as subprocesses, performs a magic-
cookie handshake, and talks gRPC over a unix socket.  This module is the
same shape with Python-native parts: the host launches the plugin
executable with the cookie in the environment, the plugin binds a unix
socket and announces it on stdout with a go-plugin-style handshake line

    CORE-PROTOCOL|APP-PROTOCOL|unix|<socket path>|json

and both sides then speak length-prefixed JSON messages with request-id
multiplexing (so a blocked `wait_task` does not stall `stats` polls —
the same reason the reference multiplexes gRPC streams).

A plugin author writes:

    from nomad_tpu.plugins import serve_driver
    class MyDriver(Driver): ...
    if __name__ == "__main__":
        serve_driver(MyDriver())

and ships the file; the client's PluginManager discovers it in
`plugin_dir`, launches it, and dispenses it like a built-in.
"""

from __future__ import annotations

import json
from collections import deque
import itertools
import os
import socket
import struct
import subprocess
import sys
import threading
from typing import Any, Callable, Dict, Optional

MAGIC_COOKIE_KEY = "NOMAD_TPU_PLUGIN_MAGIC_COOKIE"
MAGIC_COOKIE_VALUE = "nomad-tpu-plugin-f1a9"
SOCKET_ENV = "NOMAD_TPU_PLUGIN_SOCKET"
CORE_PROTOCOL = 1
APP_PROTOCOL = 1


class PluginError(Exception):
    pass


def _send(sock: socket.socket, obj: Dict) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv(sock: socket.socket) -> Optional[Dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return json.loads(body)


class PluginClient:
    """Host-side connection to one plugin process: request-id multiplexed
    JSON-RPC over the handshaken unix socket."""

    def __init__(self, proc: subprocess.Popen, sock: socket.socket,
                 info: Dict) -> None:
        self.proc = proc
        self.sock = sock
        self.info = info                      # {type, name, version}
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, list] = {}   # id -> [event, result, error]
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"plugin-{info.get('name')}")
        self._reader.start()

    _DEFAULT_TIMEOUT = 60.0

    def call(self, method: str, timeout: Any = "__default__",
             **params) -> Any:
        """`timeout=None` blocks until the plugin answers (wait_task on a
        long-running task); omitted -> 60s."""
        if timeout == "__default__":
            timeout = self._DEFAULT_TIMEOUT
        with self._lock:
            if self._closed:
                raise PluginError("plugin connection closed")
            self._next_id += 1
            rid = self._next_id
            waiter = [threading.Event(), None, None]
            self._pending[rid] = waiter
        # send OUTSIDE the registration lock: _read_loop needs it to
        # deliver responses, and a full socket buffer would otherwise
        # deadlock both directions.  The send lock alone keeps frames
        # from interleaving.
        try:
            with self._send_lock:
                # the send lock serializes exactly this (blocking)
                # socket write; nothing else is guarded by it
                _send(self.sock, {"id": rid,  # analyze: ok lockorder
                                  "method": method, "params": params})
        except OSError as e:
            with self._lock:
                self._pending.pop(rid, None)
            raise PluginError(f"plugin send failed: {e}") from e
        if not waiter[0].wait(timeout):
            with self._lock:
                self._pending.pop(rid, None)
            raise PluginError(f"plugin call {method} timed out")
        if waiter[2] is not None:
            raise PluginError(waiter[2])
        return waiter[1]

    def _read_loop(self) -> None:
        while True:
            try:
                msg = _recv(self.sock)
            except OSError:
                msg = None
            if msg is None:
                break
            with self._lock:
                waiter = self._pending.pop(msg.get("id"), None)
            if waiter is not None:
                waiter[1] = msg.get("result")
                waiter[2] = msg.get("error")
                waiter[0].set()
        # EOF: plugin died — fail everything in flight
        with self._lock:
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        for waiter in pending:
            waiter[2] = "plugin process exited"
            waiter[0].set()

    def alive(self) -> bool:
        return not self._closed and self.proc.poll() is None

    def close(self) -> None:
        with self._lock:
            self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()


_LAUNCH_SEQ = itertools.count()


def launch_plugin(cmd, socket_dir: str, timeout: float = 60.0,
                  ) -> PluginClient:
    """Launch a plugin executable and perform the handshake
    (reference: go-plugin Client.Start)."""
    os.makedirs(socket_dir, exist_ok=True)
    env = dict(os.environ)
    env[MAGIC_COOKIE_KEY] = MAGIC_COOKIE_VALUE
    # plugins written against this SDK import nomad_tpu; make sure the
    # child can resolve it regardless of its own cwd
    sdk_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    prev = env.get("PYTHONPATH", "")
    if sdk_root not in prev.split(os.pathsep):
        env["PYTHONPATH"] = (sdk_root + (os.pathsep + prev if prev else ""))
    sock_path = os.path.join(
        socket_dir, f"plugin-{os.getpid()}-{threading.get_ident()}-"
        f"{next(_LAUNCH_SEQ)}.sock")
    env[SOCKET_ENV] = sock_path
    # stderr is drained by a daemon thread into the bounded log ring —
    # NOT DEVNULL (a crashing child's traceback is the only diagnosis
    # there is), NOT an undrained pipe (blocks a chatty child at 64KB),
    # NOT a temp file (a long-lived chatty plugin would grow unlinked
    # disk invisibly).  The tail deque feeds launch-failure messages;
    # later stderr stays observable via `monitor`.
    from nomad_tpu.core.logging import log as _log
    err_tail: deque = deque(maxlen=30)
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, env=env)
    except OSError as e:
        raise PluginError(f"plugin launch failed: {e}") from e

    def _drain(stream, label):
        # drain daemon thread: the pipe closing mid-read at plugin
        # shutdown is normal, not a reason to die with a traceback
        try:
            for raw in stream:
                line = raw.decode(errors="replace").rstrip()
                if line:
                    if label == "stderr":
                        err_tail.append(line)
                    _log("plugins", "debug", f"plugin {label}",
                         cmd=cmd[-1], line=line)
        except (OSError, ValueError):
            pass

    drain_t = threading.Thread(target=_drain,
                               args=(proc.stderr, "stderr"),
                               daemon=True, name="plugin-stderr")
    drain_t.start()
    tmp: Optional[PluginClient] = None
    try:
        line = _read_handshake_line(proc, timeout)
        # stdout drains from here on — BEFORE the plugin_info RPC: a
        # plugin print()ing >64KB between its handshake line and that
        # reply would wedge on the full pipe and time the launch out
        threading.Thread(target=_drain, args=(proc.stdout, "stdout"),
                         daemon=True, name="plugin-stdout").start()
        parts = line.strip().split("|")
        if len(parts) < 5 or parts[2] != "unix" or parts[4] != "json":
            raise PluginError(f"bad plugin handshake line: {line!r}")
        if not parts[0].isdigit() or int(parts[0]) != CORE_PROTOCOL:
            raise PluginError(
                f"plugin core protocol {parts[0]!r} unsupported")
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(parts[3])
        except OSError as e:
            raise PluginError(f"plugin socket connect failed: {e}") from e
        sock.settimeout(None)
        # identify (reference: base plugin PluginInfo RPC)
        tmp = PluginClient(proc, sock, {})
        info = tmp.call("plugin_info", timeout=timeout)
        tmp.info = info
        return tmp
    except Exception as e:
        # never leak the subprocess, and surface everything as PluginError
        # (WITH the child's stderr tail — the only diagnosis a startup
        # crash leaves) so callers have ONE failure type to supervise on
        if tmp is not None:
            tmp.close()
        elif proc.poll() is None:
            proc.kill()
        try:
            proc.wait(timeout=3)
            drain_t.join(timeout=1)   # let the tail settle before reading
        except Exception:  # noqa: BLE001 - diagnosis is best-effort
            pass
        msg = f"{e}" if isinstance(e, PluginError) else \
            f"plugin launch failed: {e}"
        try:
            tail = "\n".join(list(err_tail)[-8:])
        except RuntimeError:          # drain still appending: one retry
            tail = "\n".join(list(err_tail)[-8:])
        if tail:
            msg += f"; child stderr: {tail[-500:]}"
        raise PluginError(msg) from e


def _read_handshake_line(proc: subprocess.Popen, timeout: float) -> str:
    """Read the announcement line without blocking forever on a bad
    plugin (a plugin that prints nothing, or exits immediately)."""
    result: list = []

    def read():
        try:
            result.append(proc.stdout.readline().decode())
        except Exception as e:  # noqa: BLE001
            result.append(e)

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    if not result or isinstance(result[0], Exception) or not result[0]:
        proc.kill()
        raise PluginError("plugin did not announce its socket "
                          "(missing handshake line on stdout)")
    return result[0]


# --------------------------------------------------------------------------
# Plugin-side serve harness (reference: go-plugin plugin.Serve)
# --------------------------------------------------------------------------


def serve(handlers: Dict[str, Callable[..., Any]], info: Dict) -> None:
    """Run a plugin process: bind the socket from the environment,
    announce it, and serve JSON-RPC until the host disconnects.  Each
    request runs in its own thread so blocking calls (wait_task) don't
    stall the connection."""
    if os.environ.get(MAGIC_COOKIE_KEY) != MAGIC_COOKIE_VALUE:
        # lint: allow-print (pre-handshake: stderr is the only channel)
        print("this binary is a nomad-tpu plugin and must be launched "  # lint: allow-print
              "by the agent's plugin manager, not run directly",
              file=sys.stderr)
        sys.exit(1)
    sock_path = os.environ[SOCKET_ENV]
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(1)
    print(f"{CORE_PROTOCOL}|{APP_PROTOCOL}|unix|{sock_path}|json",  # lint: allow-print
          flush=True)
    conn, _ = srv.accept()
    send_lock = threading.Lock()

    def handle(msg: Dict) -> None:
        rid = msg.get("id")
        method = msg.get("method", "")
        out: Dict[str, Any] = {"id": rid}
        try:
            if method == "plugin_info":
                out["result"] = info
            else:
                fn = handlers.get(method)
                if fn is None:
                    raise PluginError(f"unknown method {method!r}")
                out["result"] = fn(**(msg.get("params") or {}))
        except Exception as e:  # noqa: BLE001 - surface to the host
            out["error"] = str(e)
        with send_lock:
            try:
                _send(conn, out)
            except OSError:
                pass

    while True:
        try:
            msg = _recv(conn)
        except OSError:
            break
        if msg is None:
            break
        threading.Thread(target=handle, args=(msg,), daemon=True).start()
    sys.exit(0)
