"""External device plugins
(reference: plugins/device/ DevicePlugin — Fingerprint/Reserve/Stats).

A device plugin advertises device groups (vendor/type/name + instance
IDs + attributes) that the client merges into its node's
`NodeResources.devices`, and maps reserved instance IDs onto container/
process specs (env vars, mounts) at task start.
"""

from __future__ import annotations

from typing import Dict, List

from nomad_tpu.structs import NodeDeviceResource

from .base import PluginClient, serve


class DevicePlugin:
    """Contract for plugin authors (reference: device.DevicePlugin)."""

    name = "device"

    def fingerprint(self) -> List[NodeDeviceResource]:
        raise NotImplementedError

    def reserve(self, device_ids: List[str]) -> Dict:
        """-> {"envs": {...}, "mounts": [...], "devices": [...]}
        (reference: device.ContainerReservation)."""
        return {"envs": {}, "mounts": [], "devices": []}

    def stats(self) -> Dict:
        return {}


def _group_to_wire(g: NodeDeviceResource) -> Dict:
    return {"vendor": g.vendor, "type": g.type, "name": g.name,
            "instance_ids": list(g.instance_ids),
            "attributes": dict(g.attributes)}


def group_from_wire(d: Dict) -> NodeDeviceResource:
    return NodeDeviceResource(
        vendor=d.get("vendor", ""), type=d.get("type", ""),
        name=d.get("name", ""),
        instance_ids=list(d.get("instance_ids") or []),
        attributes=dict(d.get("attributes") or {}))


def serve_device(plugin: DevicePlugin) -> None:
    """Plugin-process entry point."""
    handlers = {
        "fingerprint": lambda: [
            _group_to_wire(g) for g in plugin.fingerprint()],
        "reserve": lambda device_ids: plugin.reserve(list(device_ids)),
        "stats": lambda: plugin.stats(),
    }
    serve(handlers, {"type": "device", "name": plugin.name, "version": "1"})


class ExternalDevicePlugin:
    """Host-side shim."""

    def __init__(self, client: PluginClient) -> None:
        self.client = client
        self.name = client.info.get("name", "device")

    def fingerprint(self) -> List[NodeDeviceResource]:
        if not self.client.alive():
            return []
        return [group_from_wire(d)
                for d in (self.client.call("fingerprint", timeout=10.0)
                          or [])]

    def reserve(self, device_ids: List[str]) -> Dict:
        return self.client.call("reserve", device_ids=list(device_ids),
                                timeout=10.0) or {}

    def stats(self) -> Dict:
        return self.client.call("stats", timeout=5.0) or {}
