"""External plugin framework (reference: plugins/ + go-plugin)."""

from .base import (  # noqa: F401
    MAGIC_COOKIE_KEY,
    MAGIC_COOKIE_VALUE,
    PluginClient,
    PluginError,
    launch_plugin,
    serve,
)
from .device import (  # noqa: F401
    DevicePlugin,
    ExternalDevicePlugin,
    serve_device,
)
from .driver import ExternalDriver, serve_driver  # noqa: F401
from .manager import PluginManager  # noqa: F401
