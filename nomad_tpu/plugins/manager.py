"""Plugin manager: discovery, launch, supervision, dispense
(reference: client/pluginmanager/drivermanager + devicemanager,
nomad/plugins catalog loading from the agent's plugin_dir).

Discovery: every executable file (or *.py file, launched with the current
interpreter) directly inside `plugin_dir` is treated as a plugin binary.
Each is launched and handshaken once at scan; its `plugin_info` decides
whether it dispenses as a task driver or a device plugin.  A supervisor
thread (started by `start_supervisor`, the client does this) rescans
periodically, relaunching crashed plugins — the reference's
drivermanager restarts plugin processes the same way.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional

from nomad_tpu.core.logging import log

from .base import PluginClient, PluginError, launch_plugin
from .device import ExternalDevicePlugin
from .driver import ExternalDriver


class PluginManager:
    def __init__(self, plugin_dir: str,
                 socket_dir: Optional[str] = None) -> None:
        self.plugin_dir = plugin_dir
        self.socket_dir = socket_dir or os.path.join(plugin_dir, ".sockets")
        self._lock = threading.Lock()
        self._cmds: Dict[str, List[str]] = {}      # path -> launch argv
        self._clients: Dict[str, PluginClient] = {}
        self.drivers: Dict[str, ExternalDriver] = {}
        self.devices: Dict[str, ExternalDevicePlugin] = {}
        self._group_plugin: Dict[str, str] = {}    # group id -> plugin name
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None

    def start_supervisor(self, interval: float = 10.0) -> None:
        """Relaunch crashed plugins periodically (reference:
        drivermanager's instance loop)."""
        if self._supervisor is not None:
            return

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.scan()
                except Exception as e:  # noqa: BLE001 - keep supervising
                    log("plugins", "error", "plugin rescan failed",
                        error=str(e))

        self._supervisor = threading.Thread(
            target=loop, daemon=True, name="plugin-supervisor")
        self._supervisor.start()

    # ------------------------------------------------------------ discovery

    def scan(self) -> None:
        """Discover + launch plugins (idempotent; relaunches dead ones,
        drops plugins whose files were removed, launches in parallel so
        one slow plugin doesn't serialize client startup)."""
        if not os.path.isdir(self.plugin_dir):
            return
        cmds: Dict[str, List[str]] = {}
        for entry in sorted(os.listdir(self.plugin_dir)):
            path = os.path.join(self.plugin_dir, entry)
            if not os.path.isfile(path):
                continue
            if entry.endswith(".py"):
                cmds[path] = [sys.executable, path]
            elif os.access(path, os.X_OK):
                cmds[path] = [path]
        to_launch = []
        with self._lock:
            # prune plugins whose files disappeared (drop their shims)
            for path in list(self._clients):
                if path not in cmds:
                    self._forget(path, self._clients[path])
            self._cmds = cmds
            for path, cmd in cmds.items():
                client = self._clients.get(path)
                if client is not None and client.alive():
                    continue
                if client is not None:
                    # keep the dispensed shim: _launch swaps its client
                    self._forget(path, client, drop_dispensed=False)
                to_launch.append((path, cmd))
        if not to_launch:
            return
        if len(to_launch) == 1:
            self._launch(*to_launch[0])
            return
        threads = [threading.Thread(target=self._launch, args=(p, c),
                                    daemon=True) for p, c in to_launch]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _launch(self, path: str, cmd: List[str]) -> None:
        client = None
        for attempt in (1, 2):      # cold interpreter starts can be slow
            try:
                client = launch_plugin(cmd, self.socket_dir)
                break
            except PluginError as e:
                log("plugins", "error", "plugin launch failed",
                    plugin=path, attempt=attempt, error=str(e))
        if client is None:
            return
        info = client.info
        with self._lock:
            self._register(path, client, info)

    def _register(self, path: str, client: PluginClient, info) -> None:
        self._clients[path] = client
        name = info.get("name", path)
        if info.get("type") == "driver":
            existing = self.drivers.get(name)
            if existing is not None:
                # relaunch: swap the connection IN PLACE so registries
                # holding this ExternalDriver keep working
                existing.client = client
            else:
                self.drivers[name] = ExternalDriver(client)
            log("plugins", "info", "external driver dispensed",
                name=name, plugin=path)
        elif info.get("type") == "device":
            existing = self.devices.get(name)
            if existing is not None:
                existing.client = client
            else:
                self.devices[name] = ExternalDevicePlugin(client)
            log("plugins", "info", "external device plugin dispensed",
                name=name, plugin=path)
        else:
            log("plugins", "warn", "unknown plugin type",
                plugin=path, type=info.get("type"))
            client.close()
            self._clients.pop(path, None)

    def _forget(self, path: str, client: PluginClient,
                drop_dispensed: bool = True) -> None:
        client.close()
        self._clients.pop(path, None)
        if not drop_dispensed:
            return
        name = client.info.get("name")
        if client.info.get("type") == "driver":
            self.drivers.pop(name, None)
        elif client.info.get("type") == "device":
            self.devices.pop(name, None)

    # ------------------------------------------------------------- queries

    def fingerprint_devices(self):
        """All device groups reported by live device plugins; records
        which plugin owns each group id for reserve() routing."""
        groups = []
        for p in list(self.devices.values()):
            try:
                mine = p.fingerprint()
            except Exception as e:  # noqa: BLE001 - a dead plugin is not fatal
                log("plugins", "warn", "device fingerprint failed",
                    plugin=p.name, error=str(e))
                continue
            for g in mine:
                self._group_plugin[g.id()] = p.name
            groups.extend(mine)
        return groups

    def reserve(self, allocated_devices, task_name: str = ""):
        """Map assigned device instances onto env vars via the owning
        device plugin's reserve() (reference: device_hook.go calling
        DevicePlugin.Reserve).  Returns merged env vars; plugin failures
        degrade to the generic NOMAD_DEVICE_* exposure."""
        envs: Dict[str, str] = {}
        for ad in allocated_devices or ():
            if task_name and ad.task and ad.task != task_name:
                continue
            pname = self._group_plugin.get(ad.group_id())
            plug = self.devices.get(pname) if pname else None
            if plug is None:
                continue
            try:
                r = plug.reserve(ad.device_ids) or {}
            except Exception as e:  # noqa: BLE001
                log("plugins", "warn", "device reserve failed",
                    plugin=pname, error=str(e))
                continue
            for k, v in (r.get("envs") or {}).items():
                envs[str(k)] = str(v)
        return envs

    def shutdown(self) -> None:
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=2)
        with self._lock:
            for path, client in list(self._clients.items()):
                self._forget(path, client)
