"""Task environment (reference: client/taskenv) — the NOMAD_* env vars and
${...} interpolation available to tasks and templates."""

from __future__ import annotations

import re
from typing import Dict

_VAR = re.compile(r"\$\{([^}]+)\}")


def build_task_env(alloc, task, node, task_dir: str = "",
                   secrets_dir: str = "") -> Dict[str, str]:
    """reference: taskenv.Builder.Build"""
    env = {
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(alloc.index()),
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_JOB_ID": alloc.job_id,
        "NOMAD_JOB_NAME": alloc.job.name if alloc.job else alloc.job_id,
        "NOMAD_NAMESPACE": alloc.namespace,
        "NOMAD_DC": node.datacenter if node else "",
        "NOMAD_REGION": "global",
        "NOMAD_CPU_LIMIT": str(task.resources.cpu),
        "NOMAD_MEMORY_LIMIT": str(task.resources.memory_mb),
    }
    if task_dir:
        env["NOMAD_TASK_DIR"] = task_dir
        env["NOMAD_ALLOC_DIR"] = task_dir
    if secrets_dir:
        env["NOMAD_SECRETS_DIR"] = secrets_dir
    for label, port in alloc.allocated_ports.items():
        env[f"NOMAD_PORT_{label}"] = str(port)
        env[f"NOMAD_HOST_PORT_{label}"] = str(port)
    # assigned device instances (reference: device_hook.go — drivers map
    # these onto isolation primitives; exec-class drivers get env vars)
    # key carries the full vendor/type/name id (nvidia/gpu vs amd/gpu must
    # not collide); two requests landing on the SAME group merge their ids
    dev_ids: Dict[str, list] = {}
    for ad in getattr(alloc, "allocated_devices", ()) or ():
        if ad.task and ad.task != task.name:
            continue
        key = "_".join(p for p in (ad.vendor, ad.type, ad.name) if p)
        key = key.upper().replace("-", "_").replace(".", "_")
        dev_ids.setdefault(key, []).extend(ad.device_ids)
    for key, ids in dev_ids.items():
        env[f"NOMAD_DEVICE_{key}"] = ",".join(ids)
    for k, v in (task.env or {}).items():
        env[k] = interpolate(v, env, node)
    return env


def interpolate(s: str, env: Dict[str, str], node=None) -> str:
    """${env.X} / ${attr.X} / ${meta.X} / ${node.X} interpolation
    (reference: taskenv ReplaceEnv)."""
    def repl(m):
        key = m.group(1)
        if node is not None:
            if key.startswith("attr."):
                return node.attributes.get(key[5:], "")
            if key.startswith("meta."):
                return node.meta.get(key[5:], "")
            if key == "node.datacenter":
                return node.datacenter
            if key == "node.class":
                return node.node_class
            if key == "node.unique.name":
                return node.name
            if key == "node.unique.id":
                return node.id
        return env.get(key, env.get(key.replace("env.", ""), m.group(0)))
    return _VAR.sub(repl, s)
