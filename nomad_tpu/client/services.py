"""Client-side service registration + health checks (reference:
client/serviceregistration/ + the checks runner in
client/serviceregistration/checks/ — the provider="nomad" native path).

When an alloc's tasks are all running, its group+task services register
with the server (one ServiceRegistration per service).  Each service's
checks run on their interval from the client; the aggregate pass/fail is
pushed to the registration AND feeds the alloc health hook when the update
stanza says `health_check = "checks"`.

Check types: `tcp` and `http` run real probes (stdlib); anything else
(script/grpc need an exec surface) reports passing after `interval`
elapses once, which keeps mock-driver test jobs deployable — the same
shortcut the reference's mock driver ecosystem leans on in tests.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
import urllib.parse
from typing import Dict, List, Optional

from nomad_tpu.structs import Allocation, Service, ServiceRegistration

STATUS_PASSING = "passing"
STATUS_CRITICAL = "critical"


def registration_id(alloc_id: str, owner: str, svc: str) -> str:
    return f"_nomad-task-{alloc_id}-{owner}-{svc}"


def _interp(label: str, alloc: Allocation) -> int:
    return alloc.allocated_ports.get(label, 0) if label else 0


def build_registrations(alloc: Allocation, node,
                        address: str = "127.0.0.1"
                        ) -> List[ServiceRegistration]:
    """Group + task services of a running alloc -> registrations."""
    job = alloc.job
    if job is None:
        return []
    tg = job.lookup_task_group(alloc.task_group)
    if tg is None:
        return []
    out: List[ServiceRegistration] = []

    def add(owner: str, svc: Service) -> None:
        if svc.provider != "nomad":
            # consul-provider services belong to an external registry the
            # reference integrates with; only provider="nomad" uses the
            # native discovery store
            return
        out.append(ServiceRegistration(
            id=registration_id(alloc.id, owner, svc.name),
            service_name=svc.name,
            namespace=alloc.namespace,
            node_id=alloc.node_id,
            job_id=alloc.job_id,
            alloc_id=alloc.id,
            datacenter=node.datacenter if node is not None else "",
            tags=list(svc.tags),
            address=address,
            port=_interp(svc.port_label, alloc),
            status="" if not svc.checks else STATUS_CRITICAL,
        ))

    for svc in tg.services:
        add(tg.name, svc)
    for task in tg.tasks:
        for svc in task.services:
            add(task.name, svc)
    return out


class CheckRunner:
    """Runs one service's checks on their interval in a daemon thread;
    reports aggregate status transitions through `on_status`."""

    def __init__(self, reg: ServiceRegistration, checks: List[Dict],
                 on_status) -> None:
        self.reg = reg
        self.checks = checks
        self.on_status = on_status
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.status = STATUS_CRITICAL if checks else ""
        self._started_at = time.time()

    def start(self) -> None:
        if not self.checks:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"checks-{self.reg.service_name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                status = (STATUS_PASSING
                          if all(self._one(c) for c in self.checks)
                          else STATUS_CRITICAL)
            except Exception:  # noqa: BLE001 - a probe bug must not kill
                status = STATUS_CRITICAL   # the runner thread
            # no transitions after stop(): a post-deregister status push
            # would resurrect the deleted registration server-side
            if status != self.status and not self._stop.is_set():
                self.status = status
                self.on_status(self.reg, status)
            interval = min((_seconds(c.get("interval"), 10.0)
                            for c in self.checks), default=10.0)
            self._stop.wait(max(interval, 0.5))

    def _one(self, check: Dict) -> bool:
        ctype = (check.get("type") or "").lower()
        timeout = _seconds(check.get("timeout"), 2.0)
        port = self.reg.port or int(check.get("port") or 0)
        if ctype == "tcp":
            try:
                with socket.create_connection(
                        (self.reg.address or "127.0.0.1", port),
                        timeout=timeout):
                    return True
            except OSError:
                return False
        if ctype == "http":
            path = check.get("path") or "/"
            try:
                conn = http.client.HTTPConnection(
                    self.reg.address or "127.0.0.1", port,
                    timeout=timeout)
                conn.request(check.get("method") or "GET",
                             urllib.parse.quote(path, safe="/?=&"))
                ok = 200 <= conn.getresponse().status < 300
                conn.close()
                return ok
            except (OSError, http.client.HTTPException):
                return False
        # script/grpc: no probe surface in-process — healthy once the
        # first interval has elapsed (keeps mock-driver jobs deployable)
        return (time.time() - self._started_at
                >= _seconds(check.get("interval"), 10.0))


def _seconds(v, default: float) -> float:
    if v is None:
        return default
    if isinstance(v, (int, float)):
        # Go time.Duration JSON is nanoseconds when large
        return v / 1e9 if v > 1e6 else float(v)
    s = str(v)
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000
        if s.endswith("s"):
            return float(s[:-1])
        return float(s)
    except ValueError:
        return default


class ServiceManager:
    """Per-client registry of the allocs' service registrations + their
    check runners; ships registrations/status through the RPC seam."""

    def __init__(self, rpc, node) -> None:
        self.rpc = rpc
        self.node = node
        self._runners: Dict[str, List[CheckRunner]] = {}
        self._lock = threading.Lock()

    def is_registered(self, alloc_id: str) -> bool:
        with self._lock:
            return alloc_id in self._runners

    def register_alloc(self, alloc: Allocation) -> None:
        """Idempotent; concurrent callers race on the claim, not on the
        runner threads."""
        with self._lock:
            if alloc.id in self._runners:
                return
            # claim the slot: even a service-less alloc gets an (empty)
            # entry so checks_healthy can distinguish "no checks" from
            # "registration hasn't happened yet"
            self._runners[alloc.id] = []
        regs = build_registrations(alloc, self.node)
        if not regs:
            return
        self.rpc.update_service_registrations(regs)
        runners: List[CheckRunner] = []
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        by_owner = {}
        if tg is not None:
            for svc in tg.services:
                by_owner[(tg.name, svc.name)] = svc
            for task in tg.tasks:
                for svc in task.services:
                    by_owner[(task.name, svc.name)] = svc
        for reg in regs:
            owner_svc = next((s for (o, n), s in by_owner.items()
                              if registration_id(alloc.id, o, n) == reg.id),
                             None)
            checks = owner_svc.checks if owner_svc else []
            if checks:
                r = CheckRunner(reg, checks, self._on_status)
                r.start()
                runners.append(r)
        with self._lock:
            if alloc.id in self._runners:
                self._runners[alloc.id] = runners
            else:
                # deregistered while we were starting: unwind
                for r in runners:
                    r.stop()

    def deregister_alloc(self, alloc_id: str) -> None:
        with self._lock:
            runners = self._runners.pop(alloc_id, [])
        for r in runners:
            r.stop()
        self.rpc.remove_service_registrations(alloc_id)

    def checks_healthy(self, alloc_id: str) -> bool:
        """True when every check-bearing service of the alloc passes —
        the `health_check = "checks"` input to the alloc health hook.
        An alloc whose registration hasn't happened yet reports False
        (its checks exist but have not run); a registered alloc with no
        checks reports True."""
        with self._lock:
            runners = self._runners.get(alloc_id)
        if runners is None:
            return False
        return all(r.status == STATUS_PASSING for r in runners)

    def _on_status(self, reg: ServiceRegistration, status: str) -> None:
        with self._lock:
            if reg.alloc_id not in self._runners:
                return     # deregistered: do not resurrect the row
        reg.status = status
        try:
            self.rpc.update_service_registrations([reg])
        except Exception:  # noqa: BLE001 - transient RPC failures retried
            pass           # on the next status transition

    def shutdown(self) -> None:
        with self._lock:
            allocs = list(self._runners)
        for aid in allocs:
            self.deregister_alloc(aid)
