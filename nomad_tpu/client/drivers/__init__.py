"""Task driver plugins (reference: drivers/ + plugins/drivers).

In-process plugin registry instead of go-plugin gRPC subprocesses: every
driver implements the `Driver` interface (the DriverPlugin contract —
fingerprint / start_task / wait_task / stop_task / destroy_task /
inspect_task / signal_task / exec_task).
"""

from .base import Driver, DriverCapabilities, TaskHandle, TaskResult
from .docker import DockerDriver
from .execdriver import ExecDriver
from .java import JavaDriver
from .mock import MockDriver
from .qemu import QemuDriver
from .rawexec import RawExecDriver

BUILTIN_DRIVERS = {
    "mock": MockDriver,
    "raw_exec": RawExecDriver,
    "exec": ExecDriver,
    "docker": DockerDriver,
    "java": JavaDriver,
    "qemu": QemuDriver,
}


def new_driver_registry(names=None):
    """Instantiate the builtin drivers (reference:
    client/pluginmanager/drivermanager Dispense)."""
    out = {}
    for name, cls in BUILTIN_DRIVERS.items():
        if names is None or name in names:
            out[name] = cls()
    return out
